"""FleetRouter: N ServingEngine replicas behind one prefix-aware
front-door (round 11 — ROADMAP open item 4, the "heavy traffic"
scenario where the single-host engine stops being the unit of
deployment).

Two previously-separate halves join here:

- the **serving** stack (PRs 2-4) gives every replica a full SLO
  surface — ``submit``/``step``/``status``/``cancel``/``healthz``,
  deadlines, shedding, prefix caching — plus the new ``drain()`` toggle;
- the **master** stack contributes its etcd-analog lease machinery:
  :class:`~paddle_tpu.master.service.LeaseTable` gives each replica a
  (slot, token) TTL lease, so liveness is decided by heartbeats on the
  injected clock and a zombie replica whose slot was reclaimed can
  never ack again (token mismatch — the exact semantics
  ``Service.heartbeat`` pins for trainers).

Routing is by **chained prompt-block hash** — literally the
:class:`~paddle_tpu.serving.kv_cache.PrefixCache` key function
(:func:`~paddle_tpu.serving.kv_cache.prefix_chain_hashes`) — so two
prompts that would share cached pages inside an engine also share a
routing key across the fleet, and shared-prefix traffic lands where its
pages already live.  The router remembers which replica owns each chain
key (updated at every successful dispatch, dropped on replica death);
healthz-driven load balancing (``queue_depth`` / ``free_pages``) is the
tiebreak for unkeyed traffic and the overflow path when the prefix
owner is saturated.  ``routing="round_robin"`` keeps the naive policy
alive as the bench's A/B control.

Replica lifecycle::

    JOINING ──(lease alive + healthz ok)──▶ READY
      READY ──drain_replica()──▶ DRAINING ──(engine empty)──▶ DEAD
      READY/DRAINING ──(kill fault | lease expiry)──▶ DEAD

DEAD is terminal and fenced: the lease is dropped (token can never ack
again), the replica's chain-key ownership is forgotten, its engine-side
in-flight work is cancelled (pages return to its pool), and every
not-yet-terminal fleet request it carried is **resubmitted** to a
survivor through the normal dispatch path — deadlines carry over as
absolute times, resubmits are budgeted (``serving_fleet_resubmit_budget``)
and then FAILED, and the rid map is severed BEFORE resubmission so one
fleet rid can never complete twice (``duplicate_completions`` is a
counter precisely so the conservation check can assert it stayed 0).

Token streams are exactly-once: the router wraps ``on_token`` with a
high-water mark per fleet request, so a greedy request replayed on a
survivor after a kill re-emits only the tokens the user has not seen
yet (greedy decoding is deterministic, so the replay prefix matches).

``check_fleet_conservation()`` extends the engine's PAGE/REF-LEAK
contract to the fleet: after a drain, every submitted fleet rid reached
EXACTLY one terminal status, no rid completed twice, and every
replica's pool — dead ones included — holds zero live refs.  Violations
raise :class:`~paddle_tpu.serving.faults.PageLeakError` tagged
``FLEET-LEAK`` (tools_tier1.sh exit 6), and ``python -m
paddle_tpu.serving.fleet check`` replays a seeded kill-chaos trace as a
standalone gate.
"""

from __future__ import annotations

import itertools
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from enum import Enum
from typing import (Callable, Deque, Dict, List, Optional, Sequence, Set,
                    Tuple)

from paddle_tpu.analysis.concurrency.lifecycle import record_transition
from paddle_tpu.master.service import LeaseTable
from paddle_tpu.obs.registry import MetricsRegistry
from paddle_tpu.obs.trace import NULL_TRACER, tracer_for
from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving.control import (AdmissionLedger, Autoscaler,
                                        AutoscalePolicy, TenantRegistry,
                                        WeightedFairQueue)
from paddle_tpu.serving.engine import ServingEngine
from paddle_tpu.serving.faults import FleetFaultPlan, PageLeakError
from paddle_tpu.serving.kv_cache import prefix_chain_hashes
from paddle_tpu.serving.metrics import FleetMetrics
from paddle_tpu.serving.migrate import (export_chain, export_prefix,
                                        import_chain, import_prefix)
from paddle_tpu.serving.scheduler import RequestStatus

__all__ = ["FleetRouter", "Replica", "ReplicaState"]

_frid_counter = itertools.count()


class ReplicaState(str, Enum):
    """Replica lifecycle (str-valued like RequestStatus, so comparisons
    against the literal strings work)."""

    JOINING = "joining"      # registered, not yet admitted to routing
    READY = "ready"          # lease live, healthz ok — routable
    DRAINING = "draining"    # admission closed, running work finishing
    DEAD = "dead"            # fenced: lease dropped, never routable again

    def __str__(self) -> str:
        return self.value


@dataclass
class _FleetRequest:
    """One fleet-level request: the fleet rid is the caller's handle;
    the (replica, erid) binding below it changes across resubmits but
    at most ONE binding is live at a time."""

    frid: int
    prompt: List[int]
    max_tokens: int
    on_token: Optional[Callable[[int], None]] = None
    deadline_at: Optional[float] = None   # absolute, carries over resubmits
    status: RequestStatus = RequestStatus.QUEUED
    replica: Optional[int] = None         # current replica index
    erid: Optional[int] = None            # current engine rid
    resubmits: int = 0
    emitted: int = 0                      # exactly-once stream high-water
    attempt_tokens: int = 0               # tokens seen in CURRENT attempt
    result: Optional[List[int]] = None
    submitted_at: Optional[float] = None
    finished_at: Optional[float] = None
    terminal_transitions: int = 0         # conservation: must end at 1
    tenant: str = "default"               # billing identity; survives
    #                                       resubmits and migrations

    @property
    def finished(self) -> bool:
        return self.status.terminal


class Replica:
    """One engine plus its fleet-side bookkeeping."""

    def __init__(self, idx: int, engine: ServingEngine,
                 role: str = "unified"):
        self.idx = idx
        self.engine = engine
        self.role = role                      # prefill | decode | unified
        self.state = ReplicaState.JOINING
        self.slot: Optional[int] = None       # LeaseTable slot
        self.token: Optional[str] = None      # lease token (zombie fence)
        self.last_hb: Optional[float] = None
        self.rid_map: Dict[int, int] = {}     # engine rid -> fleet rid
        self.dead_reason: Optional[str] = None

    def load_key(self) -> Tuple[int, int, int]:
        """Balancing key: fewer queued+running first, more free pages as
        the tiebreak, index for determinism.  Reads the engine's O(1)
        ``load()`` probe, not ``healthz()`` — routing runs this per
        candidate replica per submit, and healthz pays a full
        conservation scan for its ``ok`` bit."""
        ld = self.engine.load()
        return (ld["queue_depth"] + ld["running"], -ld["free_pages"],
                self.idx)

    def prefill_key(self) -> Tuple[int, int, int, int]:
        """Balancing key for PROMPT dispatch in a disaggregated fleet:
        lead with the O(1) ``prefill_backlog_tokens`` probe (the tokens
        actually ahead of a new prompt), then the classic load key —
        queue depth alone undercounts a replica chewing a 2k-token
        prefill."""
        ld = self.engine.load()
        return (ld["prefill_backlog_tokens"],
                ld["queue_depth"] + ld["running"], -ld["free_pages"],
                self.idx)


@dataclass
class _Transfer:
    """One pending page transfer, queued per DESTINATION and admitted
    against its per-tick page credit (``serving_migrate_budget``) —
    charged to the destination like chunked prefill, never blocking its
    decode tick.  ``kind="chain"`` hands a live request off;
    ``kind="seed"`` warms a peer's PrefixCache."""

    kind: str                          # "chain" | "seed"
    src: int                           # source replica index
    dest: int                          # destination replica index
    seq: int                           # fleet-wide migration sequence no.
    frid: Optional[int] = None         # chain: the fleet rid moving
    erid: Optional[int] = None         # chain: source engine rid at enqueue
    tokens: Optional[List[int]] = None  # seed: the prompt to warm
    pages: int = 0                     # admission estimate (re-read at apply)


class FleetRouter:
    """Prefix-affinity router over N ServingEngine replicas on ONE
    injected clock (see module doc).

    ``make_engine(idx, time_fn)`` must build each replica's engine with
    ``time_fn=time_fn`` (and no per-engine fault clock), so the whole
    fleet shares the router's clock — the same determinism contract the
    single-engine fault plans use.
    """

    def __init__(self, make_engine: Callable[[int, Callable[[], float]],
                                             ServingEngine],
                 num_replicas: Optional[int] = None, *,
                 heartbeat_s: Optional[float] = None,
                 resubmit_budget: Optional[int] = None,
                 routing: str = "affinity",
                 overflow_queue_depth: Optional[int] = None,
                 max_retained: int = 10000,
                 max_owner_keys: int = 16384,
                 faults: Optional[FleetFaultPlan] = None,
                 time_fn: Optional[Callable[[], float]] = None,
                 tracer=None,
                 registry: Optional[MetricsRegistry] = None,
                 roles: Optional[Sequence[str]] = None,
                 migrate_budget: Optional[int] = None,
                 tenants: Optional[TenantRegistry] = None,
                 wfq: Optional[bool] = None,
                 autoscale=None):
        enforce_that(routing in ("affinity", "round_robin"),
                     f"unknown routing policy {routing!r}",
                     context="serving")
        if num_replicas is None:
            num_replicas = int(FLAGS.serving_fleet_replicas)
        if heartbeat_s is None:
            heartbeat_s = float(FLAGS.serving_fleet_heartbeat_s)
        if resubmit_budget is None:
            resubmit_budget = int(FLAGS.serving_fleet_resubmit_budget)
        # disaggregation (round 16): per-replica roles; a shorter list
        # pads with "unified", empty = the classic every-replica-unified
        # fleet with every migration path dormant
        if roles is None:
            raw = str(FLAGS.serving_fleet_roles).strip()
            roles = [s.strip() for s in raw.split(",")
                     if s.strip()] if raw else []
        self._roles: List[str] = [str(r) for r in roles]
        for r in self._roles:
            enforce_that(r in ("prefill", "decode", "unified"),
                         f"unknown replica role {r!r}", context="serving")
        if migrate_budget is None:
            migrate_budget = int(FLAGS.serving_migrate_budget)
        self.migrate_budget = max(0, int(migrate_budget))
        self._disagg = any(r != "unified" for r in self._roles)
        enforce_that(num_replicas >= 1, "fleet needs >= 1 replica",
                     context="serving")
        self._make_engine = make_engine
        self.routing = routing
        self.heartbeat_s = float(heartbeat_s)
        # 3x heartbeat, the master's lease_ttl_s : timeout_s ratio — two
        # missed heartbeats survive, the third is death
        self.lease_ttl_s = 3.0 * self.heartbeat_s
        self.resubmit_budget = max(0, int(resubmit_budget))
        self.overflow_queue_depth = overflow_queue_depth
        self.max_retained = max(1, int(max_retained))
        self.max_owner_keys = max(1, int(max_owner_keys))
        self.faults = faults
        if faults is not None and faults.clock is not None:
            self._time = faults.clock
        else:
            self._time = time_fn or time.monotonic
        # obs: ONE tracer and ONE registry for the whole fleet (replica
        # engines get the tracer scoped to their index and the registry
        # labeled with it), so a chaos replay yields one timeline and
        # one scrape surface instead of N disjoint ones
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.tracer = tracer if tracer is not None \
            else tracer_for(self._time, registry=self.registry)
        if self.tracer.enabled and self.tracer.registry is None:
            self.tracer.registry = self.registry
        self._postmortems_dumped: Set[str] = set()
        self._lease = LeaseTable(self.lease_ttl_s, time_fn=self._time,
                                 tracer=self.tracer if self.tracer.enabled
                                 else None)
        self.metrics = FleetMetrics()
        self.replicas: List[Replica] = []
        self._requests: Dict[int, _FleetRequest] = {}
        self._live: Set[int] = set()          # non-terminal fleet rids
        self._retired: Deque[int] = deque()   # terminal rids, oldest first
        # chain hash -> owning replica, LRU-bounded at max_owner_keys:
        # like every other long-lived structure here (max_retained
        # history, the engines' LRU caches) it must not grow per unique
        # prompt forever.  Eviction only degrades affinity to a load-
        # balanced pick — correctness never depends on this map.
        self._prefix_owner: "OrderedDict[int, int]" = OrderedDict()
        self._rr_next = 0
        self._tick = 0
        # page-migration plane (round 16): pending transfers FIFO per
        # destination, admitted against a per-destination page credit of
        # ``migrate_budget`` pages per fleet tick; chain transfers are
        # also indexed by fleet rid so a terminal transition anywhere
        # (completion, death resubmit) aborts the in-flight handoff
        # instead of leaving it pending forever
        self._mig_queues: Dict[int, Deque[_Transfer]] = {}
        self._mig_pending: Dict[int, _Transfer] = {}   # frid -> transfer
        self._mig_credit: Dict[int, int] = {}
        self._mig_seq = 0
        # control plane (round 17): tenant SLO classes, weighted fair
        # queuing ahead of dispatch, and the autoscaler policy loop.
        # All three default off via flags, so the classic fleet is
        # byte-identical; the admission ledger ALWAYS runs (it is free
        # and the CONTROL-LEAK gate asserts it even with WFQ off).
        if tenants is None:
            raw = str(FLAGS.serving_tenant_classes).strip()
            tenants = TenantRegistry.from_flag(raw) if raw else None
        self.tenants = tenants
        if wfq is None:
            wfq = bool(FLAGS.serving_wfq)
        self.wfq = WeightedFairQueue() if wfq else None
        self.ledger = AdmissionLedger()
        if autoscale is None:
            autoscale = bool(FLAGS.serving_autoscale)
        if autoscale is True:
            autoscale = AutoscalePolicy(
                cooldown_ticks=int(FLAGS.serving_autoscale_cooldown))
        self.autoscaler = Autoscaler(self, autoscale) \
            if isinstance(autoscale, AutoscalePolicy) else None
        for _ in range(num_replicas):
            self.add_replica()
        # initial replicas come up READY before the first submit (their
        # leases are fresh); replicas added later go through an
        # observable JOINING tick first
        self._promote_joining()

    @classmethod
    def over_mesh_slices(cls, make_engine, tp: int = 1,
                         axis: str = "model", devices=None,
                         num_replicas: Optional[int] = None, **kwargs
                         ) -> "FleetRouter":
        """Build a fleet whose replica unit is a MESH SLICE, not a chip:
        the device set is partitioned into ``tp``-chip slices
        (:func:`~paddle_tpu.parallel.mesh.mesh_slices`) and
        ``make_engine(idx, time_fn, mesh)`` must return a
        ``ServingEngine(mesh=mesh, ...)`` on its slice (``mesh`` is
        None when ``tp == 1`` — plain replicated replicas).  Everything
        else — prefix-affinity routing, leases, death fencing,
        resubmission — is unchanged: a slice dies and rejoins as one
        unit, which is exactly what a multi-chip model replica is.
        ``num_replicas`` caps the slice count (default: every full
        slice the devices afford)."""
        if tp <= 1:
            slices = None
            n = num_replicas
        else:
            from paddle_tpu.parallel.mesh import mesh_slices

            slices = mesh_slices(tp, axis=axis, devices=devices,
                                 max_slices=num_replicas)
            n = len(slices)

        def mk(i: int, time_fn):
            return make_engine(i, time_fn,
                               slices[i] if slices is not None else None)

        return cls(mk, n, **kwargs)

    # ---- replica lifecycle ------------------------------------------------

    def add_replica(self, role: Optional[str] = None) -> int:
        """Elastic join: build an engine on the shared clock, claim a
        lease, enter JOINING.  Promoted to READY by the next tick's
        sweep once the lease is live and healthz reports ok.

        ``role`` pins the new replica's class explicitly (the
        autoscaler joins where the pressure is); None keeps the
        classic resolution — the fleet's roles list, then the engine's
        own role, then "unified"."""
        idx = len(self.replicas)
        engine = self._make_engine(idx, self._time)
        if role is None:
            # role: the fleet's roles list wins (padding with
            # "unified"); an engine built with its own role keeps it
            # when the list is silent about this index
            role = self._roles[idx] if idx < len(self._roles) \
                else getattr(engine, "role", "unified")
        else:
            enforce_that(role in ("prefill", "decode", "unified"),
                         f"unknown replica role {role!r}",
                         context="serving")
            # record the explicit role so _disagg and later joins see a
            # consistent picture
            while len(self._roles) < idx:
                self._roles.append("unified")
            if len(self._roles) == idx:
                self._roles.append(role)
            else:
                self._roles[idx] = role
            self._disagg = any(r != "unified" for r in self._roles)
        engine.role = role
        if self.tenants is not None:
            # preemption precedence: batch-class slots are victimized
            # before interactive ones, on EVERY replica incl. late joins
            engine.scheduler.precedence_fn = self.tenants.precedence
        rep = Replica(idx, engine, role=role)
        # one fleet-wide tracer/registry: the engine's instrumentation
        # points report under this replica's identity
        rep.engine.set_tracer(self.tracer.scoped(replica=idx))
        rep.engine.set_registry(self.registry, replica=idx)
        rep.slot, rep.token = self._lease.register(self.lease_ttl_s)
        rep.last_hb = self._time()
        self.replicas.append(rep)
        self.metrics.replicas_joined += 1
        self.tracer.instant("replica_join", cat="fleet", replica=idx)
        return idx

    def drain_replica(self, idx: int) -> None:
        """Begin a clean retirement: admission closes now (both at the
        router — no longer routable — and at the engine, whose own
        ``submit`` REJECTs), running and queued work finishes, and the
        replica retires to DEAD once its engine is empty."""
        rep = self.replicas[idx]
        enforce_that(rep.state in (ReplicaState.READY, ReplicaState.JOINING),
                     f"cannot drain replica in state {rep.state}",
                     context="serving")
        if self._disagg and rep.role in ("prefill", "unified"):
            # PINNED behavior (round 17): draining the LAST
            # prefill-capable replica of a disaggregated fleet is
            # REFUSED loudly rather than silently stranding every
            # future prompt — the autoscaler filters its drain
            # candidates on exactly this predicate, so the policy loop
            # can never trip it
            others = [o for o in self.replicas
                      if o.idx != idx and
                      o.state in (ReplicaState.READY,
                                  ReplicaState.JOINING) and
                      o.role in ("prefill", "unified")]
            enforce_that(bool(others),
                         f"refusing to drain replica {idx}: it is the "
                         "last prefill-capable replica of a "
                         "disaggregated fleet (prompts would have "
                         "nowhere to prefill)", context="serving")
        record_transition("replica_lifecycle", str(rep.state), "draining",
                          registry=self.registry)
        rep.state = ReplicaState.DRAINING
        rep.engine.drain()
        self._forget_owner(idx)
        self.tracer.instant("replica_drain", cat="fleet", replica=idx)

    def kill_replica(self, idx: int,
                     reason: str = "killed by operator") -> None:
        """Immediately fence a replica (operator kill, or an external
        failure detector ahead of the lease timeout): DEAD, lease
        dropped, chain-key ownership forgotten, in-flight work
        resubmitted to survivors.  Same path the injected kill fault
        takes."""
        self._mark_dead(self.replicas[idx], self._time(), reason)

    def restart_replica(self, idx: int) -> int:
        """Crash-WARM restart (round 21): rebuild a DEAD replica as a
        fresh engine that re-adopts its predecessor's host-RAM spill
        tier instead of starting cold.  Crash semantics are honored —
        device (HBM) pages died with the engine and are NOT salvaged;
        only pages the old engine had already spilled to host memory
        survive, and every one of them is checksum-verified during
        adoption (a corrupt page counts ``HOSTTIER-CORRUPT`` and is
        dropped, never served).  The successor is a NEW replica index
        going through the normal JOINING -> READY lifecycle, so the
        lease/fence/resubmit machinery is untouched: the dead replica's
        in-flight work was already resubmitted at fence time, and the
        exactly-once stream fence makes any replay invisible.  Returns
        the successor's index."""
        rep = self.replicas[idx]
        enforce_that(rep.state is ReplicaState.DEAD,
                     f"cannot warm-restart replica in state {rep.state} "
                     "(kill or drain it first)", context="serving")
        old_tier = rep.engine.host_tier
        # the successor re-enters through JOINING: record the warm
        # restart as the dead replica's declared dead -> joining edge
        record_transition("replica_lifecycle", "dead", "joining",
                          registry=self.registry)
        new_idx = self.add_replica(role=rep.role)
        new_rep = self.replicas[new_idx]
        restored = 0
        if old_tier is not None and new_rep.engine.host_tier is not None:
            tier = new_rep.engine.host_tier
            before = tier.restored
            tier.adopt(old_tier)
            restored = tier.restored - before
        self.metrics.on_warm_restart(restored)
        self.tracer.instant("replica_warm_restart", cat="fleet",
                            replica=idx, successor=new_idx,
                            pages_restored=restored)
        return new_idx

    def replica_state(self, idx: int) -> ReplicaState:
        return self.replicas[idx].state

    def _promote_joining(self) -> None:
        for rep in self.replicas:
            if rep.state is not ReplicaState.JOINING:
                continue
            if self._lease.alive(rep.slot, rep.token) and \
                    rep.engine.healthz()["ok"]:
                record_transition("replica_lifecycle", "joining", "ready",
                                  registry=self.registry)
                rep.state = ReplicaState.READY
                self.tracer.instant("replica_ready", cat="fleet",
                                    replica=rep.idx)

    def _lease_sweep(self, tick: int, now: float) -> None:
        """Renew every live replica's lease (unless partitioned), then
        declare any replica whose lease lapsed DEAD.  Renewal is a
        cheap host op, so it runs EVERY sweep rather than being paced
        by ``heartbeat_s`` — pacing would turn any engine tick slower
        than the TTL minus the pace (a first-compile spike on a real
        clock) into a mass false-positive death of the whole fleet.
        ``heartbeat_s`` is the TTL knob: a partitioned replica stops
        renewing, its lease expires after ``3 * heartbeat_s``, and when
        the partition heals its stale token can never ack — the zombie
        fence, end-to-end.  On a wall clock, size ``heartbeat_s`` above
        the worst-case single tick (compile spikes), since a tick
        longer than the whole TTL still lapses mid-tick.

        Deaths are collected, then ALL fenced, then reaped: a
        correlated failure (one partition taking out several replicas
        crosses the TTL on the same sweep) must not burn a request's
        bounded resubmit budget dispatching it to a replica this same
        sweep is about to declare dead."""
        lapsed: List[Tuple[Replica, str]] = []
        for rep in self.replicas:
            if rep.state is ReplicaState.DEAD:
                continue
            blocked = (self.faults is not None and
                       self.faults.heartbeat_blocked(rep.idx, tick))
            if not blocked:
                if self._lease.heartbeat(rep.slot, rep.token,
                                         self.lease_ttl_s):
                    rep.last_hb = now
                else:
                    lapsed.append((rep, "lease lost (zombie ack "
                                        "rejected)"))
                    continue
            if not self._lease.alive(rep.slot, rep.token):
                lapsed.append((rep, "lease expired"))
        for rep, reason in lapsed:
            self._fence(rep, now, reason)
        for rep, _ in lapsed:
            self._reap(rep, now)
        self._promote_joining()

    def _forget_owner(self, idx: int) -> None:
        self._prefix_owner = OrderedDict(
            (h, i) for h, i in self._prefix_owner.items() if i != idx)

    def _record_owner(self, hashes: List[int], idx: int) -> None:
        owner = self._prefix_owner
        for h in hashes:
            owner[h] = idx
            owner.move_to_end(h)
        while len(owner) > self.max_owner_keys:
            owner.popitem(last=False)

    def _mark_dead(self, rep: Replica, now: float, reason: str) -> None:
        """Fence a replica and resubmit its in-flight work (see module
        doc for the ordering that makes this idempotent).  Callers with
        SEVERAL deaths to declare at once fence them all first and only
        then reap (see _lease_sweep) — this one-replica path is for
        isolated deaths (operator kill)."""
        if rep.state is ReplicaState.DEAD:
            return
        self._fence(rep, now, reason)
        self._reap(rep, now)

    def _fence(self, rep: Replica, now: float, reason: str) -> None:
        """DEAD, lease dropped, chain ownership forgotten: from this
        line on the replica is unroutable and its zombie token can
        never ack.  Resubmission of its work is _reap's job."""
        record_transition("replica_lifecycle", str(rep.state), "dead",
                          registry=self.registry)
        rep.state = ReplicaState.DEAD
        rep.dead_reason = reason
        self.metrics.replicas_dead += 1
        self._lease.drop(rep.slot, rep.token)
        self._forget_owner(rep.idx)
        self.tracer.instant("replica_fence", cat="fleet", replica=rep.idx,
                            reason=reason)

    def _reap(self, rep: Replica, now: float) -> None:
        """Resubmit a fenced replica's unfinished work to survivors.

        Completions that landed BEFORE death are real — harvest them
        first so only genuinely unfinished work resubmits."""
        self._harvest(rep, now)
        pending = list(rep.rid_map.items())
        self.tracer.instant("replica_reap", cat="fleet", replica=rep.idx,
                            in_flight=len(pending))
        # sever the map BEFORE resubmitting: from this line on, nothing
        # this replica's engine does can reach a fleet request again
        rep.rid_map.clear()
        for erid, frid in pending:
            freq = self._requests[frid]
            # tear down the dead engine's copy so its pages return (the
            # process still owns the pool even though the fleet fenced
            # the replica) and the fleet-wide conservation check stays
            # provable over ALL replicas
            if not rep.engine.status(erid).terminal:
                rep.engine.cancel(erid, now=now)
            if freq.finished:
                continue
            freq.replica = None
            freq.erid = None
            self._resubmit(freq, now)

    def _retire_replica(self, rep: Replica, now: float) -> None:
        """Clean end of a drain: engine empty, lease handed back."""
        self._lease.drop(rep.slot, rep.token)
        record_transition("replica_lifecycle", str(rep.state), "dead",
                          registry=self.registry)
        rep.state = ReplicaState.DEAD
        rep.dead_reason = "drained"
        self.metrics.replicas_drained += 1
        self._forget_owner(rep.idx)
        self.tracer.instant("replica_drained", cat="fleet",
                            replica=rep.idx)

    # ---- routing ----------------------------------------------------------

    def _ready(self, exclude: Set[int]) -> List[Replica]:
        return [r for r in self.replicas
                if r.state is ReplicaState.READY and r.idx not in exclude]

    def _page_size(self) -> int:
        return self.replicas[0].engine.kv_cfg.page_size

    def _route(self, prompt: Sequence[int],
               exclude: Set[int]) -> Tuple[Optional[int], List[int], bool,
                                           Optional[int]]:
        """Pick a READY replica for ``prompt``.  Returns (replica index
        or None, the prompt's chain hashes — empty under round_robin,
        which never reads them, routed-by-affinity?, seed-from replica
        or None).

        Disaggregated fleets restrict PROMPT dispatch to prefill-class
        replicas (prefill/unified), balanced by their
        ``prefill_backlog_tokens`` probe.  The affinity owner map is
        keyed by the union of classes — a chain migrated to a decode
        replica records it as owner — so when the deepest owner cannot
        (or should not) take the prompt itself, the pick falls to the
        least-backlogged prefill replica and the owner comes back as
        ``seed_from``: the dispatcher warms the target's cache from the
        owner via the page-migration plane instead of re-prefilling."""
        ready = self._ready(exclude)
        if not ready:
            return None, [], False, None
        if self.routing == "round_robin":
            while True:   # `ready` is non-empty, so the cycle terminates
                idx = self._rr_next % len(self.replicas)
                self._rr_next += 1
                rep = self.replicas[idx]
                if rep.state is ReplicaState.READY and idx not in exclude:
                    return idx, [], False, None
        if self._disagg:
            eligible = [r for r in ready
                        if r.role in ("prefill", "unified")] or ready
            balance_key = Replica.prefill_key
        else:
            eligible = ready
            balance_key = Replica.load_key
        eligible_idx = {r.idx for r in eligible}
        hashes = prefix_chain_hashes(prompt, self._page_size())
        # affinity: the DEEPEST chain link with a known live owner wins
        # (deeper link = longer shared prefix already materialized there)
        affinity = None
        for h in hashes:
            owner = self._prefix_owner.get(h)
            if owner is not None and owner not in exclude and \
                    self.replicas[owner].state is ReplicaState.READY:
                affinity = owner
        seed_from = None
        if affinity is not None:
            rep = self.replicas[affinity]
            if affinity in eligible_idx:
                limit = self.overflow_queue_depth
                if limit is None:
                    # default: tolerate a queue as deep as two full decode
                    # batches before overflowing to the least-loaded
                    # replica
                    limit = 2 * rep.engine._max_slots
                if rep.engine.load()["queue_depth"] < limit:
                    return affinity, hashes, True, None
            # the owner holds the prefix but is not taking the prompt
            # (wrong class, or saturated): seed the eventual target
            seed_from = affinity
        best = min(eligible, key=balance_key)
        if seed_from == best.idx:
            seed_from = None
        return best.idx, hashes, False, seed_from

    # ---- user surface ------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_tokens: int,
               on_token: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               now: Optional[float] = None,
               tenant: str = "default") -> int:
        """Route a request into the fleet; returns its fleet rid ALWAYS
        (a refused request carries status REJECTED, mirroring the
        engine's contract).  ``deadline_s`` becomes an absolute deadline
        on the shared clock and carries over death-resubmits — a request
        does not get a fresh budget because its replica died.

        ``tenant`` is the billing identity (round 17).  With a tenant
        registry configured: a submit without its own ``deadline_s``
        inherits the tenant's SLO-class deadline, and the tenant's
        token bucket meters admission (an over-quota submit is REJECTED
        up front and ledgered as quota_deferred).  With WFQ on, the
        request buffers in the per-tenant virtual-time queue and is
        released to dispatch at its weighted share on the next tick."""
        now = self._time() if now is None else now
        tenant = str(tenant)
        freq = _FleetRequest(frid=next(_frid_counter),
                             prompt=[int(t) for t in prompt],
                             max_tokens=int(max_tokens), on_token=on_token,
                             tenant=tenant)
        freq.submitted_at = now
        if deadline_s is None and self.tenants is not None:
            deadline_s = self.tenants.deadline_s(tenant)
        if deadline_s is not None:
            freq.deadline_at = now + float(deadline_s)
        self._requests[freq.frid] = freq
        self._live.add(freq.frid)
        self.metrics.on_submit(now)
        self.ledger.on_submit(tenant)
        # THE root span: one async begin per fleet rid, ended by the
        # request's single terminal transition in _finish — the
        # exactly-once invariant drawn as exactly one bar per rid
        self.tracer.async_begin("fleet_request", id=freq.frid,
                                id_space="frid", tokens=len(freq.prompt),
                                max_tokens=freq.max_tokens)
        if self.tenants is not None and not self.tenants.admit_quota(
                tenant, len(freq.prompt) + freq.max_tokens, now):
            # token-bucket refusal: worst-case token cost (prompt +
            # max_tokens), terminal REJECTED — the caller retries after
            # the bucket refills, the fleet never buffers over-quota work
            self.ledger.on_quota_deferred(tenant)
            self.tracer.instant("quota_defer", cat="fleet",
                                frid=freq.frid, tenant=tenant)
            self._finish(freq, RequestStatus.REJECTED, now)
            return freq.frid
        if self.wfq is not None:
            weight = self.tenants.weight(tenant) \
                if self.tenants is not None else 1.0
            self.wfq.push(tenant, len(freq.prompt), weight, freq)
            self.tracer.instant("wfq_enqueue", cat="fleet",
                                frid=freq.frid, tenant=tenant)
            return freq.frid
        self.ledger.on_admit(tenant)
        self._dispatch(freq, now)
        return freq.frid

    def status(self, frid: int) -> RequestStatus:
        """Fleet-level lifecycle status; raises KeyError for a rid this
        fleet never issued (or evicted past ``max_retained``)."""
        return self._requests[frid].status

    def result(self, frid: int) -> Optional[List[int]]:
        """Generated tokens for a COMPLETED fleet rid (None while in
        flight or for non-completed terminals); KeyError for unknown."""
        return self._requests[frid].result

    def cancel(self, frid: int, now: Optional[float] = None) -> bool:
        """Cancel a fleet request wherever it currently lives."""
        freq = self._requests[frid]
        if freq.finished:
            return False
        now = self._time() if now is None else now
        if self.wfq is not None and self.wfq.remove(freq) is not None:
            # cancelled while still buffered ahead of dispatch: it left
            # the WFQ without being admitted — ledger it as shed so the
            # per-tenant partition stays balanced
            self.ledger.on_shed(freq.tenant)
        if freq.replica is not None:
            rep = self.replicas[freq.replica]
            rep.rid_map.pop(freq.erid, None)
            if not rep.engine.status(freq.erid).terminal:
                rep.engine.cancel(freq.erid, now=now)
        self._finish(freq, RequestStatus.CANCELLED, now)
        return True

    @property
    def has_work(self) -> bool:
        return bool(self._live)

    def step(self) -> bool:
        """One fleet tick: advance the shared clock, apply fleet faults
        (kills), sweep leases (partition -> expiry -> DEAD -> resubmit),
        step every live replica (slow replicas skip their off ticks),
        harvest terminal engine statuses into fleet statuses, retire
        drained replicas.  Returns True while fleet work remains."""
        tick = self._tick
        if self.faults is not None:
            self.faults.tick_begin(tick)
        now = self._time()
        if self.faults is not None:
            ready_idx = [r.idx for r in self.replicas
                         if r.state is ReplicaState.READY]
            # fence every killed replica before reaping any (same
            # correlated-death ordering as _lease_sweep)
            doomed = []
            for idx in self.faults.kills(tick, ready_idx):
                if 0 <= idx < len(self.replicas):
                    rep = self.replicas[idx]
                    if rep.state is not ReplicaState.DEAD:
                        self._fence(rep, now, f"injected kill @ tick {tick}")
                        doomed.append(rep)
            for rep in doomed:
                self._reap(rep, now)
        # the permutable mid-tick section.  Canonical order: lease sweep
        # (membership is current for everything after), autoscaler
        # (may join/drain replicas), WFQ drain (releases this tick's
        # weighted-fair share into dispatch), migration pump (a chain
        # or seed that clears its destination's per-tick credit lands
        # ahead of that destination's admission/decode this tick).
        # These four phases are CLAIMED commutable w.r.t. terminal
        # outcomes — the SCHED-AUDIT explorer replays chaos drives
        # under every permutation the hook asks for and holds the
        # fleet to that claim; the kill prologue above and the
        # engine-step/scan epilogue below are fixed, not permutable.
        for phase in self._schedule(tick, "phases", self._PHASES):
            if phase == "lease_sweep":
                self._lease_sweep(tick, now)
            elif phase == "autoscale":
                if self.autoscaler is not None:
                    self.autoscaler.on_tick(tick, now)
            elif phase == "wfq_drain":
                self._drain_wfq(now)
            else:                             # mig_pump
                self._pump_migrations(now)
        self._step_replicas(tick, now)
        # AFTER the engines step: prefill-class replicas whose requests
        # just finished prefilling (first token this tick) enqueue their
        # chain handoffs; the transfers clear next tick's pump
        self._scan_migratable()
        self._tick = tick + 1
        return self.has_work

    # canonical phase order for the permutable mid-tick section
    _PHASES = ("lease_sweep", "autoscale", "wfq_drain", "mig_pump")

    # SCHED-AUDIT ordering point: None (production) keeps canonical
    # order at zero cost; the schedule explorer installs a callable
    # ``hook(tick, kind, names) -> permutation`` with kind "phases"
    # (the four mid-tick phases) or "replicas" (engine step order)
    schedule_hook: Optional[Callable[[int, str, List], List]] = None

    def _schedule(self, tick: int, kind: str, names: List) -> List:
        """Ask the installed schedule hook (if any) for this tick's
        order of ``names``; the hook must return a permutation — the
        explorer probes orderings, it may not drop or invent work."""
        hook = self.schedule_hook
        if hook is None:
            return list(names)
        order = list(hook(tick, kind, list(names)))
        enforce_that(sorted(order, key=repr) == sorted(names, key=repr),
                     f"schedule_hook returned {order!r}, not a "
                     f"permutation of {names!r}", context="serving")
        return order

    def _step_replicas(self, tick: int, now: float) -> None:
        """Step every live replica (slow replicas skip their off
        ticks), harvest terminal engine statuses into fleet statuses,
        retire drained replicas — in hook-chosen order."""
        idxs = [rep.idx for rep in self.replicas]
        for idx in self._schedule(tick, "replicas", idxs):
            rep = self.replicas[idx]
            if rep.state is ReplicaState.DEAD:
                continue
            if self.faults is not None and \
                    not self.faults.replica_steps(rep.idx, tick):
                continue                      # slow replica: off tick
            if rep.engine.has_work:
                rep.engine.step()
            self._harvest(rep, self._time())
            if rep.state is ReplicaState.DRAINING and \
                    not rep.engine.has_work:
                self._retire_replica(rep, now)

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, List[int]]:
        """Tick until the fleet drains (or ``max_ticks``); returns
        {fleet rid: tokens} for completions so far.  A full drain runs
        the fleet conservation check (FLEET-LEAK on violation)."""
        ticks = 0
        while self.has_work:
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        if not self.has_work:
            self.check_fleet_conservation()
        return {frid: fr.result for frid, fr in self._requests.items()
                if fr.result is not None}

    # ---- weighted fair queuing (round 17) ----------------------------------

    def _drain_wfq(self, now: float) -> None:
        """Release buffered requests to dispatch in virtual-time order,
        bounded by the READY replicas' admission slack (two decode
        batches of headroom each, the same depth the affinity overflow
        tolerates) — so engine queues stay shallow and the WFQ, not
        FIFO arrival order, decides who runs next.  Buffered requests
        whose deadline lapsed are shed here: they never reached an
        engine, so the router is their deadline enforcer."""
        if self.wfq is None:
            return
        for tenant, freq in self.wfq.expire(
                lambda fr: fr.deadline_at is not None and
                now >= fr.deadline_at):
            self.ledger.on_shed(tenant)
            self._finish(freq, RequestStatus.TIMED_OUT, now)
        if not len(self.wfq):
            return
        budget = 0
        for rep in self._ready(set()):
            ld = rep.engine.load()
            budget += max(0, 2 * rep.engine._max_slots -
                          (ld["queue_depth"] + ld["running"]))
        while budget > 0:
            popped = self.wfq.pop()
            if popped is None:
                break
            tenant, freq = popped
            if freq.finished:
                continue       # raced a cancel; already ledgered there
            self.ledger.on_admit(tenant)
            budget -= 1
            self._dispatch(freq, now)

    # ---- dispatch / harvest ------------------------------------------------

    def _wrap_on_token(self, freq: _FleetRequest):
        """Exactly-once stream fence: forward only tokens beyond the
        high-water mark, so a resubmitted (deterministically replayed)
        request never double-delivers."""
        def cb(tok: int) -> None:
            freq.attempt_tokens += 1
            if freq.attempt_tokens > freq.emitted:
                freq.emitted += 1
                self.metrics.on_token(self._time(), tenant=freq.tenant)
                if freq.on_token is not None:
                    freq.on_token(tok)
        return cb

    def _dispatch(self, freq: _FleetRequest, now: float) -> bool:
        """Route and submit; on engine-side REJECT (backpressure, drain
        race) the next-best replica is tried — the overflow path — and
        only when every READY replica refuses is the fleet rid REJECTED."""
        tried: Set[int] = set()
        while True:
            idx, hashes, affinity, seed_from = self._route(freq.prompt,
                                                           tried)
            if idx is None:
                self._finish(freq, RequestStatus.REJECTED, now)
                return False
            rep = self.replicas[idx]
            freq.attempt_tokens = 0
            remaining = None
            if freq.deadline_at is not None:
                remaining = freq.deadline_at - now   # may be <= 0: the
                #                     engine times it out on its next tick
            erid = rep.engine.submit(freq.prompt, freq.max_tokens,
                                     on_token=self._wrap_on_token(freq),
                                     deadline_s=remaining, now=now,
                                     tenant=freq.tenant)
            if rep.engine.status(erid) is RequestStatus.REJECTED:
                tried.add(idx)
                continue
            freq.replica, freq.erid = idx, erid
            record_transition("request_status", str(freq.status), "queued",
                              registry=self.registry)
            freq.status = RequestStatus.QUEUED
            rep.rid_map[erid] = freq.frid
            if self.routing == "affinity":
                self._record_owner(hashes, idx)   # RR never reads the map
            self.metrics.on_route(affinity)
            self.tracer.instant("route", cat="fleet", replica=idx,
                                frid=freq.frid, erid=erid,
                                affinity=affinity,
                                attempt=freq.resubmits)
            if seed_from is not None and self._disagg and \
                    self.migrate_budget > 0:
                # the prefix owner warms the chosen target through the
                # page plane — paced by the destination's migrate
                # budget, racing the request's own admission (a seed
                # that lands first saves the whole prefix re-prefill;
                # one that loses still warms the cache for the NEXT
                # prompt sharing the prefix)
                self._enqueue_seed(seed_from, idx, freq.prompt)
            return True

    def _resubmit(self, freq: _FleetRequest, now: float) -> None:
        if freq.resubmits >= self.resubmit_budget:
            # budget burned: a terminal FAILED, never an infinite
            # kill->resubmit->kill loop.  Checked BEFORE counting, so
            # `resubmits` reports re-dispatches that actually happened
            # (the documented meaning), not refused ones.
            self._finish(freq, RequestStatus.FAILED, now)
            return
        freq.resubmits += 1
        self.metrics.on_resubmit()
        self.registry.counter("fleet_resubmits_total",
                              "death-driven re-dispatches").inc()
        self.tracer.instant("resubmit", cat="fleet", frid=freq.frid,
                            attempt=freq.resubmits)
        if self._dispatch(freq, now) and freq.replica is not None:
            # re-adopt surviving pages (round 16): before the target
            # engine's next tick can admit (and re-prefill) the replayed
            # request, seed its cache from whichever surviving replica
            # still holds the deepest cached prefix — typically the
            # prefill replica whose parked pages outlived the dead
            # decoder.  Synchronous on purpose: this races admission
            # within the same fleet tick, and it is already budgeted by
            # the resubmit budget that gated this very call.
            self._seed_for_resubmit(freq)

    def _harvest(self, rep: Replica, now: float) -> None:
        """Pull terminal engine statuses up into fleet statuses; mirror
        live ones for observability."""
        done: List[Tuple[int, int, RequestStatus]] = []
        for erid, frid in rep.rid_map.items():
            st = rep.engine.status(erid)
            if st.terminal:
                done.append((erid, frid, st))
            else:
                freq = self._requests[frid]
                if freq.status is not st:
                    record_transition("request_status", str(freq.status),
                                      str(st), registry=self.registry)
                freq.status = st
        for erid, frid, st in done:
            del rep.rid_map[erid]
            freq = self._requests[frid]
            if freq.finished:
                # the rid map said this engine rid still owned the fleet
                # rid, yet the fleet already finished it elsewhere: an
                # idempotence violation the conservation check must see
                self.metrics.duplicate_completions += 1
                continue
            if st is RequestStatus.COMPLETED:
                freq.result = list(rep.engine.result(erid))
                self._finish(freq, st, now)
            elif st is RequestStatus.REJECTED:
                # post-admission REJECT = the engine shed it (unmeetable
                # deadline).  The deadline carries over resubmits, so
                # re-dispatching a lost cause would only burn budget.
                self._finish(freq, st, now, shed=True)
            else:                 # TIMED_OUT / FAILED / CANCELLED
                self._finish(freq, st, now)

    def _finish(self, freq: _FleetRequest, status: RequestStatus,
                now: float, shed: bool = False) -> None:
        """THE fleet terminal transition (mirrors the engine's _finish):
        stamp, count, unbind, retire — and count a second transition
        instead of silently overwriting it."""
        if freq.finished:
            self.metrics.duplicate_completions += 1
            return
        # a terminal transition aborts any in-flight chain handoff for
        # this rid — the pump would only discover a dangling transfer
        # later, and the migration ledger must balance at ANY drain
        if self._mig_pending.pop(freq.frid, None) is not None:
            self.metrics.on_migration_aborted()
            record_transition("migration_transfer", "started", "aborted",
                              registry=self.registry)
            self.tracer.instant("migrate_abort", cat="fleet",
                                frid=freq.frid, reason="terminal")
        record_transition("request_status", str(freq.status), str(status),
                          registry=self.registry)
        freq.status = status
        freq.terminal_transitions += 1
        freq.finished_at = now
        freq.replica = None
        freq.erid = None
        self._live.discard(freq.frid)
        self.metrics.on_terminal(status, shed=shed)
        self.tracer.async_end("fleet_request", id=freq.frid,
                              id_space="frid", status=str(status),
                              resubmits=freq.resubmits,
                              tokens=freq.emitted)
        self._retired.append(freq.frid)
        while len(self._retired) > self.max_retained:
            self._requests.pop(self._retired.popleft(), None)

    # ---- page migration (round 16) ----------------------------------------

    def _enqueue_seed(self, src_idx: int, dest_idx: int,
                      prompt: Sequence[int]) -> None:
        """Queue a cross-replica prefix warm: ``src`` (the affinity
        owner) will push its cached prefix of ``prompt`` into ``dest``'s
        PrefixCache through the page plane.  Seeds ride the same
        per-destination credit as chain handoffs but are opportunistic —
        they drop silently when stale and never enter the migration
        ledger."""
        t = _Transfer(kind="seed", src=src_idx, dest=dest_idx, seq=-1,
                      tokens=[int(x) for x in prompt],
                      pages=max(1, len(prompt) // self._page_size()))
        self._mig_queues.setdefault(dest_idx, deque()).append(t)
        self.tracer.instant("seed_enqueue", cat="fleet", src=src_idx,
                            dest=dest_idx, tokens=len(t.tokens))

    def _scan_migratable(self) -> None:
        """Enqueue chain handoffs: every request on a prefill-class
        replica that has finished its prefill (first token emitted)
        moves to the least-loaded decode replica.  Runs after the
        engines step so a prefill completed THIS tick is picked up
        immediately; the transfer itself clears at the next tick's pump,
        charged against the destination's page credit."""
        if not (self._disagg and self.migrate_budget > 0):
            return
        decode_ready = [r for r in self.replicas
                        if r.state is ReplicaState.READY and
                        r.role == "decode"]
        if not decode_ready:
            return                 # no decode class left: prefill
            #                        replicas finish their own requests
        page = self._page_size()
        for rep in self.replicas:
            if rep.role != "prefill" or rep.state is ReplicaState.DEAD:
                continue
            for erid in rep.engine.migratable_rids():
                frid = rep.rid_map.get(erid)
                if frid is None:
                    continue
                freq = self._requests.get(frid)
                if freq is None or freq.finished or \
                        frid in self._mig_pending:
                    continue
                # least-loaded decode target, pending transfers included
                # (else every handoff this tick piles on one replica)
                dest = min(decode_ready, key=lambda r:
                           (len(self._mig_queues.get(r.idx, ())),) +
                           r.load_key())
                ereq = rep.engine._requests[erid]
                pages = -(-(ereq.cache_len + 1) // page)
                seq = self._mig_seq      # chain-only numbering: the
                self._mig_seq += 1       # fault plan's drop schedule
                #                          addresses the Nth HANDOFF
                t = _Transfer(kind="chain", src=rep.idx, dest=dest.idx,
                              seq=seq, frid=frid, erid=erid, pages=pages)
                self._mig_pending[frid] = t
                self._mig_queues.setdefault(dest.idx, deque()).append(t)
                self.metrics.on_migration_start()
                self.tracer.instant("migrate_start", cat="fleet",
                                    frid=frid, src=rep.idx, dest=dest.idx,
                                    seq=seq, pages=pages)

    def _pump_migrations(self, now: float) -> None:
        """Apply pending transfers, bounded per destination per tick by
        ``migrate_budget`` pages — the transfer plane's admission
        control, charged to the DESTINATION exactly like chunked
        prefill.  Unspent credit accrues while a transfer waits (a blob
        bigger than the budget lands after ceil(pages/budget) ticks) and
        resets when the queue drains, so an idle destination never banks
        a burst."""
        for dest_idx in list(self._mig_queues):
            q = self._mig_queues[dest_idx]
            credit = self._mig_credit.get(dest_idx, 0) + \
                self.migrate_budget
            while q:
                t = q[0]
                if t.kind == "chain" and \
                        self._mig_pending.get(t.frid) is not t:
                    q.popleft()       # aborted elsewhere (terminal rid)
                    continue
                viable, pages = self._transfer_viable(t)
                if not viable:
                    q.popleft()
                    self._abort_transfer(t, reason="stale")
                    continue
                if pages > credit:
                    break             # out of credit: resume next tick
                q.popleft()
                credit -= pages
                if t.kind == "seed":
                    self._apply_seed(t)
                elif self._apply_chain(t, now) == "retry":
                    # destination full right now (no slot / pages):
                    # refund and retry next tick — the source keeps
                    # decoding meanwhile, nothing is lost
                    q.appendleft(t)
                    credit += pages
                    break
            if q:
                self._mig_credit[dest_idx] = credit
            else:
                del self._mig_queues[dest_idx]
                self._mig_credit.pop(dest_idx, None)

    def _transfer_viable(self, t: _Transfer) -> Tuple[bool, int]:
        """(still worth applying?, pages to charge).  Chain transfers
        re-read the source request's CURRENT page count — it grew by its
        ongoing decode since enqueue."""
        dest = self.replicas[t.dest]
        if dest.state is not ReplicaState.READY:
            return False, 0
        src = self.replicas[t.src]
        if t.kind == "seed":
            if src.state is ReplicaState.DEAD or src.engine.cache is None:
                return False, 0
            return True, max(1, t.pages)
        freq = self._requests.get(t.frid)
        if freq is None or freq.finished or freq.replica != t.src or \
                freq.erid != t.erid or src.state is ReplicaState.DEAD:
            return False, 0           # rebound (death resubmit) or gone
        ereq = src.engine._requests.get(t.erid)
        if ereq is None or ereq.status is not RequestStatus.RUNNING or \
                ereq.prefilling or not ereq.generated:
            return False, 0
        return True, -(-(ereq.cache_len + 1) // self._page_size())

    def _abort_transfer(self, t: _Transfer, reason: str) -> None:
        if t.kind != "chain":
            return                    # seeds drop silently
        if self._mig_pending.pop(t.frid, None) is not None:
            self.metrics.on_migration_aborted()
            record_transition("migration_transfer", "started", "aborted",
                              registry=self.registry)
            self.tracer.instant("migrate_abort", cat="fleet",
                                frid=t.frid, reason=reason)

    def _apply_chain(self, t: _Transfer, now: float) -> str:
        """Execute one chain handoff.  Returns "retry" when the
        destination cannot host it right now; "done" otherwise (applied,
        or dropped-in-flight -> re-prefill fallback)."""
        src = self.replicas[t.src]
        dest = self.replicas[t.dest]
        freq = self._requests[t.frid]
        with self.tracer.span("migrate", cat="fleet", frid=t.frid,
                              src=t.src, dest=t.dest, seq=t.seq):
            blob = export_chain(src.engine, t.erid)
            if self.faults is not None and \
                    self.faults.drop_migration(t.seq):
                # blob lost in flight: the source copy is already
                # committed to cancellation (the handoff was its exit),
                # so fall back to a plain re-prefill on the destination.
                # The exactly-once fence replays the already-emitted
                # tokens silently; greedy determinism makes the stream
                # identical.
                self._mig_pending.pop(t.frid, None)
                src.rid_map.pop(t.erid, None)
                if not src.engine.status(t.erid).terminal:
                    src.engine.cancel(t.erid, now=now)
                freq.replica = None
                freq.erid = None
                freq.attempt_tokens = 0
                remaining = None
                if freq.deadline_at is not None:
                    remaining = freq.deadline_at - now
                erid2 = dest.engine.submit(
                    freq.prompt, freq.max_tokens,
                    on_token=self._wrap_on_token(freq),
                    deadline_s=remaining, now=now, tenant=freq.tenant)
                if dest.engine.status(erid2) is RequestStatus.REJECTED:
                    self._dispatch(freq, now)     # full re-route
                else:
                    freq.replica, freq.erid = t.dest, erid2
                    record_transition("request_status", str(freq.status),
                                      "queued", registry=self.registry)
                    freq.status = RequestStatus.QUEUED
                    dest.rid_map[erid2] = t.frid
                self.metrics.on_migration_fallback()
                record_transition("migration_transfer", "started",
                                  "fallback", registry=self.registry)
                self.tracer.instant("migrate_fallback", cat="fleet",
                                    frid=t.frid, seq=t.seq)
                return "done"
            # the CURRENT attempt has materialized len(generated) tokens
            # — NOT freq.emitted: a handoff of a mid-replay resubmit
            # (emitted > generated) would otherwise mis-index the
            # destination's next token and forward the wrong one
            freq.attempt_tokens = len(blob.generated)
            rid2 = import_chain(dest.engine, blob,
                                on_token=self._wrap_on_token(freq),
                                now=now)
            if rid2 is None:
                return "retry"
            self._mig_pending.pop(t.frid, None)
            # unbind BEFORE cancelling so _harvest never reads the
            # source's CANCELLED as this fleet rid's terminal status
            src.rid_map.pop(t.erid, None)
            if not src.engine.status(t.erid).terminal:
                # the source's full prefix pages stay parked in its
                # PrefixCache (RECLAIMABLE) — still exportable as seeds
                src.engine.cancel(t.erid, now=now)
            freq.replica, freq.erid = t.dest, rid2
            record_transition("request_status", str(freq.status), "running",
                              registry=self.registry)
            freq.status = RequestStatus.RUNNING
            dest.rid_map[rid2] = t.frid
            if src.engine.host_tier is not None and \
                    src.engine.cache is not None:
                # the chain now lives on the destination: drop any host
                # copies the source spilled for it, so a later warm
                # restart of the source cannot re-adopt pages the
                # migration already handed off (double-adopt)
                src.engine.host_tier.forget(src.engine.cache.chain_keys(
                    blob.prompt + blob.generated))
            if self.routing == "affinity":
                # the chain's pages now live on the decode replica: it
                # is the deepest owner for this prompt's prefix
                self._record_owner(
                    prefix_chain_hashes(freq.prompt, self._page_size()),
                    t.dest)
            self.metrics.on_migration_applied(blob.num_pages, blob.nbytes)
            record_transition("migration_transfer", "started", "applied",
                              registry=self.registry)
            self.tracer.instant("migrate_apply", cat="fleet", frid=t.frid,
                                src=t.src, dest=t.dest,
                                pages=blob.num_pages, bytes=blob.nbytes)
        return "done"

    def _apply_seed(self, t: _Transfer) -> None:
        src = self.replicas[t.src]
        dest = self.replicas[t.dest]
        blob = export_prefix(src.engine, t.tokens)
        if blob is None:
            return                    # owner evicted it meanwhile
        blocks, nbytes = import_prefix(dest.engine, blob)
        if blocks:
            self.metrics.on_seed(blocks, nbytes)
            self.tracer.instant("seed_apply", cat="fleet", src=t.src,
                                dest=t.dest, blocks=blocks, bytes=nbytes)

    def _seed_for_resubmit(self, freq: _FleetRequest) -> None:
        """Re-adopt surviving pages after a death resubmit: seed the
        resubmit target's cache from whichever live replica holds the
        DEEPEST cached prefix of the prompt, so the replay stitches onto
        imported pages instead of re-prefilling from token 0."""
        if not (self._disagg and self.migrate_budget > 0):
            return
        dest = self.replicas[freq.replica]
        if dest.engine.cache is None:
            return
        page = self._page_size()
        best, best_len = None, dest.engine.cache.lookup(freq.prompt)[1]
        for r in self.replicas:
            if r.idx == dest.idx or r.state is ReplicaState.DEAD or \
                    r.engine.cache is None:
                continue
            hit_len = r.engine.cache.lookup(freq.prompt)[1]
            if hit_len > best_len:
                best, best_len = r, hit_len
        if best is None or best_len < page:
            return                    # nobody holds more than the target
        blob = export_prefix(best.engine, freq.prompt)
        if blob is None:
            return
        blocks, nbytes = import_prefix(dest.engine, blob)
        if blocks:
            self.metrics.on_seed(blocks, nbytes)
            self.metrics.on_migration_resubmit()
            self.tracer.instant("readopt", cat="fleet", frid=freq.frid,
                                src=best.idx, dest=dest.idx,
                                blocks=blocks, bytes=nbytes)

    # ---- invariants / health ----------------------------------------------

    def check_fleet_conservation(self) -> None:
        """Fleet-wide conservation, valid at drain (raises
        :class:`PageLeakError` tagged ``FLEET-LEAK``):

        - every retained fleet rid sits at EXACTLY one terminal status
          (one terminal transition — no double completion, no overwrite,
          no rid left in flight);
        - ``duplicate_completions`` stayed 0;
        - every replica's pool — DEAD ones included, because death
          fencing cancels their in-flight work — passes the engine's
          PAGE/REF-LEAK check with zero live refs."""
        problems: List[str] = []
        for fr in self._requests.values():
            if not fr.status.terminal or fr.terminal_transitions != 1:
                problems.append(
                    f"frid {fr.frid}: status={fr.status} "
                    f"terminal_transitions={fr.terminal_transitions}")
        if self.metrics.duplicate_completions:
            problems.append(f"{self.metrics.duplicate_completions} "
                            "duplicate completions")
        for rep in self.replicas:
            try:
                rep.engine.check_page_conservation()
            except PageLeakError as e:
                problems.append(f"replica {rep.idx}: {e}")
            refs = rep.engine.pool.total_refs
            if refs != 0:
                problems.append(f"replica {rep.idx}: {refs} live page "
                                "refs after fleet drain")
        if problems:
            # flight recorder: ship the event history with the report
            # (once per router; no-op when tracing is off)
            if "FLEET-LEAK" not in self._postmortems_dumped:
                self._postmortems_dumped.add("FLEET-LEAK")
                self.tracer.dump_postmortem("FLEET-LEAK")
            raise PageLeakError("FLEET-LEAK: " + "; ".join(problems))

    def healthz(self) -> Dict[str, object]:
        """Fleet liveness snapshot: aggregate ok, per-replica state +
        load signals, and the idempotence counter."""
        reps = {}
        ok = True
        tenants: Dict[str, Dict[str, int]] = {}
        for rep in self.replicas:
            hz = rep.engine.healthz()
            if rep.state is not ReplicaState.DEAD and not hz["ok"]:
                ok = False
            # per-tenant fleet aggregation (round 17): sum each
            # replica's tenant_counts — dead replicas included, since
            # their historical deadline misses are still real
            for t, counts in hz["tenants"].items():
                agg = tenants.setdefault(
                    t, {"running": 0, "queued": 0, "pages_in_use": 0,
                        "pages_host": 0, "deadline_misses": 0,
                        "buffered": 0})
                for k, v in counts.items():
                    agg[k] = agg.get(k, 0) + v
            reps[rep.idx] = {
                "state": rep.state.value,
                "role": rep.role,
                "ok": hz["ok"],
                "queue_depth": hz["queue_depth"],
                "running": hz["running"],
                "free_pages": hz["free_pages"],
                "pages_host": hz.get("pages_host", 0),
                "prefill_backlog_tokens": hz["prefill_backlog_tokens"],
                "prefix_hit_rate": round(
                    rep.engine.metrics.prefix_hit_rate(), 4),
                "dead_reason": rep.dead_reason,
            }
        if self.metrics.duplicate_completions:
            ok = False
        if self.wfq is not None:
            for t, n in self.wfq.backlog().items():
                agg = tenants.setdefault(
                    t, {"running": 0, "queued": 0, "pages_in_use": 0,
                        "pages_host": 0, "deadline_misses": 0,
                        "buffered": 0})
                agg["buffered"] = n
        return {
            "ok": ok,
            "tick": self._tick,
            "in_flight": len(self._live),
            "ready": sum(1 for r in self.replicas
                         if r.state is ReplicaState.READY),
            "replicas": reps,
            "duplicate_completions": self.metrics.duplicate_completions,
            "deadline_miss_rate": round(
                self.metrics.deadline_miss_rate(), 4),
            # control-plane surfaces (round 17)
            "tenants": tenants,
            "admission_ledger": self.ledger.snapshot(),
        }

    def snapshot(self) -> Dict[str, object]:
        """Fleet metrics + per-replica prefix stats in one JSON-able
        dict (the bench's one-line contract)."""
        snap = self.metrics.snapshot()
        requested = sum(r.engine.metrics.prefix_requested_tokens
                        for r in self.replicas)
        saved = sum(r.engine.metrics.prefill_tokens_saved
                    for r in self.replicas)
        snap["fleet_prefix_hit_rate"] = round(
            saved / requested, 4) if requested else 0.0
        snap["per_replica_prefix_hit_rate"] = [
            round(r.engine.metrics.prefix_hit_rate(), 4)
            for r in self.replicas]
        snap["replica_states"] = [r.state.value for r in self.replicas]
        if self.autoscaler is not None:
            snap["control_scale_ups"] = self.autoscaler.scale_ups
            snap["control_scale_downs"] = self.autoscaler.scale_downs
            snap["control_replica_ticks"] = self.autoscaler.replica_ticks
        # keep the unified registry current: fleet counters land next to
        # the replicas' serving_* series and stage histograms, so one
        # scrape surface (registry.snapshot()/to_text()) has it all
        self.metrics.publish(self.registry)
        return snap

    def metrics_text(self) -> str:
        """Prometheus-style exposition of the fleet's unified registry
        (publishes the latest fleet + per-replica counters first)."""
        self.metrics.publish(self.registry)
        for rep in self.replicas:
            rep.engine.metrics.publish(self.registry, replica=rep.idx)
        return self.registry.to_text()


# ---------------------------------------------------------------------------
# standalone gate: `python -m paddle_tpu.serving.fleet check`
# ---------------------------------------------------------------------------


def _selfcheck() -> int:
    """Replay a small seeded kill-chaos trace and run the fleet
    conservation check — the tier-1 ladder's FLEET-LEAK gate
    (tools_tier1.sh exit 6), kept standalone so the wrapper can branch
    on THIS process's exit status instead of grepping a shared log.
    Returns 0 (clean) or 1 (findings); a crash propagates as 2."""
    import jax
    import numpy as np

    from paddle_tpu.serving.engine import DecoderLM
    from paddle_tpu.serving.faults import ManualClock

    model = DecoderLM(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          kill_at={6: 0})

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=1, page_size=4,
                             num_pages=32, max_pages_per_seq=8, max_slots=4,
                             buckets=(8, 16), time_fn=time_fn)

    fleet = FleetRouter(mk, 3, heartbeat_s=0.05, resubmit_budget=2,
                        faults=plan)
    rng = np.random.RandomState(0)
    system = rng.randint(2, 64, size=8).tolist()    # 2 full pages shared
    frids = [fleet.submit(system + rng.randint(2, 64, size=4).tolist(),
                          max_tokens=6) for _ in range(9)]
    fleet.run(max_ticks=500)        # drain runs check_fleet_conservation
    if fleet.has_work:
        print("FLEET-LEAK: fleet failed to drain within 500 ticks")
        return 1
    snap = fleet.snapshot()
    bad = [f for f in frids if not fleet.status(f).terminal]
    if bad or snap["fleet_duplicate_completions"]:
        print(f"FLEET-LEAK: non-terminal={bad} "
              f"dups={snap['fleet_duplicate_completions']}")
        return 1
    print(f"fleet-check ok: {snap['fleet_completed']} completed, "
          f"{snap['fleet_resubmits']} resubmits after 1 injected kill, "
          f"0 duplicate completions, 0 leaks across "
          f"{len(fleet.replicas)} replicas")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI dispatch, importable so callers (tools_tier1.sh) can run the
    gate via ``python -c "...fleet.main(['check'])"`` — ``python -m``
    would have runpy execute a SECOND copy of this module alongside the
    one ``paddle_tpu.serving`` already imported (its RuntimeWarning),
    leaving duplicate FleetRouter/ReplicaState classes in the process."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else "check"
    if cmd != "check":
        print(f"unknown command {cmd!r}; usage: "
              "python -m paddle_tpu.serving.fleet check")
        return 2
    try:
        return _selfcheck()
    except PageLeakError as e:
        print(str(e))
        return 1
    except Exception as e:   # crash != findings: distinct exit code
        print(f"fleet check crashed: {e!r}")
        return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
