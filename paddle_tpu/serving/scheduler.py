"""Continuous-batching scheduler: the host-side policy of the serving
engine.

The reference served generation through ``SequenceGenerator``
(paddle/api/SequenceGenerator.cpp:38-96) — one request at a time, one
host->C++ forward per token.  Here requests arrive and finish at
different times and the chip must stay busy throughout, so scheduling is
continuous: every engine tick (1) admits queued requests while slots AND
pages are available, (2) prefills them bucketed to a small ladder of
padded lengths (one jit specialization per bucket), (3) runs ONE fused
decode step over all running sequences, (4) retires sequences on EOS or
``max_tokens`` and returns their pages, and (5) when the page pool runs
dry mid-decode, preempts the youngest running sequence (its pages are
freed, its tokens re-queued for re-prefill — the recompute flavour of
vLLM-style preemption) so the oldest requests always make progress.

Robustness policy (the SLO layer the engine drives):

- every request carries a terminal-status :class:`RequestStatus` and
  optional queue/total deadlines;
- re-prefill recomputes are CAPPED per request
  (``SchedulerConfig.preempt_budget``): a request that has burned its
  budget is never chosen as a preemption victim again and requeues with
  escalated priority (ahead of every non-escalated entry), so
  youngest-first preemption cannot livelock a long prompt;
- ``release`` takes the terminal status, so timeout/cancel/failure all
  share one slot-and-pages return path.

This module is pure bookkeeping — no jax.  The engine owns the compiled
prefill/decode functions and calls into the scheduler for decisions, so
the policy is testable without a model.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Deque, Dict, List, Optional, Tuple

from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.serving.kv_cache import PagePool, PrefixCache

_rid_counter = itertools.count()


class RequestStatus(str, Enum):
    """Request lifecycle.  ``str``-valued so existing comparisons against
    the literal strings keep working (``req.status == "queued"``)."""

    QUEUED = "queued"
    RUNNING = "running"
    PREEMPTED = "preempted"      # evicted, waiting to re-prefill
    COMPLETED = "completed"
    TIMED_OUT = "timed_out"
    CANCELLED = "cancelled"
    REJECTED = "rejected"
    FAILED = "failed"

    @property
    def terminal(self) -> bool:
        return self in _TERMINAL

    def __str__(self) -> str:  # "completed", not "RequestStatus.COMPLETED"
        return self.value


_TERMINAL = frozenset({RequestStatus.COMPLETED, RequestStatus.TIMED_OUT,
                       RequestStatus.CANCELLED, RequestStatus.REJECTED,
                       RequestStatus.FAILED})


@dataclass
class Request:
    """One generation request and its runtime bookkeeping."""

    prompt: List[int]
    max_tokens: int
    on_token: Optional[Callable[[int], None]] = None
    # sampling policy (None = greedy argmax, the parity-test contract);
    # a SamplingParams from serving.speculate with seeded per-position
    # RNG streams, so replays are bit-identical
    sampling: Optional[object] = None
    # multi-tenant identity (round 17): who this request bills to.  The
    # control plane (serving/control.py) keys SLO deadlines, quotas and
    # preemption precedence on it; it survives preemption, death
    # resubmission and chain migration unchanged.
    tenant: str = "default"
    rid: int = field(default_factory=lambda: next(_rid_counter))
    # SLOs (absolute times on the engine's clock; None = unbounded)
    queue_deadline_at: Optional[float] = None   # must be admitted by
    deadline_at: Optional[float] = None         # must finish by

    # runtime state (owned by the scheduler/engine)
    generated: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    cache_len: int = 0              # tokens currently materialized in KV
    status: RequestStatus = RequestStatus.QUEUED
    submitted_at: Optional[float] = None
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    preemptions: int = 0
    escalated: bool = False         # preempt budget burned: never a victim
    last_progress_tick: int = 0     # engine tick of the last emitted token
    # prefix caching + chunked prefill (round 9)
    cached_len: int = 0             # prefix tokens stitched from the cache
    cow_src: Optional[int] = None   # shared page to COW-fork before prefill
    prefilling: bool = False        # admitted but chunks still
    #                                 materializing; False once decoding
    # cache-insert chain cursor (engine-owned, reset per admission):
    # chunk j's insert resumes hashing where chunk j-1 stopped
    chain_hash: Optional[int] = None
    chain_blocks: int = 0
    # speculative decoding (round 18): per-request acceptance counters
    # (the per-slot acceptance-rate observable)
    spec_proposed: int = 0          # drafted tokens shipped to verify
    spec_accepted: int = 0          # of those, accepted

    @property
    def cache_tokens(self) -> List[int]:
        """Tokens that must be in the KV cache before the next decode:
        the prompt plus everything generated so far (after a preemption
        the whole list is re-prefilled and the prefill's last-position
        logits produce the NEXT, not-yet-emitted token)."""
        return self.prompt + self.generated

    @property
    def finished(self) -> bool:
        return self.status in _TERMINAL

    @property
    def tokens_remaining(self) -> int:
        return max(0, self.max_tokens - len(self.generated))


@dataclass(frozen=True)
class SchedulerConfig:
    max_slots: int
    page_size: int
    max_pages_per_seq: int
    max_queue: Optional[int] = None     # None = unbounded queueing
    preempt_budget: Optional[int] = None  # None = unlimited re-prefills

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq


class ContinuousBatchingScheduler:
    """Queue + slot + page bookkeeping.  All methods are host-side and
    cheap; device work happens in the engine between calls."""

    def __init__(self, pool: PagePool, cfg: SchedulerConfig,
                 cache: Optional[PrefixCache] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.pool = pool
        self.cfg = cfg
        self.cache = cache          # prefix cache; None = caching off
        self.tracer = None          # obs hook, bound by the engine; None
        #                             (tracing off) costs one is-None
        #                             check on the preempt/requeue edges
        # injectable clock (engine passes its own — possibly a fault
        # plan's ManualClock); only the submit(now=None) fallback reads it
        self._time = time_fn
        self.queue: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}       # slot -> request
        self._free_slots: List[int] = list(range(cfg.max_slots - 1, -1, -1))
        self.preemption_count = 0
        # tenant preemption precedence (round 17): a callable
        # ``tenant -> rank`` bound by the control plane (higher rank =
        # victimized FIRST, so batch-class slots evict before
        # interactive ones).  None — the default — ranks every tenant
        # equally and preserves the classic pure-youngest-first policy.
        self.precedence_fn: Optional[Callable[[str], int]] = None
        # O(1) load probe for class-aware fleet routing (round 16):
        # prompt tokens still to prefill across queued + running
        # requests, maintained incrementally on every cache_len edge
        # (submit/admit/chunk/preempt/release).  ``recompute_backlog``
        # is the audit-time ground truth.
        self.prefill_backlog_tokens = 0

    # ---- admission -------------------------------------------------------

    def submit(self, req: Request, now: Optional[float] = None) -> bool:
        """Enqueue, or refuse.  Refusal (returns False, status
        ``REJECTED``) happens for requests that could NEVER run — longer
        than ``max_seq_len`` or needing more pages than the pool owns —
        and as backpressure when the queue is at ``max_queue``."""
        enforce_that(len(req.prompt) >= 1, "empty prompt", context="serving")
        enforce_that(req.max_tokens >= 1, "max_tokens must be >= 1",
                     context="serving")
        req.submitted_at = self._time() if now is None else now
        total = len(req.prompt) + req.max_tokens
        if total > self.cfg.max_seq_len or \
                self._pages_for(total) > self.pool.num_usable:
            req.status = RequestStatus.REJECTED
            return False
        if self.cfg.max_queue is not None and \
                len(self.queue) >= self.cfg.max_queue:
            req.status = RequestStatus.REJECTED
            return False
        req.status = RequestStatus.QUEUED
        self.queue.append(req)
        self._backlog_enter(req)
        return True

    # ---- prefill-backlog accounting (round 16) ----------------------------
    #
    # Invariant: ``prefill_backlog_tokens`` equals the sum over every
    # queued-or-running request of ``max(0, len(prompt) - cache_len)`` —
    # the prompt tokens the engine still owes a prefill.  Decoding
    # requests (cache_len >= prompt) contribute 0, so the number is the
    # pure prefill debt the fleet router reads before dispatching a
    # prompt to a prefill-class replica.

    def _backlog_enter(self, req: Request) -> None:
        self.prefill_backlog_tokens += max(0,
                                           len(req.prompt) - req.cache_len)

    def _backlog_leave(self, req: Request) -> None:
        self.prefill_backlog_tokens -= max(0,
                                           len(req.prompt) - req.cache_len)

    def note_prefill_progress(self, req: Request, old_cache_len: int) -> None:
        """Re-account a tracked request after its ``cache_len`` moved
        (admission stitch, a finished prefill chunk, a preemption reset).
        The engine calls this from ``_finish_chunk``; the scheduler's
        own edges call it internally."""
        plen = len(req.prompt)
        self.prefill_backlog_tokens += (max(0, plen - req.cache_len)
                                        - max(0, plen - old_cache_len))

    def recompute_backlog(self) -> int:
        """Ground-truth backlog (O(requests)); the migrate conservation
        checker compares this against the incremental counter."""
        live = list(self.queue) + list(self.running.values())
        return sum(max(0, len(r.prompt) - r.cache_len) for r in live)

    def _pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.cfg.page_size)  # ceil

    def _alloc(self, n: int) -> Optional[List[int]]:
        """Allocate with cache pressure relief: when the free list is
        short, evict LRU refcount-0 cached pages to cover the shortfall
        before giving up — cached pages are an opportunistic reserve,
        never a reason to refuse admission or trigger preemption."""
        if self.cache is not None and n > self.pool.num_free:
            self.cache.evict(n - self.pool.num_free)
        return self.pool.alloc(n)

    def admit(self) -> List[Request]:
        """Move queued requests into slots while a slot AND the pages for
        their (re-)prefill are available.  FIFO with head-of-line
        blocking: a big request at the head waits rather than being
        starved by small ones slipping past it.

        The allocation covers ``cache_tokens + 1`` — the prefill plus
        the first decode append — so a freshly-admitted request can
        never be the growth victim of the very tick that paid for its
        prefill (the engine runs growth/preemption BEFORE admission).

        With a prefix cache, the request is charged only its NEW pages:
        the longest verified cached prefix is stitched in as shared
        pages (ref'd, not copied) and the prefill starts at
        ``cached_len``.  A full-cover hit (every page of ``cache_tokens``
        cached) marks the last shared page for a copy-on-write fork —
        the tail must recompute the final position's logits, and its KV
        write may not land in a page other sequences read."""
        admitted: List[Request] = []
        page = self.cfg.page_size
        while self.queue and self._free_slots:
            req = self.queue[0]
            toks = req.cache_tokens
            total = self._pages_for(len(toks) + 1)
            shared: List[int] = []
            stitched = 0
            cow_src = None
            if self.cache is not None:
                hit_pages, hit_len = self.cache.lookup(toks)
                if hit_pages and hit_len >= len(toks):
                    # full cover: fork the last shared page, recompute
                    # only the final token (its logits seed decoding)
                    cow_src = hit_pages[-1]
                    shared = hit_pages[:-1]
                    stitched = len(toks) - 1
                else:
                    shared = hit_pages
                    stitched = hit_len
            # pin the stitched pages (and the COW fork source — it is
            # read by the engine's fork, after this call returns) BEFORE
            # allocating: _alloc may evict refcount-0 cached pages, and
            # without the pin it could evict and re-grant the very pages
            # this hit is about to share.  On refusal the pins are
            # dropped, restoring the exact prior state (all-or-nothing).
            self.pool.ref(shared)
            if cow_src is not None:
                self.pool.ref([cow_src])
            new = self._alloc(total - len(shared))
            if new is None:
                self.pool.free(shared)
                if cow_src is not None:
                    self.pool.free([cow_src])
                break
            self.queue.popleft()
            if self.cache is not None:
                # admission committed: NOW touch the LRU order and the
                # hit/miss counters, exactly once per stitch (the probe
                # above was a pure read; the pins above guarantee the
                # re-walk sees the same entries)
                self.cache.lookup(toks, touch=True)
            req.pages = shared + new     # page j holds tokens [jP, jP+P)
            old_len = req.cache_len      # 0 (fresh or preempt-reset)
            req.cached_len = stitched
            req.cache_len = stitched     # engine prefills from here on
            self.note_prefill_progress(req, old_len)
            req.cow_src = cow_src        # fork target is new[0] (engine)
            req.slot = self._free_slots.pop()
            req.status = RequestStatus.RUNNING
            self.running[req.slot] = req
            admitted.append(req)
        return admitted

    def drop_queued(self, req: Request, status: RequestStatus) -> None:
        """Remove a not-yet-admitted request from the queue with a
        terminal status (deadline shed, cancellation)."""
        enforce_that(status in _TERMINAL, "drop_queued needs a terminal "
                     "status", context="serving")
        try:
            self.queue.remove(req)
            self._backlog_leave(req)
        except ValueError:
            pass
        req.status = status

    # ---- decode-time growth / preemption --------------------------------

    def ensure_decode_pages(self) -> List[Request]:
        """Before a decode tick: every running sequence whose next append
        lands on a page boundary needs one more page.  Oldest requests
        are served first; when the pool is dry, refcount-0 cached pages
        are LRU-evicted first, and only then is the YOUNGEST running
        sequence still under its preemption budget preempted (pages
        unref'd, tokens re-queued at the front) until the growth fits.
        A grower with no eligible victim preempts ITSELF — correctness
        (the append must land on an owned page) beats its budget.
        Returns the preempted requests."""
        preempted: List[Request] = []
        for req in sorted(self.running.values(),
                          key=lambda r: (r.submitted_at, r.rid)):
            if req.status is not RequestStatus.RUNNING:
                continue  # preempted below while an older one grew
            if req.cache_len < len(req.pages) * self.cfg.page_size:
                continue
            while True:
                got = self._alloc(1)
                if got is not None:
                    req.pages.extend(got)
                    break
                victim = self._youngest_victim(exclude=req)
                if victim is None:
                    victim = req  # alone (or peers exempt): requeue itself
                self._preempt(victim)
                preempted.append(victim)
                if victim is req:
                    break
        return preempted

    def alloc_pages(self, n: int) -> Optional[List[int]]:
        """Public allocation seam for engine-side page needs outside
        admission/growth (the verify-time COW fork): same cache-evict
        relief as every other allocation, never preemption.  Returns
        the pages at refcount 1, or None."""
        return self._alloc(n)

    def grant_lookahead(self, req: Request, k: int) -> int:
        """Charge pages for ``k`` speculative lookahead tokens beyond
        the base decode append — OPPORTUNISTICALLY: cached pages may be
        LRU-evicted to cover it (via ``_alloc``) but nothing is ever
        preempted for speculation, so under page pressure the grant
        shrinks and the engine speculates less (down to the plain
        1-token decode, which ``ensure_decode_pages`` already
        guaranteed).  Returns the lookahead that actually fits —
        ``min(k, owned page room - 1)``, also bounded by the page-table
        width."""
        page = self.cfg.page_size
        want = req.cache_len + int(k) + 1
        while len(req.pages) * page < want:
            if len(req.pages) >= self.cfg.max_pages_per_seq:
                break
            got = self._alloc(1)
            if got is None:
                break
            req.pages.extend(got)
        return max(0, min(int(k),
                          len(req.pages) * page - req.cache_len - 1))

    def rollback_pages(self, req: Request) -> int:
        """Roll a speculating request's page table back to its length:
        free lookahead pages past what ``cache_len + 1`` (the next
        decode append — the same charge admission makes) needs.  Only
        ever frees pages past the materialized length, so stitched
        prefix pages (always a prefix of the table, below ``cache_len``)
        can never be touched.  Returns how many pages went back."""
        needed = max(1, self._pages_for(req.cache_len + 1))
        if len(req.pages) <= needed:
            return 0
        extra = req.pages[needed:]
        del req.pages[needed:]
        self.pool.free(extra)
        return len(extra)

    def _youngest_victim(self, exclude: Request) -> Optional[Request]:
        budget = self.cfg.preempt_budget
        cands = [r for r in self.running.values()
                 if r is not exclude and not r.escalated and
                 (budget is None or r.preemptions < budget)]
        if not cands:
            return None
        # precedence leads the key: with a control plane bound, the
        # highest-rank tenant class (batch) is victimized before any
        # lower-rank one (interactive), and only WITHIN a rank does the
        # classic youngest-first rule pick
        rank = self.precedence_fn or (lambda tenant: 0)
        return max(cands, key=lambda r: (rank(r.tenant), r.submitted_at,
                                         r.rid))

    def _preempt(self, req: Request) -> None:
        if self.tracer is not None:
            self.tracer.instant("preempt", rid=req.rid, slot=req.slot,
                                preemptions=req.preemptions + 1)
        self._release_slot_and_pages(req)
        old_len = req.cache_len
        req.cache_len = 0
        self.note_prefill_progress(req, old_len)  # re-owes its prefill
        req.cached_len = 0
        req.cow_src = None
        req.prefilling = False       # re-stitched at re-admission
        req.status = RequestStatus.PREEMPTED
        req.preemptions += 1
        self.preemption_count += 1
        if self.cfg.preempt_budget is not None and \
                req.preemptions >= self.cfg.preempt_budget:
            req.escalated = True
        self._requeue_front(req)

    def _requeue_front(self, req: Request) -> None:
        """Preempted requests go back to the front; an escalated request
        jumps ahead of everything, a normal one slots in after the
        leading escalated run (escalation is a real priority, not just a
        no-more-preemptions flag)."""
        if req.escalated:
            self.queue.appendleft(req)
            return
        i = 0
        for r in self.queue:
            if not r.escalated:
                break
            i += 1
        self.queue.insert(i, req)

    # ---- completion ------------------------------------------------------

    def release(self, req: Request,
                status: RequestStatus = RequestStatus.COMPLETED) -> None:
        """Return a sequence's slot and pages to the pool with its
        terminal status — completion, timeout, cancellation, and failure
        all exit through here so none of them can leak."""
        enforce_that(status in _TERMINAL, "release needs a terminal status",
                     context="serving")
        self._backlog_leave(req)
        self._release_slot_and_pages(req)
        req.status = status

    def _release_slot_and_pages(self, req: Request) -> None:
        if req.cow_src is not None:
            # admission pinned the fork source; if the request exits
            # before the engine ran the fork, drop the pin here
            self.pool.free([req.cow_src])
            req.cow_src = None
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []
        if req.slot is not None:
            del self.running[req.slot]
            self._free_slots.append(req.slot)
            req.slot = None

    # ---- views -----------------------------------------------------------

    def running_requests(self) -> List[Request]:
        return [self.running[s] for s in sorted(self.running)]

    def queued_requests(self) -> List[Request]:
        return list(self.queue)

    @property
    def has_work(self) -> bool:
        return bool(self.queue or self.running)

    @property
    def queue_depth(self) -> int:
        return len(self.queue)


def pack_prefill_chunks(prefilling: List[Request], chunk: int, align: int,
                        budget: int) -> Tuple[List[Tuple[Request, int, int,
                                                         int]], int]:
    """Select which prefill chunks ride in THIS tick's unified step.

    Each prefilling request contributes one chunk of at most ``chunk``
    tokens (0 = its whole remainder), padded up to ``align`` rows (the
    ragged kernel's one-sequence-per-block packing; 1 on the reference
    path).  Chunks pack greedily in the given order until ``budget``
    rows — the engine orders candidates oldest-progress-first, so a
    request crowded out this tick is first in line next tick and the
    per-tick prefill row count (hence the jit bucket) stays bounded.
    The FIRST chunk always packs even if it alone exceeds the budget
    (``bucket_for`` rounds the oversize up), so progress is guaranteed.

    Returns ``([(request, start, n_tokens, n_rows)], total_rows)``;
    this is scheduling policy, so it lives here with the rest of it.
    """
    out: List[Tuple[Request, int, int, int]] = []
    total = 0
    for req in prefilling:
        remaining = len(req.cache_tokens) - req.cache_len
        if remaining <= 0:
            continue
        n = remaining if chunk <= 0 else min(chunk, remaining)
        rows = -(-n // align) * align
        if out and total + rows > budget:
            break
        out.append((req, req.cache_len, n, rows))
        total += rows
    return out, total


def bucket_for(length: int, buckets: Tuple[int, ...], max_len: int) -> int:
    """Smallest bucket >= length; lengths beyond the ladder round up to
    the next page-agnostic multiple of the largest bucket, capped at
    ``max_len`` (so the number of prefill jit specializations stays
    O(len(buckets) + max_len / max(buckets)))."""
    for b in sorted(buckets):
        if length <= b <= max_len:
            return b
    top = max(buckets) if buckets else max_len
    return min(max_len, -(-length // top) * top)
