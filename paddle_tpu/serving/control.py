"""Multi-tenant SLO control plane (round 17 — ROADMAP open item 5,
the policy layer over the fleet's mechanisms).

Every mechanism this module governs already exists: drain/join
elasticity and lease-driven membership (fleet.py), deadlines /
shedding / preemption budgets (engine.py + scheduler.py), role-split
replicas with page migration (migrate.py), one Prometheus scrape
surface (obs.registry).  What was missing is POLICY — today one
tenant's prompt storm starves everyone and fleet size is fixed
forever.  Three pieces compose here:

- :class:`TenantRegistry` — SLO classes (interactive / standard /
  batch, overridable per tenant): latency-tier deadlines stamped at
  fleet submit, token-rate quotas enforced at admission via
  injected-clock token buckets, and preemption precedence so
  batch-class slots are victimized before interactive ones
  (``ContinuousBatchingScheduler.precedence_fn``).
- :class:`WeightedFairQueue` — per-tenant virtual-time queues ahead
  of dispatch, prompt-token-weighted service: an adversarial storm
  from one tenant backlogs only that tenant's queue while the others
  drain at their weighted share and keep their deadline SLO.
- :class:`Autoscaler` — a policy loop on the same injected clock that
  joins/drains replicas from registry signals (queue_wait_ms_p95,
  pages_in_use, deadline-miss delta, prefill_backlog_tokens) with
  hysteresis + cooldown; in disaggregated fleets the joined replica's
  role follows the dominant pressure (prefill backlog vs decode
  load), and the drain candidate is never the last prefill-capable
  replica (``FleetRouter.drain_replica`` refuses that loudly — the
  pinned behavior; the autoscaler filters candidates so it never
  trips it).

The conservation story extends to admission: the
:class:`AdmissionLedger` partitions every submitted fleet request,
per tenant, as ``submitted == admitted + quota_deferred + shed`` —
"admitted" the moment the router releases it to dispatch (immediately
with WFQ off; at WFQ drain with it on), "quota_deferred" when the
token bucket refuses it (terminal REJECTED), "shed" when it leaves
the WFQ buffer without dispatch (deadline expiry, or cancel while
buffered).  :func:`check_control_conservation` asserts the partition,
an empty WFQ at drain, zero duplicate completions and the fleet's own
page/ref conservation on every replica (dead ones included);
violations raise :class:`~paddle_tpu.serving.faults.PageLeakError`
tagged ``CONTROL-LEAK`` (tools_tier1.sh exit 12), and ``python -c
"...control.main(['check'])"`` replays a seeded tenant-storm +
autoscale + kill trace as the standalone gate.

This module must stay importable WITHOUT fleet.py (fleet imports it);
the selfcheck imports the router lazily.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import (Callable, Deque, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.serving.faults import PageLeakError

__all__ = ["TenantClass", "TenantSpec", "TenantRegistry", "DEFAULT_CLASSES",
           "AdmissionLedger", "WeightedFairQueue", "AutoscalePolicy",
           "Autoscaler", "check_control_conservation"]


# ---------------------------------------------------------------------------
# SLO classes and the tenant registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TenantClass:
    """One latency tier: the default deadline stamped on submits that
    do not carry their own, the WFQ service weight, and the preemption
    precedence rank (HIGHER rank = victimized FIRST when the scheduler
    needs pages back, so batch slots evict before interactive ones)."""

    name: str
    deadline_s: Optional[float]    # None = no deadline (batch)
    weight: float                  # WFQ service share
    precedence: int                # higher = preempted first


DEFAULT_CLASSES: Dict[str, TenantClass] = {
    "interactive": TenantClass("interactive", deadline_s=0.5, weight=4.0,
                               precedence=0),
    "standard": TenantClass("standard", deadline_s=2.0, weight=2.0,
                            precedence=1),
    "batch": TenantClass("batch", deadline_s=None, weight=1.0,
                         precedence=2),
}


@dataclass
class TenantSpec:
    """One tenant's resolved policy: its class plus per-tenant
    overrides, and the token-bucket quota state.  The bucket runs on
    whatever clock the caller passes ``now`` from — it never reads a
    clock itself, so fleet replays on an injected clock are
    bit-deterministic."""

    name: str
    cls: TenantClass
    deadline_s: Optional[float] = None     # None = class default
    quota_tokens_per_s: Optional[float] = None   # None = unmetered
    burst_tokens: Optional[float] = None   # None = 1s worth of quota
    # token-bucket state (filled lazily on first admit)
    _tokens: float = field(default=0.0, repr=False)
    _last_refill: Optional[float] = field(default=None, repr=False)

    @property
    def effective_deadline_s(self) -> Optional[float]:
        return self.cls.deadline_s if self.deadline_s is None \
            else self.deadline_s

    @property
    def effective_burst(self) -> float:
        if self.burst_tokens is not None:
            return float(self.burst_tokens)
        return float(self.quota_tokens_per_s or 0.0)

    def take(self, cost: float, now: float) -> bool:
        """Token-bucket admission: refill at ``quota_tokens_per_s``
        capped at the burst, then take ``cost`` tokens or refuse.
        Unmetered tenants (no quota) always pass."""
        if self.quota_tokens_per_s is None:
            return True
        if self._last_refill is None:
            self._tokens = self.effective_burst    # bucket starts full
        else:
            dt = max(0.0, now - self._last_refill)
            self._tokens = min(self.effective_burst,
                               self._tokens + dt * self.quota_tokens_per_s)
        self._last_refill = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False


class TenantRegistry:
    """Tenant -> policy resolution.  Unknown tenants auto-register as
    ``standard`` on first touch — the legacy "default" tenant every
    un-annotated submit bills to just works, with middle-tier SLOs."""

    def __init__(self, classes: Optional[Dict[str, TenantClass]] = None):
        self.classes = dict(DEFAULT_CLASSES if classes is None else classes)
        self._specs: Dict[str, TenantSpec] = {}

    def register(self, name: str, cls: str = "standard", *,
                 deadline_s: Optional[float] = None,
                 quota_tokens_per_s: Optional[float] = None,
                 burst_tokens: Optional[float] = None) -> TenantSpec:
        enforce_that(cls in self.classes,
                     f"unknown tenant class {cls!r} for tenant {name!r} "
                     f"(have {sorted(self.classes)})", context="serving")
        spec = TenantSpec(name=str(name), cls=self.classes[cls],
                          deadline_s=deadline_s,
                          quota_tokens_per_s=quota_tokens_per_s,
                          burst_tokens=burst_tokens)
        self._specs[spec.name] = spec
        return spec

    def spec(self, name: str) -> TenantSpec:
        sp = self._specs.get(name)
        if sp is None:
            sp = self.register(name)       # auto-register: standard tier
        return sp

    def deadline_s(self, name: str) -> Optional[float]:
        return self.spec(name).effective_deadline_s

    def weight(self, name: str) -> float:
        return self.spec(name).cls.weight

    def precedence(self, name: str) -> int:
        """The scheduler's victim rank (bound to
        ``ContinuousBatchingScheduler.precedence_fn``)."""
        return self.spec(name).cls.precedence

    def admit_quota(self, name: str, cost_tokens: float,
                    now: float) -> bool:
        return self.spec(name).take(float(cost_tokens), now)

    def tenants(self) -> List[str]:
        return sorted(self._specs)

    @classmethod
    def from_flag(cls, text: str) -> "TenantRegistry":
        """Parse ``FLAGS.serving_tenant_classes``: a comma list of
        ``name:class`` pairs (``alice:interactive,bulk:batch``).  A
        bare name (no colon) registers as standard."""
        reg = cls()
        for part in str(text).split(","):
            part = part.strip()
            if not part:
                continue
            name, _, klass = part.partition(":")
            reg.register(name.strip(), klass.strip() or "standard")
        return reg


# ---------------------------------------------------------------------------
# admission ledger: the CONTROL-LEAK partition
# ---------------------------------------------------------------------------


class AdmissionLedger:
    """Per-tenant admission accounting.  The invariant the gate
    asserts: for every tenant, ``submitted == admitted +
    quota_deferred + shed`` — each submit ends in exactly one bucket,
    so no request can be silently dropped between the front door and
    dispatch (nor double-released into the fleet)."""

    def __init__(self):
        self.submitted: Dict[str, int] = {}
        self.admitted: Dict[str, int] = {}
        self.quota_deferred: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}

    @staticmethod
    def _inc(d: Dict[str, int], tenant: str) -> None:
        d[tenant] = d.get(tenant, 0) + 1

    def on_submit(self, tenant: str) -> None:
        self._inc(self.submitted, tenant)

    def on_admit(self, tenant: str) -> None:
        self._inc(self.admitted, tenant)

    def on_quota_deferred(self, tenant: str) -> None:
        self._inc(self.quota_deferred, tenant)

    def on_shed(self, tenant: str) -> None:
        self._inc(self.shed, tenant)

    def problems(self) -> List[str]:
        out: List[str] = []
        tenants = set(self.submitted) | set(self.admitted) | \
            set(self.quota_deferred) | set(self.shed)
        for t in sorted(tenants):
            sub = self.submitted.get(t, 0)
            adm = self.admitted.get(t, 0)
            quo = self.quota_deferred.get(t, 0)
            shd = self.shed.get(t, 0)
            if sub != adm + quo + shd:
                out.append(f"tenant {t!r}: submitted={sub} != "
                           f"admitted={adm} + quota_deferred={quo} + "
                           f"shed={shd}")
        return out

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {t: {"submitted": self.submitted.get(t, 0),
                    "admitted": self.admitted.get(t, 0),
                    "quota_deferred": self.quota_deferred.get(t, 0),
                    "shed": self.shed.get(t, 0)}
                for t in sorted(set(self.submitted) | set(self.admitted) |
                                set(self.quota_deferred) | set(self.shed))}


# ---------------------------------------------------------------------------
# weighted fair queuing (virtual-time WFQ)
# ---------------------------------------------------------------------------


class WeightedFairQueue:
    """Classic virtual-time WFQ over per-tenant FIFO queues.

    Each pushed item is stamped a virtual FINISH time::

        start  = max(vtime, last_finish[tenant])
        finish = start + cost / weight

    and ``pop`` serves the earliest head finish tag across tenants,
    advancing ``vtime`` to it.  With cost = prompt tokens, a tenant
    flooding 10x traffic only pushes ITS OWN finish tags far into the
    virtual future — other tenants' tags stay near ``vtime`` and keep
    being served at their weighted share, which is exactly the
    cross-tenant isolation the storm bench asserts."""

    def __init__(self):
        self._queues: Dict[str, Deque[Tuple[float, object]]] = {}
        self._last_finish: Dict[str, float] = {}
        self._vtime = 0.0

    def push(self, tenant: str, cost: float, weight: float,
             item: object) -> None:
        start = max(self._vtime, self._last_finish.get(tenant, 0.0))
        fin = start + max(1.0, float(cost)) / max(1e-9, float(weight))
        self._last_finish[tenant] = fin
        self._queues.setdefault(tenant, deque()).append((fin, item))

    def pop(self) -> Optional[Tuple[str, object]]:
        """Serve the earliest finish tag; None when empty."""
        best: Optional[str] = None
        best_fin = 0.0
        for t, q in self._queues.items():
            if not q:
                continue
            fin = q[0][0]
            if best is None or fin < best_fin:
                best, best_fin = t, fin
        if best is None:
            return None
        fin, item = self._queues[best].popleft()
        if not self._queues[best]:
            del self._queues[best]
        self._vtime = max(self._vtime, fin)
        return best, item

    def remove(self, item: object) -> Optional[str]:
        """Drop ``item`` wherever it is buffered; returns its tenant
        (None when not found) so the caller can balance the ledger."""
        for t, q in list(self._queues.items()):
            for pair in q:
                if pair[1] is item:
                    q.remove(pair)
                    if not q:
                        del self._queues[t]
                    return t
        return None

    def expire(self, pred: Callable[[object], bool]
               ) -> List[Tuple[str, object]]:
        """Remove every buffered item with ``pred(item)`` true;
        returns the (tenant, item) pairs removed."""
        out: List[Tuple[str, object]] = []
        for t, q in list(self._queues.items()):
            keep = deque(p for p in q if not pred(p[1]))
            if len(keep) != len(q):
                out.extend((t, p[1]) for p in q if pred(p[1]))
                if keep:
                    self._queues[t] = keep
                else:
                    del self._queues[t]
        return out

    def backlog(self) -> Dict[str, int]:
        return {t: len(q) for t, q in self._queues.items()}

    def items(self) -> Iterable[Tuple[str, object]]:
        for t, q in self._queues.items():
            for _, item in q:
                yield t, item

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------


@dataclass
class AutoscalePolicy:
    """Hysteresis knobs for the policy loop.  ``*_hi`` thresholds
    trigger scale-UP when ANY is breached; scale-DOWN needs the fleet
    genuinely idle (zero queued/running/buffered work and no fresh
    misses) — an asymmetry on purpose: adding capacity under pressure
    is cheap to undo, removing it under load is not."""

    min_replicas: int = 1
    max_replicas: int = 8
    queue_wait_hi_ms: float = 50.0     # p95 admission wait, any replica
    pages_hi_frac: float = 0.85        # live pages / usable, any replica
    backlog_hi_tokens: int = 512       # prompt tokens still owed prefill
    buffered_hi: int = 8               # WFQ items ahead of dispatch
    cooldown_ticks: int = 10           # no action for N ticks after one


class Autoscaler:
    """Joins/drains replicas from registry signals on the fleet's
    clock.  Stateless between fleets; all counters are public so the
    bench and the gate can assert the loop actually acted:

    - ``scale_ups`` / ``scale_downs`` — actions taken;
    - ``replica_ticks`` — alive-replica x tick integral, the
      "chip-ticks" currency the autoscaled-vs-static comparison uses.
    """

    def __init__(self, router, policy: Optional[AutoscalePolicy] = None):
        self.router = router
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.scale_ups = 0
        self.scale_downs = 0
        self.replica_ticks = 0
        self._cooldown = 0
        self._last_misses = 0

    # -- signals -----------------------------------------------------------

    def _miss_delta(self) -> int:
        m = self.router.metrics
        misses = m.timed_out + m.shed
        delta = misses - self._last_misses
        self._last_misses = misses
        return delta

    def on_tick(self, tick: int, now: float) -> None:
        """One policy evaluation, called by ``FleetRouter.step`` after
        the lease sweep (so membership is current) and before WFQ
        drain/dispatch (so a joined replica can admit this tick's
        releases next tick, once JOINING promotes)."""
        from paddle_tpu.serving.fleet import ReplicaState

        R = self.router
        p = self.policy
        self.replica_ticks += sum(1 for r in R.replicas
                                  if r.state is not ReplicaState.DEAD)
        miss_delta = self._miss_delta()    # track EVERY tick, so a miss
        #                            during cooldown still reads as fresh
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        alive = [r for r in R.replicas
                 if r.state in (ReplicaState.READY, ReplicaState.JOINING)]
        ready = [r for r in alive if r.state is ReplicaState.READY]
        buffered = len(R.wfq) if R.wfq is not None else 0
        if not ready:
            # fleet-wide outage (every replica killed/draining): grow if
            # the ceiling allows — the scale-up-under-kill path
            if len(alive) < p.max_replicas and (buffered or R.has_work):
                self._scale_up(reason="no ready replicas")
            return
        wait_ms = max(r.engine.metrics.queue_wait_ms_p95() for r in ready)
        pages_frac = max(
            r.engine.pool.num_live / max(1, r.engine.pool.num_usable)
            for r in ready)
        backlog = sum(r.engine.load()["prefill_backlog_tokens"]
                      for r in ready)
        live_load = sum(r.engine.load()["queue_depth"] +
                        r.engine.load()["running"] for r in ready)
        hot = (wait_ms > p.queue_wait_hi_ms or
               pages_frac > p.pages_hi_frac or
               backlog > p.backlog_hi_tokens or
               buffered > p.buffered_hi or
               miss_delta > 0)
        # cold = provably idle: wait-p95 is a trailing window (it stays
        # high long after a storm), so the DOWN decision reads live
        # state only — nothing queued, running, buffered, owed, or
        # freshly missed
        cold = (live_load == 0 and buffered == 0 and backlog == 0 and
                miss_delta == 0)
        if hot and len(alive) < p.max_replicas:
            self._scale_up(reason=f"wait={wait_ms:.0f}ms "
                                  f"pages={pages_frac:.2f} "
                                  f"backlog={backlog} buffered={buffered} "
                                  f"miss_delta={miss_delta}")
        elif cold and len(alive) > p.min_replicas:
            self._scale_down(ready)

    # -- actions -----------------------------------------------------------

    def _role_for_join(self) -> str:
        """In a disaggregated fleet, join where the pressure is: a
        dominant prefill backlog wants another prefill replica,
        otherwise decode.  Unified fleets always join unified."""
        from paddle_tpu.serving.fleet import ReplicaState

        R = self.router
        if not R._disagg:
            return "unified"
        backlog = queued = 0
        for r in R.replicas:
            if r.state is ReplicaState.DEAD:
                continue
            ld = r.engine.load()
            backlog += ld["prefill_backlog_tokens"]
            queued += ld["queue_depth"] + ld["running"]
        return "prefill" if backlog >= queued * self._page(R) else "decode"

    @staticmethod
    def _page(R) -> int:
        return R.replicas[0].engine.kv_cfg.page_size

    def _scale_up(self, reason: str) -> None:
        R = self.router
        idx = R.add_replica(role=self._role_for_join())
        self.scale_ups += 1
        self._cooldown = self.policy.cooldown_ticks
        R.tracer.instant("autoscale_up", cat="fleet", replica=idx,
                         reason=reason)

    def _scale_down(self, ready) -> None:
        from paddle_tpu.serving.fleet import ReplicaState

        R = self.router
        # drain the newest idle replica (LIFO — undo the latest join)
        # that is NOT the last prefill-capable one: drain_replica
        # refuses that loudly, and the policy loop must never trip the
        # refusal it relies on
        for rep in sorted(ready, key=lambda r: r.idx, reverse=True):
            if R._disagg and rep.role in ("prefill", "unified"):
                others = [o for o in R.replicas
                          if o.idx != rep.idx and
                          o.state in (ReplicaState.READY,
                                      ReplicaState.JOINING) and
                          o.role in ("prefill", "unified")]
                if not others:
                    continue
            R.drain_replica(rep.idx)
            self.scale_downs += 1
            self._cooldown = self.policy.cooldown_ticks
            R.tracer.instant("autoscale_down", cat="fleet",
                             replica=rep.idx)
            return


# ---------------------------------------------------------------------------
# conservation: the CONTROL-LEAK gate
# ---------------------------------------------------------------------------


def check_control_conservation(router) -> None:
    """Control-plane conservation, valid at drain (raises
    :class:`PageLeakError` tagged ``CONTROL-LEAK``):

    - the admission ledger partitions per tenant:
      ``submitted == admitted + quota_deferred + shed``;
    - the WFQ buffer is empty (nothing half-admitted);
    - ``duplicate_completions`` stayed 0 through every scaling event;
    - the fleet's own conservation holds — every rid at exactly one
      terminal status and every replica's pool (dead ones included)
      free of page/ref leaks."""
    problems: List[str] = []
    ledger = getattr(router, "ledger", None)
    if ledger is not None:
        problems.extend(ledger.problems())
    wfq = getattr(router, "wfq", None)
    if wfq is not None and len(wfq):
        problems.append(f"{len(wfq)} requests still buffered in the "
                        "WFQ after drain")
    if router.metrics.duplicate_completions:
        problems.append(f"{router.metrics.duplicate_completions} "
                        "duplicate completions")
    try:
        router.check_fleet_conservation()
    except PageLeakError as e:
        problems.append(f"fleet conservation: {e}")
    if problems:
        if "CONTROL-LEAK" not in router._postmortems_dumped:
            router._postmortems_dumped.add("CONTROL-LEAK")
            router.tracer.dump_postmortem("CONTROL-LEAK")
        raise PageLeakError("CONTROL-LEAK: " + "; ".join(problems))


# ---------------------------------------------------------------------------
# standalone gate: `python -c "...control.main(['check'])"`
# ---------------------------------------------------------------------------


def _selfcheck() -> int:
    """Replay a seeded tenant-storm + autoscale + kill trace and run
    the control conservation check — the tier-1 ladder's CONTROL-LEAK
    gate (tools_tier1.sh exit 12), standalone so the wrapper branches
    on THIS process's exit status.  Returns 0 (clean) or 1 (findings);
    a crash propagates as 2."""
    import jax
    import numpy as np

    from paddle_tpu.serving.engine import DecoderLM, ServingEngine
    from paddle_tpu.serving.faults import FleetFaultPlan, ManualClock
    from paddle_tpu.serving.fleet import FleetRouter

    model = DecoderLM(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    clock = ManualClock(tick_s=0.01)
    # one injected clock drives everything: the kill, the storm window,
    # the quota buckets and the autoscaler cooldowns
    plan = FleetFaultPlan(seed=0, clock=clock, kill_at={10: 1},
                          tenant_storm=("carl", 2, 8, 4))
    reg = TenantRegistry()
    reg.register("alice", "interactive")
    reg.register("bob", "standard")
    # carl is metered: the storm must overflow his bucket so the
    # quota_deferred path is exercised, not just the WFQ
    reg.register("carl", "batch", quota_tokens_per_s=300.0,
                 burst_tokens=40.0)

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=1, page_size=4,
                             num_pages=32, max_pages_per_seq=8, max_slots=4,
                             buckets=(8, 16), time_fn=time_fn)

    fleet = FleetRouter(mk, 2, heartbeat_s=0.05, resubmit_budget=2,
                        faults=plan, tenants=reg, wfq=True,
                        autoscale=AutoscalePolicy(
                            min_replicas=2, max_replicas=4,
                            queue_wait_hi_ms=15.0, buffered_hi=3,
                            cooldown_ticks=3))
    scaler = fleet.autoscaler
    rng = np.random.RandomState(1)
    system = rng.randint(2, 64, size=8).tolist()     # 2 shared pages
    tick = 0
    while tick < 16 or fleet.has_work:
        if tick < 16:
            for tenant in ("alice", "bob", "carl"):
                n = plan.storm_factor(tick, tenant) if tick % 2 == 0 else 0
                for _ in range(n):
                    fleet.submit(
                        system + rng.randint(2, 64, size=4).tolist(),
                        max_tokens=4, tenant=tenant)
        fleet.step()
        tick += 1
        if tick > 600:
            print("CONTROL-LEAK: fleet failed to drain within 600 ticks")
            return 1
    # idle tail: the cold condition must hold long enough (cooldowns
    # included) for the autoscaler to shrink back toward min_replicas
    for _ in range(12):
        fleet.step()
    check_control_conservation(fleet)
    led = fleet.ledger
    misses = {t: c.get("deadline_misses", 0)
              for t, c in fleet.healthz()["tenants"].items()}
    problems: List[str] = []
    for tenant in ("alice", "bob"):
        if misses.get(tenant, 0):
            problems.append(f"non-storming tenant {tenant!r} missed "
                            f"{misses[tenant]} deadlines under carl's "
                            "storm")
    if led.quota_deferred.get("carl", 0) < 1:
        problems.append("carl's storm never overflowed his quota bucket")
    if scaler.scale_ups < 1:
        problems.append("autoscaler never grew the fleet under the storm")
    if scaler.scale_downs < 1:
        problems.append("autoscaler never shrank the fleet after the storm")
    if fleet.metrics.duplicate_completions:
        problems.append(f"{fleet.metrics.duplicate_completions} duplicate "
                        "completions")
    if problems:
        print("CONTROL-LEAK: " + "; ".join(problems))
        return 1
    snap = fleet.snapshot()
    print(f"control-check ok: {snap['fleet_completed']} completed "
          f"across {len(fleet.replicas)} replicas "
          f"(ups={scaler.scale_ups} downs={scaler.scale_downs}), "
          f"ledger balanced for {len(led.snapshot())} tenants "
          f"(carl quota_deferred={led.quota_deferred.get('carl', 0)}), "
          f"0 cross-tenant misses, 0 duplicate completions, 0 leaks")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI dispatch, importable so tools_tier1.sh runs the gate via
    ``python -c "...control.main(['check'])"`` — ``python -m`` would
    have runpy execute a second copy of this module next to the one
    the serving package already imported."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else "check"
    if cmd != "check":
        print(f"unknown command {cmd!r}; usage: "
              "python -c \"from paddle_tpu.serving.control import main; "
              "main(['check'])\"")
        return 2
    try:
        return _selfcheck()
    except PageLeakError as e:
        print(str(e))
        return 1
    except Exception as e:   # crash != findings: distinct exit code
        print(f"control check crashed: {e!r}")
        return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
