"""Block-paged KV cache for the serving engine.

The TPU-native analog of vLLM-style paged KV storage (Ragged Paged
Attention, arXiv 2604.15464): instead of one contiguous per-sequence
[max_len, H, D] buffer, K/V live in a preallocated pool of fixed-size
pages ``[num_pages, page_size, H, D]`` (one pool slice per layer).  Each
sequence owns an ordered list of page ids — its *page table* — and grows
one page at a time, so HBM is shared at page granularity across
concurrently-decoding requests with zero fragmentation beyond the last
partial page.

Split of responsibilities:

- **Device side** (pure functions, jit-safe): ``append_token`` /
  ``write_prompt`` scatter new K/V into pages, ``gather_kv`` linearizes a
  page table back into a contiguous view (the oracle/fallback path).
  These take page ids and offsets as *arrays*, so one jit specialization
  serves every allocation pattern.
- **Host side**: :class:`PagePool` is the free list.  Allocation is a
  scheduling decision (admission control, growth, preemption), so it
  stays in python — the device never sees the free list, only page
  tables.

Page 0 is **reserved as the null page**: masked writes (prompt padding,
inactive decode slots) are steered to it instead of being predicated
out, which keeps every scatter dense and shape-stable under jit.  No
live sequence is ever granted page 0.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.platform.enforce import enforce_that

NULL_PAGE = 0


@dataclass(frozen=True)
class PagedKVConfig:
    """Static geometry of the paged pool (one pool shared by all layers:
    page id ``p`` addresses layer ``l``'s slice ``k[l, p]`` for every l)."""

    num_layers: int
    num_heads: int
    head_dim: int
    page_size: int
    num_pages: int           # includes the reserved null page 0
    max_pages_per_seq: int   # page-table width (static decode grid bound)
    dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        enforce_that(self.num_pages >= 2,
                     "need at least one usable page beyond the null page",
                     context="serving")
        enforce_that(self.page_size >= 1 and self.max_pages_per_seq >= 1,
                     "page_size and max_pages_per_seq must be positive",
                     context="serving")

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # page 0 is the null page

    def kv_bytes(self) -> int:
        per = (self.num_layers * self.num_pages * self.page_size *
               self.num_heads * self.head_dim *
               jnp.dtype(self.dtype).itemsize)
        return 2 * per


class KVPages(NamedTuple):
    """The device-resident pool: ``k``/``v`` are
    [num_layers, num_pages, page_size, num_heads, head_dim]."""

    k: jax.Array
    v: jax.Array


def init_kv_pages(cfg: PagedKVConfig) -> KVPages:
    shape = (cfg.num_layers, cfg.num_pages, cfg.page_size, cfg.num_heads,
             cfg.head_dim)
    return KVPages(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def append_token(kv: KVPages, layer: int, k_new: jax.Array, v_new: jax.Array,
                 page_ids: jax.Array, offsets: jax.Array) -> KVPages:
    """Scatter one decode token per sequence into its current page.

    k_new/v_new: [B, H, D]; page_ids/offsets: [B] int32 (inactive slots
    pass page_ids == NULL_PAGE — duplicates on the null page are fine,
    nothing reads it).  Pure; returns the updated pool."""
    k = kv.k.at[layer, page_ids, offsets].set(k_new.astype(kv.k.dtype))
    v = kv.v.at[layer, page_ids, offsets].set(v_new.astype(kv.v.dtype))
    return KVPages(k, v)


def write_prompt(kv: KVPages, layer: int, k_seq: jax.Array, v_seq: jax.Array,
                 dest_pages: jax.Array, offsets: jax.Array) -> KVPages:
    """Scatter a whole (padded) prompt into pages at prefill.

    k_seq/v_seq: [T, H, D]; dest_pages/offsets: [T] int32, with padded
    positions (t >= true length) steered to NULL_PAGE by the caller."""
    k = kv.k.at[layer, dest_pages, offsets].set(k_seq.astype(kv.k.dtype))
    v = kv.v.at[layer, dest_pages, offsets].set(v_seq.astype(kv.v.dtype))
    return KVPages(k, v)


def gather_kv(kv: KVPages, layer: int, page_table: jax.Array):
    """Linearize page tables into contiguous K/V.

    page_table: [B, max_pages_per_seq] int32.  Returns (k, v) each
    [B, max_pages_per_seq * page_size, H, D] — positions beyond a
    sequence's length hold whatever the referenced pages contain (callers
    mask by length; this is the oracle/fallback read path)."""
    kl, vl = kv.k[layer], kv.v[layer]
    b, pm = page_table.shape
    _, page, h, d = kl.shape
    k = kl[page_table].reshape(b, pm * page, h, d)
    v = vl[page_table].reshape(b, pm * page, h, d)
    return k, v


@dataclass
class PagePool:
    """Host-side free list over page ids 1..num_pages-1 (0 is the null
    page).  Allocation is all-or-nothing so admission control can't
    partially strand a request."""

    num_pages: int
    _free: List[int] = field(default_factory=list)

    def __post_init__(self):
        enforce_that(self.num_pages >= 2, "pool needs >= 2 pages",
                     context="serving")
        # LIFO over ascending ids: recently-freed pages are re-granted
        # first, keeping the working set compact
        self._free = list(range(self.num_pages - 1, NULL_PAGE, -1))

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_usable(self) -> int:
        return self.num_pages - 1

    @property
    def num_in_use(self) -> int:
        return self.num_usable - self.num_free

    def occupancy(self) -> float:
        return self.num_in_use / max(1, self.num_usable)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Grant ``n`` pages, or None (and no change) if fewer are free."""
        if n < 0 or n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        return got

    def free(self, pages: List[int]) -> None:
        for p in pages:
            enforce_that(p != NULL_PAGE, "cannot free the null page",
                         context="serving")
            enforce_that(p not in self._free, f"double free of page {p}",
                         context="serving")
            self._free.append(p)
