"""Block-paged KV cache for the serving engine.

The TPU-native analog of vLLM-style paged KV storage (Ragged Paged
Attention, arXiv 2604.15464): instead of one contiguous per-sequence
[max_len, H, D] buffer, K/V live in a preallocated pool of fixed-size
pages ``[num_pages, page_size, H, D]`` (one pool slice per layer).  Each
sequence owns an ordered list of page ids — its *page table* — and grows
one page at a time, so HBM is shared at page granularity across
concurrently-decoding requests with zero fragmentation beyond the last
partial page.

Split of responsibilities:

- **Device side** (pure functions, jit-safe): ``append_token`` /
  ``write_prompt`` scatter new K/V into pages, ``gather_kv`` linearizes a
  page table back into a contiguous view (the oracle/fallback path).
  These take page ids and offsets as *arrays*, so one jit specialization
  serves every allocation pattern.
- **Host side**: :class:`PagePool` is the free list.  Allocation is a
  scheduling decision (admission control, growth, preemption), so it
  stays in python — the device never sees the free list, only page
  tables.

Page 0 is **reserved as the null page**: masked writes (prompt padding,
inactive decode slots) are steered to it instead of being predicated
out, which keeps every scatter dense and shape-stable under jit.  No
live sequence is ever granted page 0.

Automatic prefix caching (round 9): pages are **refcounted** — a page
shared by N sequences is freed only when the last holder unrefs it —
and a host-side :class:`PrefixCache` indexes *full* pages by chained
token-block hashes, so a new prompt can be split into
``cached_prefix_pages + tail`` and skip re-forwarding the prefix
entirely (arXiv 2603.09555: the cache, not the kernel, is where serving
latency is won).  Cached pages at refcount 0 stay out of the free list
as a reclaimable pool; LRU eviction returns them under pressure.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import (Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Set, Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.platform.enforce import enforce_that

NULL_PAGE = 0


_QMAX = 127.0        # symmetric int8 range; -128 is never produced
_KV_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
              "int8": jnp.int8}


def resolve_kv_dtype(name):
    """Map ``FLAGS.serving_kv_dtype`` (or an explicit dtype) to a jnp
    dtype.  Accepts the flag strings and dtype objects alike."""
    if isinstance(name, str):
        enforce_that(name in _KV_DTYPES,
                     f"serving_kv_dtype must be one of {sorted(_KV_DTYPES)},"
                     f" got {name!r}", context="serving")
        return _KV_DTYPES[name]
    return jnp.dtype(name)


@dataclass(frozen=True)
class PagedKVConfig:
    """Static geometry of the paged pool (one pool shared by all layers:
    page id ``p`` addresses layer ``l``'s slice ``k[l, p]`` for every l).

    ``num_kv_heads`` (None = ``num_heads``) is the GQA knob: the pool
    stores K/V for the KV heads only, and the ragged attention kernel
    packs each group of ``num_heads // num_kv_heads`` query heads
    against one K/V load.  ``dtype=jnp.int8`` turns on quantized pages:
    every write stores amax/127-scaled int8 values plus a per-token,
    per-kv-head f32 scale (see :func:`quantize_kv`), read back by
    dequantizing in-register — roughly quartering bytes per page.

    ``tp`` (default 1) is the tensor-parallel degree: the pool's KV-head
    dim shards over the ``model`` mesh axis, so each chip physically
    holds ``kv_heads / tp`` heads of every page — and every byte
    accounting here (:meth:`bytes_per_page`, :meth:`kv_bytes`, hence
    :func:`pages_for_budget`) is PER CHIP.  The int8 scale arrays shard
    with their KV heads, so they divide by ``tp`` too."""

    num_layers: int
    num_heads: int
    head_dim: int
    page_size: int
    num_pages: int           # includes the reserved null page 0
    max_pages_per_seq: int   # page-table width (static decode grid bound)
    dtype: jnp.dtype = jnp.float32
    num_kv_heads: Optional[int] = None   # None = MHA (== num_heads)
    tp: int = 1              # model-axis shards of the KV-head dim

    def __post_init__(self):
        enforce_that(self.num_pages >= 2,
                     "need at least one usable page beyond the null page",
                     context="serving")
        enforce_that(self.page_size >= 1 and self.max_pages_per_seq >= 1,
                     "page_size and max_pages_per_seq must be positive",
                     context="serving")
        enforce_that(self.num_heads % self.kv_heads == 0,
                     f"num_kv_heads ({self.kv_heads}) must divide "
                     f"num_heads ({self.num_heads})", context="serving")
        enforce_that(self.tp >= 1, "tp must be >= 1", context="serving")
        enforce_that(self.kv_heads % self.tp == 0,
                     f"tensor parallelism tp={self.tp} must divide "
                     f"num_kv_heads ({self.kv_heads}): the paged pool "
                     "shards whole KV heads over the model axis, so each "
                     "chip must own an integer number of them — pick a "
                     f"tp that divides {self.kv_heads}, or a model with "
                     "more KV heads", context="serving")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads if self.num_kv_heads else self.num_heads

    @property
    def q_heads_per_group(self) -> int:
        return self.num_heads // self.kv_heads

    @property
    def quantized(self) -> bool:
        return jnp.dtype(self.dtype) == jnp.int8

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # page 0 is the null page

    def bytes_per_page(self) -> int:
        """K + V bytes ONE page costs PER CHIP across all layers, scale
        arrays included — the unit the pool-byte budget is charged in.
        Under tensor parallelism (``tp > 1``) each chip holds only its
        ``kv_heads / tp`` shard of every page (scales ride with their
        heads), so the same per-chip budget buys ``tp`` x the pages —
        the per-chip capacity arithmetic the TP serving plan banks on."""
        heads_per_chip = self.kv_heads // self.tp
        per = (self.num_layers * self.page_size * heads_per_chip *
               self.head_dim * jnp.dtype(self.dtype).itemsize)
        if self.quantized:
            # per-token, per-kv-head f32 scales ride with the page
            per += self.num_layers * self.page_size * heads_per_chip * 4
        return 2 * per

    def kv_bytes(self) -> int:
        """Whole-pool bytes PER CHIP (the number HBM budgets care
        about; multiply by ``tp`` for the global pool)."""
        return self.num_pages * self.bytes_per_page()


def pages_for_budget(pool_bytes: int, num_layers: int, num_heads: int,
                     head_dim: int, page_size: int, dtype,
                     num_kv_heads: Optional[int] = None,
                     tp: int = 1) -> int:
    """Total ``num_pages`` (null page included) that fit in a PER-CHIP
    pool byte budget — the knob that makes int8 pages *mean* something:
    the same ``pool_bytes`` admits ~2x the pages of bf16 and ~4x of f32
    (minus the scale-array overhead), and under ``tp``-way tensor
    parallelism ``tp`` x the pages again (each chip stores 1/tp of every
    page's KV heads, scale arrays sharded with them).  The scheduler
    charges admission in pages, so capacity gains flow straight into
    admissible concurrency and prefix-cache headroom."""
    probe = PagedKVConfig(num_layers=num_layers, num_heads=num_heads,
                          head_dim=head_dim, page_size=page_size,
                          num_pages=2, max_pages_per_seq=1,
                          dtype=resolve_kv_dtype(dtype),
                          num_kv_heads=num_kv_heads, tp=int(tp))
    return max(2, int(pool_bytes) // probe.bytes_per_page())


class KVPages(NamedTuple):
    """The device-resident pool: ``k``/``v`` are
    [num_layers, num_pages, page_size, num_kv_heads, head_dim].  With
    int8 pages, ``k_scale``/``v_scale`` are the matching per-token,
    per-kv-head f32 scales [num_layers, num_pages, page_size,
    num_kv_heads]; None for float pools (the two layouts share every
    code path through ``is-None`` checks that resolve at trace time)."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_kv_pages(cfg: PagedKVConfig, mesh=None, axis: str = "model"
                  ) -> KVPages:
    """Allocate the pool.  With a ``mesh``, every leaf is placed with
    its KV-head dim sharded over ``axis`` (see :func:`kv_pool_specs`)
    so the ``[L, pages, page, H_kv/TP, D]`` per-chip layout exists from
    tick zero — the scatters/gathers of the serving step keep it there
    (batching-dim ops never move the head dim)."""
    shape = (cfg.num_layers, cfg.num_pages, cfg.page_size, cfg.kv_heads,
             cfg.head_dim)
    if cfg.quantized:
        kv = KVPages(jnp.zeros(shape, jnp.int8),
                     jnp.zeros(shape, jnp.int8),
                     jnp.zeros(shape[:-1], jnp.float32),
                     jnp.zeros(shape[:-1], jnp.float32))
    else:
        kv = KVPages(jnp.zeros(shape, cfg.dtype),
                     jnp.zeros(shape, cfg.dtype))
    if mesh is None:
        return kv
    sh = kv_pool_sharding(mesh, axis)
    return KVPages(
        jax.device_put(kv.k, sh), jax.device_put(kv.v, sh),
        None if kv.k_scale is None else jax.device_put(kv.k_scale, sh),
        None if kv.v_scale is None else jax.device_put(kv.v_scale, sh))


def kv_pool_specs(axis: str = "model") -> Tuple[Optional[str], ...]:
    """THE canonical pool layout, as one leading-dims PartitionSpec
    entry covering every :class:`KVPages` leaf: ``k``/``v`` are 5-d
    with the KV-head dim at position 3 and the scale arrays 4-d with it
    at position 3 too, so ``(None, None, None, axis)`` shards exactly
    the head dim of each (trailing dims replicated).  Single source of
    truth — the TP :class:`~paddle_tpu.analysis.retrace.SiteContract`s
    declare it for the pool argument/outputs, :func:`init_kv_pages`
    places with it, and the engine's per-tick output constraint
    re-asserts it — so the donated-in/aliased-out layout cannot drift
    between the three."""
    return (None, None, None, axis)


def kv_pool_sharding(mesh, axis: str = "model"):
    """:func:`kv_pool_specs` as a ``NamedSharding`` (one object serves
    every pool leaf: unspecified trailing dims are replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*kv_pool_specs(axis)))


def quantize_kv(x: jax.Array):
    """Symmetric per-token, per-head int8 quantization of K/V rows.

    x: [..., D] float.  Returns ``(q, scale)`` with ``q`` int8 [..., D]
    and ``scale`` f32 [...] such that ``q * scale`` reconstructs x to
    within one quantization step of amax/127.  All-zero rows quantize
    to (0, tiny) — dequant is exactly 0 either way, and the scale never
    divides by zero."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-20) / _QMAX
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Exact inverse read of :func:`quantize_kv`'s stored form — the ONE
    dequant rule the kernel, the gather fallback, and the parity oracle
    all share, so they can never disagree on what an int8 page means."""
    return q.astype(jnp.float32) * scale[..., None]


def append_token(kv: KVPages, layer: int, k_new: jax.Array, v_new: jax.Array,
                 page_ids: jax.Array, offsets: jax.Array) -> KVPages:
    """Scatter one K/V row per ragged batch row into its page.

    k_new/v_new: [B, H_kv, D]; page_ids/offsets: [B] int32 (inactive
    rows pass page_ids == NULL_PAGE — duplicates on the null page are
    fine, nothing reads it).  Quantized pools quantize on write (the
    scale lands at the same [layer, page, offset, head] address).
    Pure; returns the updated pool.

    This is also the MULTI-TOKEN scatter of the speculative verify
    step: a slot speculating ``k`` tokens contributes ``k+1``
    consecutive rows (positions ``cache_len .. cache_len+k``, possibly
    spanning a page boundary — see :func:`pages_spanned`), all written
    in the one dispatch.  Rollback after a partial acceptance is
    host-side: the rejected positions' K/V stays as finite junk beyond
    the new length, masked away by the ``token <= position`` attention
    inequality until the real tokens overwrite it, while the lookahead
    PAGES past the length return to the pool
    (``scheduler.rollback_pages`` — rollback-to-length)."""
    if kv.quantized:
        kq, ks = quantize_kv(k_new)
        vq, vs = quantize_kv(v_new)
        return KVPages(
            kv.k.at[layer, page_ids, offsets].set(kq),
            kv.v.at[layer, page_ids, offsets].set(vq),
            kv.k_scale.at[layer, page_ids, offsets].set(ks),
            kv.v_scale.at[layer, page_ids, offsets].set(vs))
    k = kv.k.at[layer, page_ids, offsets].set(k_new.astype(kv.k.dtype))
    v = kv.v.at[layer, page_ids, offsets].set(v_new.astype(kv.v.dtype))
    return KVPages(k, v)


def write_prompt(kv: KVPages, layer: int, k_seq: jax.Array, v_seq: jax.Array,
                 dest_pages: jax.Array, offsets: jax.Array) -> KVPages:
    """Scatter a whole (padded) prompt into pages at prefill.

    k_seq/v_seq: [T, H_kv, D]; dest_pages/offsets: [T] int32, with
    padded positions (t >= true length) steered to NULL_PAGE by the
    caller.  Same quantize-on-write rule as :func:`append_token` (the
    scatter shape is identical — one row per position)."""
    return append_token(kv, layer, k_seq, v_seq, dest_pages, offsets)


def pages_spanned(start: int, count: int, page_size: int) -> range:
    """Page-table INDICES a write of ``count`` consecutive token
    positions starting at ``start`` touches (empty for ``count <= 0``).
    The one arithmetic the engine's verify-time COW guard and its tests
    share: every spanned page that is cached or refcount-shared must be
    forked before a speculative branch may write into it, so a rejected
    branch can never dirty pages another holder reads."""
    if count <= 0:
        return range(0)
    return range(start // page_size, (start + count - 1) // page_size + 1)


def zero_pages(kv: KVPages, page_ids: jax.Array) -> KVPages:
    """Zero whole pages across every layer (failed-request scrub).

    page_ids: [n] int32.  A prompt that overflows to non-finite values
    leaves inf/NaN K/V in the pages it wrote; freed and re-granted,
    those stale values would poison the NEXT owner through masked
    attention reads (softmax weight 0 times inf is NaN).  Scrubbing on
    the failure path keeps the pool finite-by-construction.  (int8
    pools can't store non-finite VALUES, but their scale arrays can —
    both are scrubbed.)"""
    k = kv.k.at[:, page_ids].set(jnp.zeros((), kv.k.dtype))
    v = kv.v.at[:, page_ids].set(jnp.zeros((), kv.v.dtype))
    if kv.quantized:
        return KVPages(k, v, kv.k_scale.at[:, page_ids].set(0.0),
                       kv.v_scale.at[:, page_ids].set(0.0))
    return KVPages(k, v)


def fork_page(kv: KVPages, src: jax.Array, dst: jax.Array) -> KVPages:
    """Copy one page's K/V (and scales) across every layer — the
    copy-on-write fork.

    src/dst: scalar int32 page ids.  The forked page becomes a private
    replica of a shared cached page, so a sequence whose tail must write
    into the last shared page of its prefix does so without corrupting
    the other holders.  Pure; returns the updated pool."""
    k = kv.k.at[:, dst].set(kv.k[:, src])
    v = kv.v.at[:, dst].set(kv.v[:, src])
    if kv.quantized:
        return KVPages(k, v,
                       kv.k_scale.at[:, dst].set(kv.k_scale[:, src]),
                       kv.v_scale.at[:, dst].set(kv.v_scale[:, src]))
    return KVPages(k, v)


def read_pages(kv: KVPages, page_ids: Sequence[int]):
    """Pull whole pages to the host as STORED values — the export half
    of the page-migration plane (``serving/migrate.py``).

    page_ids: n page ids.  Returns ``(k, v, k_scale, v_scale)`` numpy
    arrays, k/v shaped [L, n, page, H_kv, D] in the pool dtype and the
    scales [L, n, page, H_kv] f32 (None for float pools).  int8 pages
    are NOT dequantized: migration moves the quantized bytes plus their
    scales verbatim, so the destination reads bit-identical K/V and the
    transfer costs ~1/4 the f32 bytes."""
    import numpy as np

    ids = jnp.asarray(list(page_ids), jnp.int32)
    k = np.asarray(kv.k[:, ids])
    v = np.asarray(kv.v[:, ids])
    if kv.quantized:
        return (k, v, np.asarray(kv.k_scale[:, ids]),
                np.asarray(kv.v_scale[:, ids]))
    return k, v, None, None


def write_pages(kv: KVPages, page_ids: jax.Array, k: jax.Array,
                v: jax.Array, k_scale: Optional[jax.Array] = None,
                v_scale: Optional[jax.Array] = None) -> KVPages:
    """Splice whole pages into the pool — the import half of the
    migration plane, shape-compatible with :func:`read_pages` output.

    page_ids: [n] int32 destination ids (pad rows with NULL_PAGE and
    zero payload: nothing reads the null page, so padded writes keep
    the jitted import ladder shape-stable).  Stored values go in
    verbatim — no re-quantization — so an exported int8 page arrives
    bit-identical, scales included.  Pure; returns the updated pool."""
    kk = kv.k.at[:, page_ids].set(k.astype(kv.k.dtype))
    vv = kv.v.at[:, page_ids].set(v.astype(kv.v.dtype))
    if kv.quantized:
        return KVPages(kk, vv,
                       kv.k_scale.at[:, page_ids].set(
                           k_scale.astype(jnp.float32)),
                       kv.v_scale.at[:, page_ids].set(
                           v_scale.astype(jnp.float32)))
    return KVPages(kk, vv)


def gather_kv(kv: KVPages, layer: int, page_table: jax.Array):
    """Linearize page tables into contiguous K/V.

    page_table: [B, max_pages_per_seq] int32.  Returns (k, v) each
    [B, max_pages_per_seq * page_size, H_kv, D] — positions beyond a
    sequence's length hold whatever the referenced pages contain
    (callers mask by length; this is the oracle/fallback read path).
    Quantized pools are dequantized here with the shared
    :func:`dequantize_kv` rule, so the fallback reads the SAME stored
    values the kernel does and parity stays pinned."""
    kl, vl = kv.k[layer], kv.v[layer]
    b, pm = page_table.shape
    _, page, h, d = kl.shape
    k = kl[page_table]
    v = vl[page_table]
    if kv.quantized:
        k = dequantize_kv(k, kv.k_scale[layer][page_table])
        v = dequantize_kv(v, kv.v_scale[layer][page_table])
    return (k.reshape(b, pm * page, h, d), v.reshape(b, pm * page, h, d))


@dataclass
class PagePool:
    """Host-side refcounted allocator over page ids 1..num_pages-1 (0 is
    the null page).  Allocation is all-or-nothing so admission control
    can't partially strand a request.

    Every non-free page carries a refcount: ``alloc`` grants pages at
    refcount 1, ``ref`` adds a holder (prefix sharing), ``free`` drops
    one — the page returns to the free list only at refcount 0, and not
    even then if a :class:`PrefixCache` has registered it (``mark_cached``):
    cached pages at refcount 0 are *reclaimable*, parked for future
    prefix hits until ``release_cached`` (LRU eviction) returns them.

    The free list is LIFO over ascending ids (recently-freed pages are
    re-granted first, keeping the working set compact) and mirrored by a
    set, so the double-free guard is O(1) instead of an O(pages) list
    scan on every free."""

    num_pages: int
    # obs hook: the engine binds its (enabled) tracer here so page
    # custody changes land on the request timeline; None = tracing off,
    # one is-None check per pool call (never per page)
    tracer: Optional[object] = field(default=None, repr=False, compare=False)
    _free: List[int] = field(default_factory=list)
    _free_set: Set[int] = field(default_factory=set)
    _refs: Dict[int, int] = field(default_factory=dict)
    _cached: Set[int] = field(default_factory=set)

    def __post_init__(self):
        enforce_that(self.num_pages >= 2, "pool needs >= 2 pages",
                     context="serving")
        self._free = list(range(self.num_pages - 1, NULL_PAGE, -1))
        self._free_set = set(self._free)
        self._refs = {}
        self._cached = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_usable(self) -> int:
        return self.num_pages - 1

    @property
    def num_in_use(self) -> int:
        """Pages not on the free list: live (refcount > 0) plus cached
        pages parked at refcount 0."""
        return len(self._refs)

    @property
    def num_live(self) -> int:
        """Pages held by at least one sequence (or the fault plan)."""
        return sum(1 for c in self._refs.values() if c > 0)

    @property
    def num_cached(self) -> int:
        """Pages registered by a PrefixCache (any refcount)."""
        return len(self._cached)

    @property
    def num_reclaimable(self) -> int:
        """Cached pages at refcount 0 — evictable under pressure."""
        return sum(1 for p in self._cached if self._refs[p] == 0)

    @property
    def total_refs(self) -> int:
        """Sum of all refcounts — must equal the holders' page-list
        lengths summed (the REF-LEAK conservation invariant)."""
        return sum(self._refs.values())

    def occupancy(self) -> float:
        return self.num_in_use / max(1, self.num_usable)

    def refcount(self, p: int) -> int:
        return self._refs.get(p, 0)

    def is_cached(self, p: int) -> bool:
        return p in self._cached

    def alloc(self, n: int) -> Optional[List[int]]:
        """Grant ``n`` pages at refcount 1 each, or None (and no change)
        if fewer are free.  Reclaimable cached pages are NOT granted
        here — evict them first (``PrefixCache.evict``)."""
        if n < 0 or n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        for p in got:
            self._free_set.discard(p)
            self._refs[p] = 1
        if self.tracer is not None and got:
            self.tracer.instant("page_alloc", cat="pages", n=len(got),
                                pages=tuple(got))
        return got

    def ref(self, pages: Sequence[int]) -> None:
        """Add one holder to each page (a prefix-cache hit sharing them
        with a new sequence).  Pages must be in use or cached."""
        for p in pages:
            enforce_that(p in self._refs, f"ref of free page {p}",
                         context="serving")
            self._refs[p] += 1
        if self.tracer is not None and pages:
            self.tracer.instant("page_ref", cat="pages", n=len(pages),
                                pages=tuple(pages))

    def free(self, pages: Sequence[int]) -> None:
        """Drop one holder per page (unref).  A page reaches the free
        list only at refcount 0, and stays parked (reclaimable) instead
        if a PrefixCache holds it."""
        for p in pages:
            enforce_that(p != NULL_PAGE, "cannot free the null page",
                         context="serving")
            enforce_that(p not in self._free_set,
                         f"double free of page {p}", context="serving")
            enforce_that(self._refs.get(p, 0) > 0,
                         f"free of unreferenced page {p}", context="serving")
            self._refs[p] -= 1
            if self._refs[p] == 0 and p not in self._cached:
                del self._refs[p]
                self._free.append(p)
                self._free_set.add(p)
        if self.tracer is not None and pages:
            self.tracer.instant("page_free", cat="pages", n=len(pages),
                                pages=tuple(pages))

    def mark_cached(self, p: int) -> None:
        """Register a (non-free) page as prefix-cache-held: at refcount
        0 it parks as reclaimable instead of returning to the free
        list."""
        enforce_that(p in self._refs, f"cannot cache free page {p}",
                     context="serving")
        self._cached.add(p)

    def unmark_cached(self, p: int) -> None:
        """Withdraw a page's cache registration (failed-prefill
        rollback).  A page already parked at refcount 0 is freed on the
        spot — nothing holds it and nothing can hit it anymore."""
        if p not in self._cached:
            return
        self._cached.discard(p)
        if self._refs.get(p, 0) == 0:
            del self._refs[p]
            self._free.append(p)
            self._free_set.add(p)

    def release_cached(self, p: int) -> None:
        """Eviction: return a refcount-0 cached page to the free list."""
        enforce_that(p in self._cached, f"page {p} is not cached",
                     context="serving")
        enforce_that(self._refs.get(p, 0) == 0,
                     f"evicting page {p} with live holders",
                     context="serving")
        self._cached.discard(p)
        del self._refs[p]
        self._free.append(p)
        self._free_set.add(p)
        if self.tracer is not None:
            self.tracer.instant("page_evict", cat="pages", page=p)


# ---------------------------------------------------------------------------
# Automatic prefix caching: host-side index over full pages
# ---------------------------------------------------------------------------

_CHAIN_SEED = 0x9E3779B9   # any fixed non-zero start for the hash chain


def _chain_hash(prev: int, block: Tuple[int, ...]) -> int:
    """Default chained block hash: each full page's key commits to every
    token before it via the previous link.  Python's tuple hash over
    ints is deterministic within and across processes (int hashing is
    not seed-randomized), which is all the index needs — collisions are
    verified away by token comparison, never trusted."""
    return hash((prev, block))


def prefix_chain_hashes(tokens: Sequence[int], page_size: int,
                        hash_fn: Optional[Callable[[int, Tuple[int, ...]],
                                                   int]] = None) -> List[int]:
    """The :class:`PrefixCache` key chain of ``tokens``: one chained
    hash per FULL page block, ``h_j = hash(h_{j-1}, block_j)`` from
    :data:`_CHAIN_SEED` — exactly the keys ``lookup``/``insert`` walk.
    Exposed so the fleet router (``serving/fleet.py``) routes by the
    SAME function the cache indexes with: two prompts that would share
    cached pages produce a common chain prefix by construction, so
    affinity routing and cache hits can never disagree on what "same
    prefix" means."""
    hf = hash_fn or _chain_hash
    page = int(page_size)
    h = _CHAIN_SEED
    out: List[int] = []
    for j in range(len(tokens) // page):
        h = hf(h, tuple(tokens[j * page:(j + 1) * page]))
        out.append(h)
    return out


@dataclass
class _CacheEntry:
    page: int                 # the page holding this block's K/V
    tokens: Tuple[int, ...]   # the block itself (collision verification)
    prev: int                 # parent link hash (chain verification)
    tenant: Optional[str] = None   # who prefilled it (host-tier billing)


class PrefixCache:
    """Hash-chained index over *full* KV pages for automatic prefix
    caching.

    Key design points:

    - only FULL pages are indexed: a partial page is still being
      appended to by its owner, so it can never be safely shared;
    - keys are chained (``h_j = hash(h_{j-1}, block_j)``), so a hit on
      page j implies the whole prefix up to j matched — the index acts
      as a radix tree flattened into a hash map;
    - every hit is VERIFIED by comparing the stored block tokens and
      parent link, so a hash collision (including fault-injected
      degenerate hashes) degrades to a miss, never to corruption;
    - entries are LRU-ordered; :meth:`evict` frees refcount-0 pages
      oldest-first under pool pressure.  Evicting a mid-chain entry
      orphans its descendants (unreachable, evicted later by the same
      LRU sweep) — safe, just conservative.

    The cache does NOT hold refcounts of its own: a cached page with no
    sequence holders parks at refcount 0 inside the :class:`PagePool`
    (reclaimable) rather than returning to the free list."""

    def __init__(self, pool: PagePool, page_size: int,
                 hash_fn: Optional[Callable[[int, Tuple[int, ...]], int]]
                 = None):
        enforce_that(page_size >= 1, "page_size must be positive",
                     context="serving")
        self.pool = pool
        self.page_size = int(page_size)
        self._hash = hash_fn or _chain_hash
        self.tracer = None     # obs hook, bound by the engine (see pool)
        self._index: "OrderedDict[int, _CacheEntry]" = OrderedDict()
        self.hits = 0          # lookups that matched >= 1 page (healthz)
        self.misses = 0        # lookups that matched none (healthz)
        self.evictions = 0     # pages evicted (LRU or storm)
        # hierarchical spill (round 21): when the engine binds a
        # HostPageTier plus a page reader (device pages -> stored host
        # bytes), eviction DEMOTES instead of destroying — the victim's
        # K/V is staged into the host tier before the device page
        # returns to the free list
        self.host_tier: Optional["HostPageTier"] = None
        self.page_reader: Optional[Callable[[Sequence[int]], tuple]] = None

    def __len__(self) -> int:
        return len(self._index)

    def chain_keys(self, tokens: Sequence[int]) -> List[int]:
        """Every full block's chained key under THIS cache's hash
        (fault-injected overrides included) — what the host-tier
        swap-in walks to continue a lookup past the device index."""
        return prefix_chain_hashes(tokens, self.page_size, self._hash)

    def lookup(self, tokens: Sequence[int],
               touch: bool = False) -> Tuple[List[int], int]:
        """Longest verified cached prefix of ``tokens`` in full pages.

        Returns ``(pages, hit_len)`` with ``hit_len = len(pages) *
        page_size``.  Does NOT take references — the caller refs the
        pages it actually stitches (all-or-nothing with its allocation),
        so a failed admission leaves no state behind.

        ``touch=False`` (the default) is a PURE read: no LRU reorder, no
        hit/miss counting.  The scheduler probes every admission attempt
        — a head-of-line request blocked on pages re-probes every tick,
        and counting those would inflate the stats and churn eviction
        order for zero actual stitches.  It re-calls with ``touch=True``
        exactly once, when the admission commits."""
        page = self.page_size
        pages: List[int] = []
        h = _CHAIN_SEED
        for j in range(len(tokens) // page):
            block = tuple(tokens[j * page:(j + 1) * page])
            key = self._hash(h, block)
            e = self._index.get(key)
            if e is None or e.tokens != block or e.prev != h:
                break          # miss or verified-away collision
            if touch:
                self._index.move_to_end(key)
            pages.append(e.page)
            h = key
        if touch:
            if pages:
                self.hits += 1
            else:
                self.misses += 1
        return pages, len(pages) * page

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               upto: int, from_block: int = 0,
               prev_hash: Optional[int] = None,
               tenant: Optional[str] = None) -> Tuple[int, int]:
        """Index the full pages covering ``tokens[:upto]`` (page j of
        the sequence lives in ``pages[j]``).  Idempotent — re-inserting
        a chunk already indexed is a no-op, and an existing entry always
        wins (a concurrent identical prefill keeps its private copy).

        ``from_block``/``prev_hash`` resume the hash chain at a block
        boundary, so a chunked prefill indexes each chunk in O(chunk)
        instead of re-hashing the whole prefix per chunk (quadratic in
        prompt length on the tick hot path).  Returns ``(chain_hash,
        blocks_done)`` for the caller to pass back on its next chunk."""
        page = self.page_size
        h = _CHAIN_SEED if prev_hash is None else prev_hash
        nblocks = min(upto, len(tokens)) // page
        for j in range(from_block, nblocks):
            block = tuple(tokens[j * page:(j + 1) * page])
            key = self._hash(h, block)
            e = self._index.get(key)
            if e is None:
                self._index[key] = _CacheEntry(page=int(pages[j]),
                                               tokens=block, prev=h,
                                               tenant=tenant)
                self.pool.mark_cached(int(pages[j]))
            h = key
        return h, max(from_block, nblocks)

    def forget(self, pages: Sequence[int]) -> int:
        """Drop every index entry whose page is in ``pages`` (they stay
        with their holder; once unref'd they go straight to the free
        list instead of parking).  A prefill that fails the finite-
        logits guard calls this so its (possibly NaN-laden) K/V can
        never be stitched into a later request — without it, one
        overflowing prompt would poison every future request sharing
        the prefix."""
        ps = {int(p) for p in pages}
        dropped = 0
        for key in [k for k, e in self._index.items() if e.page in ps]:
            e = self._index.pop(key)
            self.pool.unmark_cached(e.page)
            dropped += 1
        return dropped

    def evict(self, n: int) -> int:
        """Evict up to ``n`` refcount-0 cached pages, LRU first; returns
        how many were actually freed.  Pages with live holders are
        skipped (their entries stay — they are still hittable)."""
        if n <= 0:
            return 0
        freed = 0
        spill = (self.host_tier is not None and
                 self.page_reader is not None)
        for key in list(self._index):
            if freed >= n:
                break
            e = self._index[key]
            if self.pool.refcount(e.page) == 0:
                if spill:
                    # demotion, not destruction: stage the victim's
                    # stored bytes into the host tier before the device
                    # page is reclaimed (depth-one writer — this commits
                    # the PREVIOUS pending spill, stages this one)
                    payload = self.page_reader([e.page])
                    self.host_tier.spill(key, e.prev, e.tokens, payload,
                                         tenant=e.tenant)
                del self._index[key]
                self.pool.release_cached(e.page)
                self.evictions += 1
                freed += 1
        if self.tracer is not None and freed:
            self.tracer.instant("cache_evict", cat="pages", n=freed)
        return freed

    def flush(self) -> int:
        """Evict every reclaimable page (the fault plan's eviction
        storm; also useful for tests).  Entries with live holders
        survive."""
        return self.evict(len(self._index))


# ---------------------------------------------------------------------------
# Hierarchical host tier (round 21): spilled pages live in host RAM,
# checksummed, until a prefix hit swaps them back in
# ---------------------------------------------------------------------------


def page_checksum(k, v, k_scale=None, v_scale=None) -> int:
    """CRC32 chained over a page's STORED bytes plus its scale arrays —
    the one integrity rule the spill writer, the swap-in verifier, and
    the warm-restart adopter share.  Computed over the bytes the writer
    INTENDED to store, so a torn commit or a flipped bit can never
    verify."""
    c = zlib.crc32(np.ascontiguousarray(k).tobytes())
    c = zlib.crc32(np.ascontiguousarray(v).tobytes(), c)
    if k_scale is not None:
        c = zlib.crc32(np.ascontiguousarray(k_scale).tobytes(), c)
        c = zlib.crc32(np.ascontiguousarray(v_scale).tobytes(), c)
    return c


@dataclass
class _HostPage:
    """One spilled page: the prefix-cache chain identity (key / prev /
    tokens — so the host index IS the same radix chain, resumable after
    the device entry is gone) plus the stored payload and its checksum."""

    key: int
    prev: int
    tokens: Tuple[int, ...]
    k: "np.ndarray"                    # [L, 1, page, H_kv, D] stored dtype
    v: "np.ndarray"
    k_scale: Optional["np.ndarray"]    # [L, 1, page, H_kv] f32, or None
    v_scale: Optional["np.ndarray"]
    checksum: int
    nbytes: int
    seq: int                           # spill sequence (fault addressing)
    tenant: Optional[str] = None


class HostPageTier:
    """The host-RAM spill tier under the device :class:`PagePool`.

    Evicted RECLAIMABLE pages demote here instead of being destroyed
    (``PrefixCache.evict`` stages them), keyed by the SAME chained block
    hash the device index uses — so a later lookup that runs off the end
    of its device hits can continue the walk in host memory and swap the
    continuation back in, verified, instead of re-prefilling it.

    Write path — the depth-one pipelined writer from
    ``resilience/checkpointer.py``, tick-deterministic (no threads, no
    wall clock): ``spill`` first commits the previously staged page
    (wait-out-previous), then stages the new one; the engine's per-tick
    ``pump`` commits the staged page unless a fault plan declares a
    slow-host-I/O window for that tick (counted as
    ``spill_stall_ticks``); ``flush`` commits unconditionally (drain,
    handoff).  Fault hooks mutate the payload AT COMMIT — after the
    checksum was taken over the intended bytes — so a torn write or a
    seeded bit flip is exactly what the verifier later catches.

    Capacity is a byte budget.  With ``dtype='int8'`` float payloads are
    transcoded to int8 + per-token scales on spill (the "engine owns the
    memory format" lever: the host tier holds ~4x the pages of the f32
    device pool for the same bytes, at quantization fidelity); with the
    default ``'stored'`` the device bytes are kept verbatim, so swap-in
    is bit-identical.  When the budget is exceeded the tier LRU-drops —
    the third rung of the degradation ladder, after device eviction and
    before shed/preempt.

    Conservation (``HOSTTIER-LEAK``): every page that ever entered the
    tier ends in exactly one state —

        spills + adopted == resident + swap_ins + dropped + corrupt
                            + handed_off + pending

    checked by :meth:`check`, which the engine folds into
    ``check_page_conservation`` (pages conserve across device, host,
    and dropped)."""

    def __init__(self, capacity_bytes: int, dtype: str = "stored",
                 faults=None, tracer=None):
        enforce_that(dtype in ("stored", "int8"),
                     "serving_host_kv_dtype must be 'stored' or 'int8', "
                     f"got {dtype!r}", context="serving")
        self.capacity_bytes = int(capacity_bytes)
        self.dtype = dtype
        self.faults = faults
        self.tracer = tracer
        # single-threaded by design: the engine tick loop is the only
        # writer (spill/pump/flush/swap-in), and warm-restart adopt()
        # runs before the successor engine starts ticking — the tier
        # needs no lock, just confinement to its owning engine
        # guarded_by(serialized: engine tick loop owns the tier)
        self._index: "OrderedDict[int, _HostPage]" = OrderedDict()
        # guarded_by(serialized: engine tick loop owns the tier)
        self._pending: Optional[_HostPage] = None
        # guarded_by(serialized: engine tick loop owns the tier)
        self._seq = 0
        # guarded_by(serialized: engine tick loop owns the tier)
        self.resident_bytes = 0
        # guarded_by(serialized: engine tick loop owns the tier)
        self.resident_by_tenant: Dict[str, int] = {}
        # ledger counters (see class docstring for the invariant)
        self.spills = 0            # pages ever staged (swap_outs gauge)
        self.swap_ins = 0          # verified pages promoted back to device
        self.dropped = 0           # LRU-dropped / forgotten / displaced
        self.corrupt = 0           # checksum failures (NEVER served)
        self.handed_off = 0        # adopted away by a successor tier
        self.adopted = 0           # records taken FROM predecessors
        self.restored = 0          # of those, verified + resident here
        self.spill_stall_ticks = 0  # pump ticks lost to slow host I/O

    def __len__(self) -> int:
        return len(self._index)

    # ---- write path (depth-one pipelined) --------------------------------

    def spill(self, key: int, prev: int, tokens: Sequence[int], payload,
              tenant: Optional[str] = None) -> None:
        """Stage one evicted page (``payload`` is ``read_pages`` output
        for a single page).  Commits any previously staged page first —
        at most one spill is ever in flight, and the tick path never
        waits on more than that one commit."""
        if self._pending is not None:
            self._commit(self._pending)
            self._pending = None
        k, v, ks, vs = payload
        k = np.array(k)
        v = np.array(v)
        ks = None if ks is None else np.array(ks, np.float32)
        vs = None if vs is None else np.array(vs, np.float32)
        if self.dtype == "int8" and ks is None:
            # transcode-on-spill: host holds int8 + f32 scales (~4x the
            # f32 pages per byte); swap-in dequantizes back
            kq, ks = quantize_kv(jnp.asarray(k, jnp.float32))
            vq, vs = quantize_kv(jnp.asarray(v, jnp.float32))
            k, v = np.array(kq), np.array(vq)
            ks, vs = np.array(ks, np.float32), np.array(vs, np.float32)
        nbytes = k.nbytes + v.nbytes
        if ks is not None:
            nbytes += ks.nbytes + vs.nbytes
        seq = self._seq       # 0-based, like the migration drop schedule:
        self._seq += 1        # the fault plan's Nth spill is seq N
        self.spills += 1
        self._pending = _HostPage(
            key=int(key), prev=int(prev), tokens=tuple(tokens),
            k=k, v=v, k_scale=ks, v_scale=vs,
            checksum=page_checksum(k, v, ks, vs),
            nbytes=int(nbytes), seq=seq, tenant=tenant)
        if self.tracer is not None:
            self.tracer.instant("host_spill", cat="pages", seq=seq)

    def pump(self, tick: int) -> int:
        """Per-tick writer advance: commit the staged page, unless the
        fault plan has host I/O stalled this tick (the spill then rides
        along until the window ends — decode never waits on it)."""
        if self._pending is None:
            return 0
        if self.faults is not None and self.faults.host_io_stalled(tick):
            self.spill_stall_ticks += 1
            return 0
        self._commit(self._pending)
        self._pending = None
        return 1

    def flush(self) -> None:
        """Commit unconditionally (drain / handoff barrier)."""
        if self._pending is not None:
            self._commit(self._pending)
            self._pending = None

    def _commit(self, rec: _HostPage) -> None:
        f = self.faults
        if f is not None:
            if f.spill_is_torn(rec.seq):
                # torn commit: the tail half of V never lands.  The
                # checksum was taken over the intended bytes at stage
                # time, so verification catches this as corruption.
                flat = rec.v.reshape(-1).view(np.uint8)
                flat[flat.size // 2:] = 0
            off = f.spill_bitflip_offset(rec.seq, rec.k.nbytes)
            if off is not None:
                flat = rec.k.reshape(-1).view(np.uint8)
                flat[off % flat.size] ^= 0x40
        self._insert(rec)

    def _insert(self, rec: _HostPage) -> bool:
        if rec.key in self._index:
            # existing entry wins (same idempotence rule as the device
            # index) — the duplicate is accounted as dropped
            self.dropped += 1
            return False
        if rec.nbytes > self.capacity_bytes:
            self.dropped += 1
            return False
        while self.resident_bytes + rec.nbytes > self.capacity_bytes:
            # ladder rung 3: host tier full -> LRU-drop host pages
            self._pop_lru()
        self._index[rec.key] = rec
        self.resident_bytes += rec.nbytes
        if rec.tenant is not None:
            self.resident_by_tenant[rec.tenant] = \
                self.resident_by_tenant.get(rec.tenant, 0) + 1
        return True

    def _pop(self, key: int) -> _HostPage:
        rec = self._index.pop(key)
        self.resident_bytes -= rec.nbytes
        if rec.tenant is not None:
            n = self.resident_by_tenant.get(rec.tenant, 0) - 1
            if n > 0:
                self.resident_by_tenant[rec.tenant] = n
            else:
                self.resident_by_tenant.pop(rec.tenant, None)
        return rec

    def _pop_lru(self) -> None:
        key = next(iter(self._index))
        self._pop(key)
        self.dropped += 1
        if self.tracer is not None:
            self.tracer.instant("host_drop", cat="pages", key=key)

    # ---- read path (verified swap-in) ------------------------------------

    def peek(self, key: int, prev: int,
             block: Sequence[int]) -> Optional[_HostPage]:
        """Pure probe: the record for ``key`` if present AND its chain
        identity matches (same token/parent verification as the device
        index — a collision is a miss).  No checksum work, no removal;
        the scheduler uses this to size a swap-in before charging it."""
        rec = self._index.get(key)
        if rec is None or rec.prev != int(prev) or \
                rec.tokens != tuple(block):
            return None
        return rec

    def take_verified(self, key: int, prev: int,
                      block: Sequence[int]) -> Optional[_HostPage]:
        """Remove-and-return the record for ``key`` iff its chain
        identity matches AND its checksum verifies.  A mismatch pops the
        record, counts ``corrupt`` (the HOSTTIER-CORRUPT counter), and
        returns None — corruption degrades to a miss, never to a
        wrong-KV hit."""
        if self.peek(key, prev, block) is None:
            return None
        rec = self._pop(int(key))
        if page_checksum(rec.k, rec.v, rec.k_scale,
                         rec.v_scale) != rec.checksum:
            self.corrupt += 1
            if self.tracer is not None:
                self.tracer.instant("HOSTTIER-CORRUPT", cat="pages",
                                    key=int(key))
            return None
        self.swap_ins += 1
        return rec

    def forget(self, keys: Sequence[int]) -> int:
        """Drop records (and any matching staged spill) by chain key —
        the no-double-adopt rule: when a chain migrates to another
        replica, the source's host copies are forgotten so the pages
        can never be re-adopted from two places."""
        n = 0
        for key in list(keys):
            key = int(key)
            if self._pending is not None and self._pending.key == key:
                self._pending = None
                self.dropped += 1
                n += 1
            if key in self._index:
                self._pop(key)
                self.dropped += 1
                n += 1
        return n

    # ---- crash-warm restart ----------------------------------------------

    def adopt(self, other: "HostPageTier") -> int:
        """Take every record from a predecessor tier (warm restart: the
        host tier outlives the engine).  Each record is re-verified
        against its checksum before becoming hittable here — a record
        corrupted while orphaned counts ``corrupt`` on THIS tier and is
        never served.  The source ledger stays balanced via
        ``handed_off``.  Returns how many records were restored."""
        other.flush()
        restored = 0
        # reaching into the predecessor's confined state is the POINT
        # of adopt(): the old engine is already stopped at handoff, so
        # its tier has no concurrent owner left
        # lint: allow(guarded-by)
        for key in list(other._index):
            rec = other._pop(key)
            other.handed_off += 1
            self.adopted += 1
            if page_checksum(rec.k, rec.v, rec.k_scale,
                             rec.v_scale) != rec.checksum:
                self.corrupt += 1
                if self.tracer is not None:
                    self.tracer.instant("HOSTTIER-CORRUPT", cat="pages",
                                        key=int(key))
                continue
            if self._insert(rec):
                self.restored += 1
                restored += 1
        return restored

    # ---- conservation + scrape -------------------------------------------

    def check(self) -> None:
        """The HOSTTIER-LEAK invariant (valid at any tick, not just at
        drain): every page that entered the tier is in exactly one of
        resident / swapped-in / dropped / corrupt / handed-off /
        pending, and resident bytes match the index under the budget."""
        from paddle_tpu.serving.faults import PageLeakError

        pend = 1 if self._pending is not None else 0
        lhs = self.spills + self.adopted
        rhs = (len(self._index) + self.swap_ins + self.dropped +
               self.corrupt + self.handed_off + pend)
        if lhs != rhs:
            raise PageLeakError(
                f"HOSTTIER-LEAK: spills({self.spills}) + "
                f"adopted({self.adopted}) != resident({len(self._index)})"
                f" + swap_ins({self.swap_ins}) + dropped({self.dropped})"
                f" + corrupt({self.corrupt}) + "
                f"handed_off({self.handed_off}) + pending({pend})")
        nb = sum(r.nbytes for r in self._index.values())
        if nb != self.resident_bytes or nb > self.capacity_bytes:
            raise PageLeakError(
                f"HOSTTIER-LEAK: resident bytes ledger {self.resident_bytes}"
                f" vs actual {nb} (capacity {self.capacity_bytes})")

    def snapshot(self) -> Dict[str, int]:
        """Host-tier gauges, merged into the engine's scrape surface."""
        return {
            "pages_host": len(self._index),
            "host_swap_ins": self.swap_ins,
            "host_swap_outs": self.spills,
            "host_corrupt": self.corrupt,
            "host_dropped": self.dropped,
            "host_restored": self.restored,
            "host_resident_bytes": self.resident_bytes,
            "spill_stall_ticks": self.spill_stall_ticks,
        }


# ---------------------------------------------------------------------------
# standalone gate: `python -m paddle_tpu.serving.kv_cache check`
# ---------------------------------------------------------------------------


def _selfcheck() -> int:
    """Replay a seeded hierarchical-tier trace — the tier-1 ladder's
    HOSTTIER gate (tools_tier1.sh exit 13).  Two phases:

    1. single engine: a clean spill/swap-in round-trip must be
       token-identical to a cold re-prefill, and an injected torn spill
       plus a seeded bit-flip must BOTH be caught by the checksum at
       swap-in (degrading to a miss) — a corrupt page served would show
       up as a parity break;
    2. small fleet: kill a replica whose host tier holds spilled pages,
       ``restart_replica`` it, and the warm successor must re-adopt
       >= 1 verified page and serve the same prompt token-identically
       with zero duplicate completions.

    Returns 0 (clean) or 1 (findings); a crash propagates as 2."""
    import jax
    import numpy as np

    from paddle_tpu.serving.engine import DecoderLM, ServingEngine
    from paddle_tpu.serving.faults import (FaultPlan, FleetFaultPlan,
                                           ManualClock)

    model = DecoderLM(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    prompt = rng.randint(2, 64, size=16).tolist()   # 4 full pages
    problems = []

    def mk_engine(**faults_kw):
        plan = FaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                         **faults_kw)
        return ServingEngine(model, params, eos_id=1, page_size=4,
                             num_pages=16, max_pages_per_seq=8,
                             max_slots=2, buckets=(8, 16), faults=plan,
                             host_tier_bytes=1 << 20, swap_in_budget=4)

    def roundtrip(eng):
        """cold serve -> flush (spill) -> warm serve; returns (cold,
        warm) token lists from the SAME engine (rids are globally
        numbered, so cross-engine comparison must go by order)."""
        r1 = eng.submit(list(prompt), max_tokens=6)
        eng.run()
        cold = eng.result(r1)
        eng.cache.flush()
        r2 = eng.submit(list(prompt), max_tokens=6)
        eng.run()
        return cold, eng.result(r2)

    # phase 1a: clean round trip — the tier must actually serve
    eng = mk_engine()
    cold, warm = roundtrip(eng)
    snap = eng.host_tier.snapshot()
    if warm != cold:
        problems.append(f"clean swap-in parity break: {warm} != {cold}")
    if snap["host_swap_ins"] < 1 or eng._host_hits < 1:
        problems.append(f"clean round trip never hit the host tier: {snap}")
    clean_swapins = snap["host_swap_ins"]
    eng.check_page_conservation()

    # phase 1b/1c: torn spill, then seeded bit-flip — each must be
    # caught at swap-in (miss + HOSTTIER-CORRUPT), never served
    for kw, name in (({"torn_spill_at": {0}}, "torn"),
                     ({"bitflip_spill_at": {0}}, "bitflip")):
        eng = mk_engine(**kw)
        cold, warm = roundtrip(eng)
        snap = eng.host_tier.snapshot()
        if warm != cold:
            problems.append(f"{name}: corrupt page SERVED "
                            f"(parity break {warm} != {cold})")
        if snap["host_corrupt"] < 1:
            problems.append(f"{name}: checksum missed the corruption "
                            f"({snap})")
        eng.check_page_conservation()

    # phase 2: crash-warm restart in a fleet
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01))

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=1, page_size=4,
                             num_pages=32, max_pages_per_seq=8,
                             max_slots=4, buckets=(8, 16), time_fn=time_fn,
                             host_tier_bytes=1 << 20, swap_in_budget=4)

    from paddle_tpu.serving.fleet import FleetRouter

    fleet = FleetRouter(mk, 2, heartbeat_s=0.05, resubmit_budget=2,
                        faults=plan)
    f1 = fleet.submit(list(prompt), max_tokens=6)
    fleet.run(max_ticks=200)
    cold = fleet.result(f1)
    victim = next(r.idx for r in fleet.replicas
                  if r.engine.cache is not None and len(r.engine.cache))
    fleet.replicas[victim].engine.cache.flush()
    fleet.kill_replica(victim)
    new_idx = fleet.restart_replica(victim)
    fleet.drain_replica(1 - victim)
    for _ in range(5):
        fleet.step()
    f2 = fleet.submit(list(prompt), max_tokens=6)
    fleet.run(max_ticks=200)
    warm = fleet.result(f2)
    restored = fleet.metrics.pages_restored
    if warm != cold:
        problems.append(f"warm-restart parity break: {warm} != {cold}")
    if restored < 1:
        problems.append("warm restart adopted 0 pages")
    if fleet.metrics.duplicate_completions:
        problems.append(f"{fleet.metrics.duplicate_completions} duplicate "
                        "completions after warm restart")
    fleet.check_fleet_conservation()

    if problems:
        print("HOSTTIER: " + "; ".join(problems))
        return 1
    print(f"kv-cache check ok: clean swap-in x{clean_swapins} "
          "token-identical to cold prefill, torn + bit-flip spills both "
          f"caught at swap-in (0 corrupt pages served), warm restart "
          f"re-adopted {restored} page(s) with 0 duplicate completions, "
          "0 leaks")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI dispatch, importable so callers (tools_tier1.sh) can run the
    gate via ``python -c "...kv_cache.main(['check'])"`` — ``python -m``
    would have runpy execute a SECOND copy of this module alongside the
    one ``paddle_tpu.serving`` already imported."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else "check"
    if cmd != "check":
        print(f"unknown command {cmd!r}; usage: "
              "python -m paddle_tpu.serving.kv_cache check")
        return 2
    from paddle_tpu.serving.faults import PageLeakError

    try:
        return _selfcheck()
    except PageLeakError as e:
        print(str(e))
        return 1
    except Exception as e:   # crash != findings: distinct exit code
        print(f"kv-cache check crashed: {e!r}")
        return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
