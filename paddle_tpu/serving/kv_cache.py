"""Block-paged KV cache for the serving engine.

The TPU-native analog of vLLM-style paged KV storage (Ragged Paged
Attention, arXiv 2604.15464): instead of one contiguous per-sequence
[max_len, H, D] buffer, K/V live in a preallocated pool of fixed-size
pages ``[num_pages, page_size, H, D]`` (one pool slice per layer).  Each
sequence owns an ordered list of page ids — its *page table* — and grows
one page at a time, so HBM is shared at page granularity across
concurrently-decoding requests with zero fragmentation beyond the last
partial page.

Split of responsibilities:

- **Device side** (pure functions, jit-safe): ``append_token`` /
  ``write_prompt`` scatter new K/V into pages, ``gather_kv`` linearizes a
  page table back into a contiguous view (the oracle/fallback path).
  These take page ids and offsets as *arrays*, so one jit specialization
  serves every allocation pattern.
- **Host side**: :class:`PagePool` is the free list.  Allocation is a
  scheduling decision (admission control, growth, preemption), so it
  stays in python — the device never sees the free list, only page
  tables.

Page 0 is **reserved as the null page**: masked writes (prompt padding,
inactive decode slots) are steered to it instead of being predicated
out, which keeps every scatter dense and shape-stable under jit.  No
live sequence is ever granted page 0.

Automatic prefix caching (round 9): pages are **refcounted** — a page
shared by N sequences is freed only when the last holder unrefs it —
and a host-side :class:`PrefixCache` indexes *full* pages by chained
token-block hashes, so a new prompt can be split into
``cached_prefix_pages + tail`` and skip re-forwarding the prefix
entirely (arXiv 2603.09555: the cache, not the kernel, is where serving
latency is won).  Cached pages at refcount 0 stay out of the free list
as a reclaimable pool; LRU eviction returns them under pressure.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import (Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Set, Tuple)

import jax
import jax.numpy as jnp

from paddle_tpu.platform.enforce import enforce_that

NULL_PAGE = 0


@dataclass(frozen=True)
class PagedKVConfig:
    """Static geometry of the paged pool (one pool shared by all layers:
    page id ``p`` addresses layer ``l``'s slice ``k[l, p]`` for every l)."""

    num_layers: int
    num_heads: int
    head_dim: int
    page_size: int
    num_pages: int           # includes the reserved null page 0
    max_pages_per_seq: int   # page-table width (static decode grid bound)
    dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        enforce_that(self.num_pages >= 2,
                     "need at least one usable page beyond the null page",
                     context="serving")
        enforce_that(self.page_size >= 1 and self.max_pages_per_seq >= 1,
                     "page_size and max_pages_per_seq must be positive",
                     context="serving")

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # page 0 is the null page

    def kv_bytes(self) -> int:
        per = (self.num_layers * self.num_pages * self.page_size *
               self.num_heads * self.head_dim *
               jnp.dtype(self.dtype).itemsize)
        return 2 * per


class KVPages(NamedTuple):
    """The device-resident pool: ``k``/``v`` are
    [num_layers, num_pages, page_size, num_heads, head_dim]."""

    k: jax.Array
    v: jax.Array


def init_kv_pages(cfg: PagedKVConfig) -> KVPages:
    shape = (cfg.num_layers, cfg.num_pages, cfg.page_size, cfg.num_heads,
             cfg.head_dim)
    return KVPages(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def append_token(kv: KVPages, layer: int, k_new: jax.Array, v_new: jax.Array,
                 page_ids: jax.Array, offsets: jax.Array) -> KVPages:
    """Scatter one decode token per sequence into its current page.

    k_new/v_new: [B, H, D]; page_ids/offsets: [B] int32 (inactive slots
    pass page_ids == NULL_PAGE — duplicates on the null page are fine,
    nothing reads it).  Pure; returns the updated pool."""
    k = kv.k.at[layer, page_ids, offsets].set(k_new.astype(kv.k.dtype))
    v = kv.v.at[layer, page_ids, offsets].set(v_new.astype(kv.v.dtype))
    return KVPages(k, v)


def write_prompt(kv: KVPages, layer: int, k_seq: jax.Array, v_seq: jax.Array,
                 dest_pages: jax.Array, offsets: jax.Array) -> KVPages:
    """Scatter a whole (padded) prompt into pages at prefill.

    k_seq/v_seq: [T, H, D]; dest_pages/offsets: [T] int32, with padded
    positions (t >= true length) steered to NULL_PAGE by the caller."""
    k = kv.k.at[layer, dest_pages, offsets].set(k_seq.astype(kv.k.dtype))
    v = kv.v.at[layer, dest_pages, offsets].set(v_seq.astype(kv.v.dtype))
    return KVPages(k, v)


def zero_pages(kv: KVPages, page_ids: jax.Array) -> KVPages:
    """Zero whole pages across every layer (failed-request scrub).

    page_ids: [n] int32.  A prompt that overflows to non-finite values
    leaves inf/NaN K/V in the pages it wrote; freed and re-granted,
    those stale values would poison the NEXT owner through masked
    attention reads (softmax weight 0 times inf is NaN).  Scrubbing on
    the failure path keeps the pool finite-by-construction."""
    k = kv.k.at[:, page_ids].set(0.0)
    v = kv.v.at[:, page_ids].set(0.0)
    return KVPages(k, v)


def fork_page(kv: KVPages, src: jax.Array, dst: jax.Array) -> KVPages:
    """Copy one page's K/V across every layer (the copy-on-write fork).

    src/dst: scalar int32 page ids.  The forked page becomes a private
    replica of a shared cached page, so a sequence whose tail must write
    into the last shared page of its prefix does so without corrupting
    the other holders.  Pure; returns the updated pool."""
    k = kv.k.at[:, dst].set(kv.k[:, src])
    v = kv.v.at[:, dst].set(kv.v[:, src])
    return KVPages(k, v)


def gather_kv(kv: KVPages, layer: int, page_table: jax.Array):
    """Linearize page tables into contiguous K/V.

    page_table: [B, max_pages_per_seq] int32.  Returns (k, v) each
    [B, max_pages_per_seq * page_size, H, D] — positions beyond a
    sequence's length hold whatever the referenced pages contain (callers
    mask by length; this is the oracle/fallback read path)."""
    kl, vl = kv.k[layer], kv.v[layer]
    b, pm = page_table.shape
    _, page, h, d = kl.shape
    k = kl[page_table].reshape(b, pm * page, h, d)
    v = vl[page_table].reshape(b, pm * page, h, d)
    return k, v


@dataclass
class PagePool:
    """Host-side refcounted allocator over page ids 1..num_pages-1 (0 is
    the null page).  Allocation is all-or-nothing so admission control
    can't partially strand a request.

    Every non-free page carries a refcount: ``alloc`` grants pages at
    refcount 1, ``ref`` adds a holder (prefix sharing), ``free`` drops
    one — the page returns to the free list only at refcount 0, and not
    even then if a :class:`PrefixCache` has registered it (``mark_cached``):
    cached pages at refcount 0 are *reclaimable*, parked for future
    prefix hits until ``release_cached`` (LRU eviction) returns them.

    The free list is LIFO over ascending ids (recently-freed pages are
    re-granted first, keeping the working set compact) and mirrored by a
    set, so the double-free guard is O(1) instead of an O(pages) list
    scan on every free."""

    num_pages: int
    # obs hook: the engine binds its (enabled) tracer here so page
    # custody changes land on the request timeline; None = tracing off,
    # one is-None check per pool call (never per page)
    tracer: Optional[object] = field(default=None, repr=False, compare=False)
    _free: List[int] = field(default_factory=list)
    _free_set: Set[int] = field(default_factory=set)
    _refs: Dict[int, int] = field(default_factory=dict)
    _cached: Set[int] = field(default_factory=set)

    def __post_init__(self):
        enforce_that(self.num_pages >= 2, "pool needs >= 2 pages",
                     context="serving")
        self._free = list(range(self.num_pages - 1, NULL_PAGE, -1))
        self._free_set = set(self._free)
        self._refs = {}
        self._cached = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_usable(self) -> int:
        return self.num_pages - 1

    @property
    def num_in_use(self) -> int:
        """Pages not on the free list: live (refcount > 0) plus cached
        pages parked at refcount 0."""
        return len(self._refs)

    @property
    def num_live(self) -> int:
        """Pages held by at least one sequence (or the fault plan)."""
        return sum(1 for c in self._refs.values() if c > 0)

    @property
    def num_cached(self) -> int:
        """Pages registered by a PrefixCache (any refcount)."""
        return len(self._cached)

    @property
    def num_reclaimable(self) -> int:
        """Cached pages at refcount 0 — evictable under pressure."""
        return sum(1 for p in self._cached if self._refs[p] == 0)

    @property
    def total_refs(self) -> int:
        """Sum of all refcounts — must equal the holders' page-list
        lengths summed (the REF-LEAK conservation invariant)."""
        return sum(self._refs.values())

    def occupancy(self) -> float:
        return self.num_in_use / max(1, self.num_usable)

    def refcount(self, p: int) -> int:
        return self._refs.get(p, 0)

    def is_cached(self, p: int) -> bool:
        return p in self._cached

    def alloc(self, n: int) -> Optional[List[int]]:
        """Grant ``n`` pages at refcount 1 each, or None (and no change)
        if fewer are free.  Reclaimable cached pages are NOT granted
        here — evict them first (``PrefixCache.evict``)."""
        if n < 0 or n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        for p in got:
            self._free_set.discard(p)
            self._refs[p] = 1
        if self.tracer is not None and got:
            self.tracer.instant("page_alloc", cat="pages", n=len(got),
                                pages=tuple(got))
        return got

    def ref(self, pages: Sequence[int]) -> None:
        """Add one holder to each page (a prefix-cache hit sharing them
        with a new sequence).  Pages must be in use or cached."""
        for p in pages:
            enforce_that(p in self._refs, f"ref of free page {p}",
                         context="serving")
            self._refs[p] += 1
        if self.tracer is not None and pages:
            self.tracer.instant("page_ref", cat="pages", n=len(pages),
                                pages=tuple(pages))

    def free(self, pages: Sequence[int]) -> None:
        """Drop one holder per page (unref).  A page reaches the free
        list only at refcount 0, and stays parked (reclaimable) instead
        if a PrefixCache holds it."""
        for p in pages:
            enforce_that(p != NULL_PAGE, "cannot free the null page",
                         context="serving")
            enforce_that(p not in self._free_set,
                         f"double free of page {p}", context="serving")
            enforce_that(self._refs.get(p, 0) > 0,
                         f"free of unreferenced page {p}", context="serving")
            self._refs[p] -= 1
            if self._refs[p] == 0 and p not in self._cached:
                del self._refs[p]
                self._free.append(p)
                self._free_set.add(p)
        if self.tracer is not None and pages:
            self.tracer.instant("page_free", cat="pages", n=len(pages),
                                pages=tuple(pages))

    def mark_cached(self, p: int) -> None:
        """Register a (non-free) page as prefix-cache-held: at refcount
        0 it parks as reclaimable instead of returning to the free
        list."""
        enforce_that(p in self._refs, f"cannot cache free page {p}",
                     context="serving")
        self._cached.add(p)

    def unmark_cached(self, p: int) -> None:
        """Withdraw a page's cache registration (failed-prefill
        rollback).  A page already parked at refcount 0 is freed on the
        spot — nothing holds it and nothing can hit it anymore."""
        if p not in self._cached:
            return
        self._cached.discard(p)
        if self._refs.get(p, 0) == 0:
            del self._refs[p]
            self._free.append(p)
            self._free_set.add(p)

    def release_cached(self, p: int) -> None:
        """Eviction: return a refcount-0 cached page to the free list."""
        enforce_that(p in self._cached, f"page {p} is not cached",
                     context="serving")
        enforce_that(self._refs.get(p, 0) == 0,
                     f"evicting page {p} with live holders",
                     context="serving")
        self._cached.discard(p)
        del self._refs[p]
        self._free.append(p)
        self._free_set.add(p)
        if self.tracer is not None:
            self.tracer.instant("page_evict", cat="pages", page=p)


# ---------------------------------------------------------------------------
# Automatic prefix caching: host-side index over full pages
# ---------------------------------------------------------------------------

_CHAIN_SEED = 0x9E3779B9   # any fixed non-zero start for the hash chain


def _chain_hash(prev: int, block: Tuple[int, ...]) -> int:
    """Default chained block hash: each full page's key commits to every
    token before it via the previous link.  Python's tuple hash over
    ints is deterministic within and across processes (int hashing is
    not seed-randomized), which is all the index needs — collisions are
    verified away by token comparison, never trusted."""
    return hash((prev, block))


def prefix_chain_hashes(tokens: Sequence[int], page_size: int,
                        hash_fn: Optional[Callable[[int, Tuple[int, ...]],
                                                   int]] = None) -> List[int]:
    """The :class:`PrefixCache` key chain of ``tokens``: one chained
    hash per FULL page block, ``h_j = hash(h_{j-1}, block_j)`` from
    :data:`_CHAIN_SEED` — exactly the keys ``lookup``/``insert`` walk.
    Exposed so the fleet router (``serving/fleet.py``) routes by the
    SAME function the cache indexes with: two prompts that would share
    cached pages produce a common chain prefix by construction, so
    affinity routing and cache hits can never disagree on what "same
    prefix" means."""
    hf = hash_fn or _chain_hash
    page = int(page_size)
    h = _CHAIN_SEED
    out: List[int] = []
    for j in range(len(tokens) // page):
        h = hf(h, tuple(tokens[j * page:(j + 1) * page]))
        out.append(h)
    return out


@dataclass
class _CacheEntry:
    page: int                 # the page holding this block's K/V
    tokens: Tuple[int, ...]   # the block itself (collision verification)
    prev: int                 # parent link hash (chain verification)


class PrefixCache:
    """Hash-chained index over *full* KV pages for automatic prefix
    caching.

    Key design points:

    - only FULL pages are indexed: a partial page is still being
      appended to by its owner, so it can never be safely shared;
    - keys are chained (``h_j = hash(h_{j-1}, block_j)``), so a hit on
      page j implies the whole prefix up to j matched — the index acts
      as a radix tree flattened into a hash map;
    - every hit is VERIFIED by comparing the stored block tokens and
      parent link, so a hash collision (including fault-injected
      degenerate hashes) degrades to a miss, never to corruption;
    - entries are LRU-ordered; :meth:`evict` frees refcount-0 pages
      oldest-first under pool pressure.  Evicting a mid-chain entry
      orphans its descendants (unreachable, evicted later by the same
      LRU sweep) — safe, just conservative.

    The cache does NOT hold refcounts of its own: a cached page with no
    sequence holders parks at refcount 0 inside the :class:`PagePool`
    (reclaimable) rather than returning to the free list."""

    def __init__(self, pool: PagePool, page_size: int,
                 hash_fn: Optional[Callable[[int, Tuple[int, ...]], int]]
                 = None):
        enforce_that(page_size >= 1, "page_size must be positive",
                     context="serving")
        self.pool = pool
        self.page_size = int(page_size)
        self._hash = hash_fn or _chain_hash
        self.tracer = None     # obs hook, bound by the engine (see pool)
        self._index: "OrderedDict[int, _CacheEntry]" = OrderedDict()
        self.hits = 0          # lookups that matched >= 1 page (healthz)
        self.misses = 0        # lookups that matched none (healthz)
        self.evictions = 0     # pages evicted (LRU or storm)

    def __len__(self) -> int:
        return len(self._index)

    def lookup(self, tokens: Sequence[int],
               touch: bool = False) -> Tuple[List[int], int]:
        """Longest verified cached prefix of ``tokens`` in full pages.

        Returns ``(pages, hit_len)`` with ``hit_len = len(pages) *
        page_size``.  Does NOT take references — the caller refs the
        pages it actually stitches (all-or-nothing with its allocation),
        so a failed admission leaves no state behind.

        ``touch=False`` (the default) is a PURE read: no LRU reorder, no
        hit/miss counting.  The scheduler probes every admission attempt
        — a head-of-line request blocked on pages re-probes every tick,
        and counting those would inflate the stats and churn eviction
        order for zero actual stitches.  It re-calls with ``touch=True``
        exactly once, when the admission commits."""
        page = self.page_size
        pages: List[int] = []
        h = _CHAIN_SEED
        for j in range(len(tokens) // page):
            block = tuple(tokens[j * page:(j + 1) * page])
            key = self._hash(h, block)
            e = self._index.get(key)
            if e is None or e.tokens != block or e.prev != h:
                break          # miss or verified-away collision
            if touch:
                self._index.move_to_end(key)
            pages.append(e.page)
            h = key
        if touch:
            if pages:
                self.hits += 1
            else:
                self.misses += 1
        return pages, len(pages) * page

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               upto: int, from_block: int = 0,
               prev_hash: Optional[int] = None) -> Tuple[int, int]:
        """Index the full pages covering ``tokens[:upto]`` (page j of
        the sequence lives in ``pages[j]``).  Idempotent — re-inserting
        a chunk already indexed is a no-op, and an existing entry always
        wins (a concurrent identical prefill keeps its private copy).

        ``from_block``/``prev_hash`` resume the hash chain at a block
        boundary, so a chunked prefill indexes each chunk in O(chunk)
        instead of re-hashing the whole prefix per chunk (quadratic in
        prompt length on the tick hot path).  Returns ``(chain_hash,
        blocks_done)`` for the caller to pass back on its next chunk."""
        page = self.page_size
        h = _CHAIN_SEED if prev_hash is None else prev_hash
        nblocks = min(upto, len(tokens)) // page
        for j in range(from_block, nblocks):
            block = tuple(tokens[j * page:(j + 1) * page])
            key = self._hash(h, block)
            e = self._index.get(key)
            if e is None:
                self._index[key] = _CacheEntry(page=int(pages[j]),
                                               tokens=block, prev=h)
                self.pool.mark_cached(int(pages[j]))
            h = key
        return h, max(from_block, nblocks)

    def forget(self, pages: Sequence[int]) -> int:
        """Drop every index entry whose page is in ``pages`` (they stay
        with their holder; once unref'd they go straight to the free
        list instead of parking).  A prefill that fails the finite-
        logits guard calls this so its (possibly NaN-laden) K/V can
        never be stitched into a later request — without it, one
        overflowing prompt would poison every future request sharing
        the prefix."""
        ps = {int(p) for p in pages}
        dropped = 0
        for key in [k for k, e in self._index.items() if e.page in ps]:
            e = self._index.pop(key)
            self.pool.unmark_cached(e.page)
            dropped += 1
        return dropped

    def evict(self, n: int) -> int:
        """Evict up to ``n`` refcount-0 cached pages, LRU first; returns
        how many were actually freed.  Pages with live holders are
        skipped (their entries stay — they are still hittable)."""
        if n <= 0:
            return 0
        freed = 0
        for key in list(self._index):
            if freed >= n:
                break
            e = self._index[key]
            if self.pool.refcount(e.page) == 0:
                del self._index[key]
                self.pool.release_cached(e.page)
                self.evictions += 1
                freed += 1
        if self.tracer is not None and freed:
            self.tracer.instant("cache_evict", cat="pages", n=freed)
        return freed

    def flush(self) -> int:
        """Evict every reclaimable page (the fault plan's eviction
        storm; also useful for tests).  Entries with live holders
        survive."""
        return self.evict(len(self._index))
