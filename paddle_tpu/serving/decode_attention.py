"""Single-token decode attention over the paged KV cache.

One query token per sequence attends over everything that sequence has
cached, where the cache is scattered across non-contiguous pages (see
``kv_cache.py``).  Two paths with identical semantics:

- **Pallas kernel** (``use_kernel=True`` or auto on TPU when the shape
  allows): grid ``(batch, heads, pages-per-seq)`` with the page axis
  streamed — the page table rides in as a *scalar-prefetch* operand
  (``pltpu.PrefetchScalarGridSpec``) so the K/V BlockSpec index maps can
  chase it and DMA exactly the pages each sequence owns, page j+1's
  fetch overlapping page j's compute.  The online-softmax carry (m, l,
  acc) lives in VMEM scratch across the page axis, the same pattern as
  ``ops/attention.py``'s flash forward.  Pages past a sequence's length
  are skipped with ``pl.when`` AND their index maps clamp to the last
  live page, so the revisiting optimisation elides the dead DMAs (the
  ragged-page-table trick of arXiv 2604.15464).
- **Reference path** (the CPU/interpreter fallback and the test oracle):
  ``gather_kv``-style linearization + ``ops.attention.mha_reference``
  with length masking expressed as segment ids — no new math to trust.

Decode is bandwidth-bound (a [1, D] x [page, D] product per page), so
the kernel's job is DMA shape, not MXU utilisation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.attention import (DEFAULT_MASK_VALUE, _dim_semantics,
                                      mha_reference)
from paddle_tpu.ops.kernel_util import interpret_default as _interpret_default

_LANES = 128  # lane width of the (1, _LANES) m/l scratch carries


# ---------------------------------------------------------------------------
# Reference path (oracle + CPU fallback)
# ---------------------------------------------------------------------------

def paged_decode_attention_reference(q, k_pages, v_pages, page_table,
                                     lengths, sm_scale: Optional[float] = None):
    """Gather-then-mask oracle.

    q: [B, H, D]; k_pages/v_pages: [num_pages, page, H, D] (ONE layer's
    pool slice); page_table: [B, max_pages_per_seq] int32; lengths: [B]
    int32 — the number of valid cached tokens per sequence (the query
    attends over positions 0..len-1).  Returns [B, H, D].

    Rows with length 0 return an arbitrary finite value (a fully-masked
    softmax degenerates to uniform); the engine never reads them."""
    b, pm = page_table.shape
    _, page, h, d = k_pages.shape
    k = k_pages[page_table].reshape(b, pm * page, h, d)
    v = v_pages[page_table].reshape(b, pm * page, h, d)
    pos = jnp.arange(pm * page, dtype=jnp.int32)[None, :]
    kv_seg = jnp.where(pos < lengths[:, None], 0, 1).astype(jnp.int32)
    q_seg = jnp.zeros((b, 1), jnp.int32)
    out = mha_reference(q[:, None], k, v, segment_ids=q_seg,
                        kv_segment_ids=kv_seg, sm_scale=sm_scale)
    return out[:, 0]


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, page_size: int,
                         num_pb: int, sm_scale: float):
    # grid (B, H, pages-per-seq): the page axis is streamed; (m, l, acc)
    # persist in VMEM scratch across it.  pt_ref/len_ref are the
    # scalar-prefetched page table [B, Pm] and lengths [B] (SMEM).
    # q_ref/o_ref: (1, 1, D); k_ref/v_ref: (1, 1, page, D).
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    n = len_ref[b]
    live = j * page_size < n

    @pl.when(live)
    def _compute():
        q = q_ref[0]                       # (1, D)
        kb = k_ref[0, 0, :, :]             # (page, D)
        vb = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                   # (1, page)
        tok = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(tok < n, s, DEFAULT_MASK_VALUE)

        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)
        l_prev = jnp.max(l_scr[...], axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_pb - 1)
    def _finalize():
        l = jnp.max(l_scr[...], axis=1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)    # length-0 rows -> zeros, not NaN
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, page_table, lengths, sm_scale,
                         interpret: bool):
    b, h, d = q.shape
    _, page, _, _ = k_pages.shape
    pm = page_table.shape[1]
    # [P, page, H, D] -> [H, P, page, D]: per-head pages are contiguous
    # blocks the index map can address as (h, page_id, 0, 0)
    kt = k_pages.transpose(2, 0, 1, 3)
    vt = v_pages.transpose(2, 0, 1, 3)
    pt = page_table.astype(jnp.int32)
    ln = lengths.astype(jnp.int32)

    def kv_idx(bi, hi, j, pt_ref, len_ref):
        # clamp dead pages (j past the sequence's last live page) to the
        # last live one so their DMA is elided by revisiting; pl.when
        # skips their compute.  max(len-1, 0) keeps length-0 rows legal.
        last = jnp.maximum(len_ref[bi] - 1, 0) // page
        return (hi, pt_ref[bi, jnp.minimum(j, last)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, pm),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda bi, hi, j, pt_ref, len_ref:
                         (bi, hi, 0)),
            pl.BlockSpec((1, 1, page, d), kv_idx),
            pl.BlockSpec((1, 1, page, d), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda bi, hi, j, pt_ref, len_ref:
                               (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, _LANES), jnp.float32),
            pltpu.VMEM((1, _LANES), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_decode_kernel, page_size=page,
                               num_pb=pm, sm_scale=sm_scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_dim_semantics(3, interpret),
        interpret=interpret,
    )(pt, ln, q, kt, vt)
    return out


def _kernel_shape_ok(head_dim: int, page_size: int) -> bool:
    """Native-compile gate: the kernel's tiles are (page, D) and (1, D);
    lane-aligned D and sublane-aligned pages avoid relayouts on real
    hardware.  Anything else rides the reference path (still correct)."""
    return head_dim % _LANES == 0 and page_size % 8 == 0


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           interpret: Optional[bool] = None):
    """Decode-step attention over a paged KV cache.

    q: [B, H, D] — this tick's single query token per sequence (its K/V
    already appended, so ``lengths`` INCLUDES it); k_pages/v_pages:
    [num_pages, page, H, D]; page_table: [B, max_pages_per_seq] int32;
    lengths: [B] int32.  Returns [B, H, D] in q's dtype.

    ``use_kernel=None`` auto-selects: the pallas kernel on TPU when the
    shape is lane/sublane aligned, otherwise the ``mha_reference``-based
    path (which is also the CPU/interpreter-mode fallback — the kernel
    itself runs under ``interpret=True`` only when forced, for tests)."""
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    if use_kernel is None:
        use_kernel = (not interpret) and _kernel_shape_ok(
            q.shape[-1], k_pages.shape[1])
    if not use_kernel:
        return paged_decode_attention_reference(
            q, k_pages, v_pages, page_table, lengths,
            sm_scale=sm_scale).astype(q.dtype)
    return _paged_decode_pallas(q, k_pages, v_pages,
                                page_table.astype(jnp.int32),
                                lengths.astype(jnp.int32),
                                float(sm_scale), bool(interpret))
