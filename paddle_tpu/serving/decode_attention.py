"""Ragged paged attention v2: ONE kernel for mixed prefill + decode.

The v1 kernel (rounds 5-9) was decode-only — grid ``(B, H, pages)``, one
query row per sequence — and chunked prefill ran as a *separate*
gather+offset-masked program interleaved at the tick level, so every
tick with in-flight prefill paid two dispatches, two softmax passes over
shared pages, and duplicate K/V HBM traffic per query head.  This
rebuild (the headline kernel of arXiv 2604.15464) folds both into one
ragged invocation:

- **Sequence-packed rows.**  The query batch is a flat ``[T, H, D]`` row
  stack: decode slots contribute one row each, in-flight prefill chunks
  contribute up to ``serving_prefill_chunk`` rows each.  A row→sequence
  map (``row_seq``) and a per-row absolute position (``qpos``, −1 for
  padding) drive ONE causal/offset mask — ``token t is visible to the
  row at position p iff t <= p`` — which subsumes decode length masking,
  in-chunk causality, and cached-prefix offsets.
- **Scalar-prefetched page tables.**  For the pallas path the rows are
  packed into blocks of :data:`BLOCK_ROWS` with one sequence per block;
  the per-block sequence id, the page tables, and the KV lengths ride in
  as scalar-prefetch operands so the K/V BlockSpec index maps chase the
  ragged page chain and DMA exactly the pages each block's sequence
  owns, page j+1's fetch overlapping page j's compute.  Dead pages are
  skipped with ``pl.when`` AND their index maps clamp to the last live
  page, so the revisiting optimisation elides the dead DMAs.
- **GQA head-group packing.**  The grid's head axis runs over KV heads,
  not query heads: a block of ``BLOCK_ROWS * group`` query rows (group =
  ``num_heads // num_kv_heads``) is packed against each K/V page load,
  so K/V HBM traffic drops by the group factor — the pool stores KV
  heads only.
- **int8 pages, dequant in-register.**  Quantized pools ship per-token,
  per-kv-head f32 scales next to the int8 pages; the kernel (and the
  gather fallback — see ``kv_cache.dequantize_kv``, the ONE shared
  rule) dequantizes in-register, so HBM reads stay 1 byte/element.

Two paths with identical semantics, selected by :func:`attention_path`
— the single dispatch gate every paged-attention call routes through:

- **Pallas kernel**: grid ``(row_blocks, kv_heads, pages)``, online-
  softmax carry (m, l, acc) in VMEM scratch across the page axis.
- **Reference path** (CPU/interpreter fallback and the parity oracle):
  page-table gather + masked softmax in f32 — no new math to trust,
  reading the SAME stored (possibly quantized) values.

Decode rows are bandwidth-bound (a [G, D] x [page, D] product per
page), so the kernel's job there is DMA shape; prefill rows add real
MXU work that v1 paid in a second dispatch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.attention import (DEFAULT_MASK_VALUE, _dim_semantics,
                                      mha_reference)
from paddle_tpu.ops.kernel_util import interpret_default as _interpret_default
from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.serving.kv_cache import dequantize_kv, quantize_kv

_LANES = 128     # lane width of the (rows, _LANES) m/l scratch carries
BLOCK_ROWS = 8   # sublane row-block granularity of the sequence packing

# the int8 parity harness's logit-error bound: attention output feeds
# logits through bounded linear maps, so a relative output-error bound
# IS a logit-error bound up to the model's Lipschitz constant.  The
# per-token amax/127 scheme lands well under 2% on gaussian K/V; 5%
# leaves slack for adversarial value distributions without letting a
# broken quant path (wrong scale axis, missing dequant) slip through.
QUANT_DRIFT_BOUND = 0.05


# ---------------------------------------------------------------------------
# Dispatch gate
# ---------------------------------------------------------------------------

def attention_path(head_dim: int, page_size: int, *,
                   num_heads: Optional[int] = None,
                   num_kv_heads: Optional[int] = None,
                   quantized: bool = False,
                   use_kernel: Optional[bool] = None,
                   interpret: Optional[bool] = None) -> str:
    """THE chooser: every paged-attention dispatch (ragged kernel,
    decode wrapper, engine step builder) routes through this one gate,
    so odd head dims / tiny pages / mismatched head groups fall back to
    the reference path at a single point instead of per-call-site
    guesswork.  Returns ``"kernel"`` or ``"reference"``.

    Native-compile gate: the kernel's tiles are (page, D) and
    (rows*group, D) — lane-aligned D and sublane-aligned pages avoid
    relayouts on real hardware; int8 additionally wants lane-aligned
    pages for its (page,) scale vectors.  ``use_kernel`` (not None)
    forces the answer either way (tests run the kernel under
    ``interpret=True``)."""
    if use_kernel is not None:
        return "kernel" if use_kernel else "reference"
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return "reference"
    if head_dim % _LANES != 0 or page_size % 8 != 0:
        return "reference"
    if quantized and page_size % _LANES != 0:
        return "reference"
    if num_heads and num_kv_heads and num_heads % num_kv_heads != 0:
        return "reference"
    return "kernel"


def _kernel_shape_ok(head_dim: int, page_size: int) -> bool:
    """Back-compat shim over :func:`attention_path` (v1 name)."""
    return attention_path(head_dim, page_size, interpret=False) == "kernel"


# ---------------------------------------------------------------------------
# Reference path (oracle + CPU fallback)
# ---------------------------------------------------------------------------

def ragged_paged_attention_reference(q, k_pages, v_pages, page_table,
                                     kv_lens, row_seq, qpos, *,
                                     k_scale=None, v_scale=None,
                                     sm_scale: Optional[float] = None):
    """Gather-then-mask oracle for the ragged kernel.

    q: [T, H, D] — the sequence-packed row stack (decode rows AND
    prefill-chunk rows); k_pages/v_pages: [num_pages, page, H_kv, D]
    (ONE layer's pool slice, possibly int8 with ``k_scale``/``v_scale``
    [num_pages, page, H_kv]); page_table: [S, max_pages_per_seq] int32;
    kv_lens: [S] int32 — valid cached tokens per sequence AFTER this
    step's writes; row_seq: [T] int32 row→sequence map; qpos: [T] int32
    per-row absolute position (−1 = padded row).  Returns [T, H, D].

    Row r attends over tokens ``0..qpos[r]`` of sequence ``row_seq[r]``
    — decode length masking, in-chunk causality and cached-prefix
    offsets are all this one inequality.  Padded rows return an
    arbitrary finite value (fully-masked softmax degenerates to
    uniform); callers never read them."""
    t, h, d = q.shape
    _, page, kvh, _ = k_pages.shape
    pm = page_table.shape[1]
    if sm_scale is None:
        sm_scale = float(d) ** -0.5
    pt = page_table[row_seq]                       # [T, Pm]
    k = k_pages[pt]                                # [T, Pm, page, KVH, D]
    v = v_pages[pt]
    if k_scale is not None:
        k = dequantize_kv(k, k_scale[pt])
        v = dequantize_kv(v, v_scale[pt])
    k = k.reshape(t, pm * page, kvh, d).astype(jnp.float32)
    v = v.reshape(t, pm * page, kvh, d).astype(jnp.float32)
    if kvh != h:
        k = jnp.repeat(k, h // kvh, axis=2)        # GQA head replication
        v = jnp.repeat(v, h // kvh, axis=2)
    tok = jnp.arange(pm * page, dtype=jnp.int32)
    live = ((tok[None, :] <= qpos[:, None]) &
            (tok[None, :] < kv_lens[row_seq][:, None]))
    s = jnp.einsum("thd,tkhd->thk", q.astype(jnp.float32), k) * sm_scale
    s = jnp.where(live[:, None, :], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("thk,tkhd->thd", p, v)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _ragged_kernel(blk_seq_ref, pt_ref, len_ref, qpos_ref, q_ref, k_ref,
                   v_ref, *rest, page_size: int, num_pb: int,
                   sm_scale: float, quantized: bool):
    # grid (row_blocks, kv_heads, pages-per-seq): the page axis is
    # streamed; (m, l, acc) persist in VMEM scratch across it.
    # blk_seq/pt/len are the scalar-prefetched block→sequence map [NB],
    # page table [S, Pm] and KV lengths [S] (SMEM).  qpos_ref: (1, RBG)
    # — per-score-row absolute positions, already group-expanded.
    # q_ref/o_ref: (1, 1, RBG, D); k_ref/v_ref: (1, 1, page, D);
    # quantized adds ks/vs (1, 1, page) scale rows.
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    ib = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    n = len_ref[blk_seq_ref[ib]]
    live = j * page_size < n

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]                    # (RBG, D)
        kb = k_ref[0, 0]                   # (page, D)
        vb = v_ref[0, 0]
        if quantized:
            # in-register dequant: HBM traffic stays 1 byte/element
            kb = kb.astype(jnp.float32) * ks_ref[0, 0][:, None]
            vb = vb.astype(jnp.float32) * vs_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                   # (RBG, page)
        tok = j * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # ONE inequality is the whole mask: causal for prefill rows,
        # length for decode rows, everything for padded rows (qpos −1)
        s = jnp.where(tok <= qpos_ref[0][:, None], s, DEFAULT_MASK_VALUE)

        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)
        l_prev = jnp.max(l_scr[...], axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_pb - 1)
    def _finalize():
        l = jnp.max(l_scr[...], axis=1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)    # length-0 rows -> zeros, not NaN
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _ragged_pallas(q, k_pages, v_pages, k_scale, v_scale, page_table,
                   kv_lens, row_seq, qpos, sm_scale, interpret: bool):
    """Kernel-path entry.  REQUIRES block-uniform packing: T a multiple
    of :data:`BLOCK_ROWS` and every aligned block of rows belonging to
    ONE sequence (callers pad each sequence's rows to the block size —
    decode slots to one block, chunks to whole blocks).  The block map
    is read as ``row_seq[::BLOCK_ROWS]``; rows that violate uniformity
    would silently attend over the wrong pages, so the engine owns the
    packing and tests pin it against the reference path."""
    t, h, d = q.shape
    _, page, kvh, _ = k_pages.shape
    pm = page_table.shape[1]
    enforce_that(t % BLOCK_ROWS == 0,
                 f"ragged kernel rows ({t}) must pack to BLOCK_ROWS "
                 f"({BLOCK_ROWS})", context="serving")
    enforce_that(h % kvh == 0, f"num_heads ({h}) must be a multiple of "
                 f"num_kv_heads ({kvh})", context="serving")
    g = h // kvh
    nb = t // BLOCK_ROWS
    rbg = BLOCK_ROWS * g
    quantized = k_scale is not None

    blk_seq = row_seq.reshape(nb, BLOCK_ROWS)[:, 0].astype(jnp.int32)
    qpos_rows = jnp.repeat(qpos.astype(jnp.int32).reshape(nb, BLOCK_ROWS),
                           g, axis=1)                     # (NB, RBG)
    # [T, H, D] -> [KVH, NB, RB*G, D]: each block packs its G query
    # heads per KV head next to each other, so one K/V page load feeds
    # the whole head group
    q5 = q.reshape(nb, BLOCK_ROWS, kvh, g, d).transpose(2, 0, 1, 3, 4)
    q5 = q5.reshape(kvh, nb, rbg, d)
    # [P, page, KVH, D] -> [KVH, P, page, D]: per-kv-head pages are
    # contiguous blocks the index map can address as (h, page_id, 0, 0)
    kt = k_pages.transpose(2, 0, 1, 3)
    vt = v_pages.transpose(2, 0, 1, 3)
    pt = page_table.astype(jnp.int32)
    ln = kv_lens.astype(jnp.int32)

    def qpos_idx(ib, hi, j, blk_ref, pt_ref, len_ref):
        return (ib, 0)

    def q_idx(ib, hi, j, blk_ref, pt_ref, len_ref):
        return (hi, ib, 0, 0)

    def kv_idx(ib, hi, j, blk_ref, pt_ref, len_ref):
        # clamp dead pages (j past the block's sequence's last live
        # page) to the last live one so their DMA is elided by
        # revisiting; pl.when skips their compute.  max(len-1, 0) keeps
        # length-0 sequences legal.
        seq = blk_ref[ib]
        last = jnp.maximum(len_ref[seq] - 1, 0) // page
        return (hi, pt_ref[seq, jnp.minimum(j, last)], 0, 0)

    def scale_idx(ib, hi, j, blk_ref, pt_ref, len_ref):
        seq = blk_ref[ib]
        last = jnp.maximum(len_ref[seq] - 1, 0) // page
        return (hi, pt_ref[seq, jnp.minimum(j, last)], 0)

    in_specs = [
        pl.BlockSpec((1, rbg), qpos_idx),
        pl.BlockSpec((1, 1, rbg, d), q_idx),
        pl.BlockSpec((1, 1, page, d), kv_idx),
        pl.BlockSpec((1, 1, page, d), kv_idx),
    ]
    args = [qpos_rows, q5, kt, vt]
    if quantized:
        in_specs += [pl.BlockSpec((1, 1, page), scale_idx),
                     pl.BlockSpec((1, 1, page), scale_idx)]
        args += [k_scale.transpose(2, 0, 1), v_scale.transpose(2, 0, 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nb, kvh, pm),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rbg, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((rbg, _LANES), jnp.float32),
            pltpu.VMEM((rbg, _LANES), jnp.float32),
            pltpu.VMEM((rbg, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_ragged_kernel, page_size=page, num_pb=pm,
                               sm_scale=sm_scale, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, nb, rbg, d), q.dtype),
        compiler_params=_dim_semantics(3, interpret),
        interpret=interpret,
    )(blk_seq, pt, ln, *args)
    out = out.reshape(kvh, nb, BLOCK_ROWS, g, d).transpose(1, 2, 0, 3, 4)
    return out.reshape(t, h, d)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def ragged_paged_attention(q, k_pages, v_pages, page_table, kv_lens,
                           row_seq, qpos, *, k_scale=None, v_scale=None,
                           sm_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           interpret: Optional[bool] = None):
    """Ragged paged attention over a sequence-packed mixed batch (see
    :func:`ragged_paged_attention_reference` for shapes/semantics).

    ``use_kernel=None`` auto-selects through :func:`attention_path`; the
    kernel additionally requires block-uniform :data:`BLOCK_ROWS`
    packing (the engine's packer guarantees it), falling back to the
    reference path otherwise."""
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    path = attention_path(q.shape[-1], k_pages.shape[1],
                          num_heads=q.shape[1],
                          num_kv_heads=k_pages.shape[2],
                          quantized=k_scale is not None,
                          use_kernel=use_kernel, interpret=interpret)
    if path == "kernel" and q.shape[0] % BLOCK_ROWS == 0:
        return _ragged_pallas(q, k_pages, v_pages, k_scale, v_scale,
                              page_table.astype(jnp.int32),
                              kv_lens.astype(jnp.int32),
                              row_seq.astype(jnp.int32),
                              qpos.astype(jnp.int32),
                              float(sm_scale), bool(interpret))
    return _ragged_reference_blocked(
        q, k_pages, v_pages, page_table, kv_lens, row_seq, qpos,
        k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale)


def ragged_paged_attention_tp(mesh, axis, q, k_pages, v_pages, page_table,
                              kv_lens, row_seq, qpos, *, k_scale=None,
                              v_scale=None, sm_scale: Optional[float] = None,
                              use_kernel: Optional[bool] = None,
                              interpret: Optional[bool] = None):
    """Tensor-parallel ragged attention: the pallas kernel wrapped in a
    ``shard_map`` over the ``axis`` (``model``) mesh dim.

    Heads are embarrassingly parallel in attention, so each chip runs
    the UNCHANGED kernel on its local slice — q ``[T, H/TP, D]`` against
    its ``[P, page, H_kv/TP, D]`` pool shard (scales ride along) — and
    no collective crosses the region: the psum lives downstream in the
    row-parallel output projection, exactly the megatron pattern.  A
    bare ``pallas_call`` under GSPMD would instead force the sharded
    operands replicated (XLA cannot partition a custom kernel), which
    is why the TP engine routes its kernel path through here.  The GQA
    group factor is shard-invariant (``(H/TP) / (H_kv/TP) == H/H_kv``),
    so head-group packing is untouched.

    Dispatch routes through :func:`attention_path` like every other
    entry point (the per-SHARD head counts decide): shapes the chooser
    rejects — odd head dims, tiny pages — fall back to the plain
    reference path, which needs no ``shard_map`` because GSPMD
    partitions its gathers/einsums over the head dim natively."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.compat import no_rep_check_kw, shard_map

    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    tp = int(mesh.shape[axis])
    path = attention_path(q.shape[-1], k_pages.shape[1],
                          num_heads=q.shape[1] // tp,
                          num_kv_heads=k_pages.shape[2] // tp,
                          quantized=k_scale is not None,
                          use_kernel=use_kernel, interpret=interpret)
    if path != "kernel" or q.shape[0] % BLOCK_ROWS != 0:
        return _ragged_reference_blocked(
            q, k_pages, v_pages, page_table, kv_lens, row_seq, qpos,
            k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale)
    head = P(None, axis, None)
    pool = P(None, None, axis, None)
    scale = P(None, None, axis)
    repl = P()
    in_specs = [head, pool, pool, repl, repl, repl, repl]
    if k_scale is not None:
        in_specs += [scale, scale]

    def local(qs, ks, vs, pt, ln, rs, qp, *scales):
        kss, vss = scales if scales else (None, None)
        return _ragged_pallas(qs, ks, vs, kss, vss, pt, ln, rs, qp,
                              float(sm_scale), bool(interpret))

    fn = shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=head, **no_rep_check_kw())
    args = [q, k_pages, v_pages, page_table.astype(jnp.int32),
            kv_lens.astype(jnp.int32), row_seq.astype(jnp.int32),
            qpos.astype(jnp.int32)]
    if k_scale is not None:
        args += [k_scale, v_scale]
    return fn(*args)


_REF_ROW_BLOCK = 64   # fallback row-block: bounds the per-row K/V gather


def _ragged_reference_blocked(q, k_pages, v_pages, page_table, kv_lens,
                              row_seq, qpos, k_scale=None, v_scale=None,
                              sm_scale=None, block: int = _REF_ROW_BLOCK):
    """The reference path evaluated in row blocks.  The dumb oracle
    gathers each row's whole page chain ([T, Pm, page, H_kv, D]) — fine
    for tests, but as the ENGINE's fallback a 256-row prefill chunk
    would materialize 256 copies of its sequence's K/V where v1's chunk
    program shared one.  Mapping the oracle over fixed row blocks
    bounds the transient to ``block`` copies with identical results
    (rows are independent); the pallas path owns big shapes, this owns
    big-ish fallbacks."""
    t = q.shape[0]
    if t <= block:
        return ragged_paged_attention_reference(
            q, k_pages, v_pages, page_table, kv_lens, row_seq, qpos,
            k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale)
    pad = (-t) % block
    qp_ = jnp.concatenate([q, jnp.zeros((pad,) + q.shape[1:], q.dtype)]) \
        if pad else q
    rs_ = jnp.concatenate([row_seq, jnp.zeros((pad,), row_seq.dtype)]) \
        if pad else row_seq
    pp_ = jnp.concatenate([qpos, jnp.full((pad,), -1, qpos.dtype)]) \
        if pad else qpos

    def body(args):
        qb, rb, pb = args
        return ragged_paged_attention_reference(
            qb, k_pages, v_pages, page_table, kv_lens, rb, pb,
            k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale)

    h, d = q.shape[1], q.shape[2]
    out = jax.lax.map(body, (qp_.reshape(-1, block, h, d),
                             rs_.reshape(-1, block),
                             pp_.reshape(-1, block)))
    return out.reshape(-1, h, d)[:t]


def quant_parity_error(q, k_pages, v_pages, page_table, kv_lens, row_seq,
                       qpos, *, sm_scale: Optional[float] = None) -> float:
    """The int8 parity harness: max relative error the quantization
    adds to ragged attention output, measured f32-pages vs the SAME
    pages int8-roundtripped through :func:`~kv_cache.quantize_kv` (the
    identical write path the engine uses).  Padded rows are excluded.
    Host-syncs by design — this is a test/CI harness, not a tick op."""
    import numpy as np
    out32 = np.asarray(ragged_paged_attention_reference(
        q, k_pages, v_pages, page_table, kv_lens, row_seq, qpos,
        sm_scale=sm_scale))
    kq, ks = quantize_kv(k_pages)
    vq, vs = quantize_kv(v_pages)
    out8 = np.asarray(ragged_paged_attention_reference(
        q, kq, vq, page_table, kv_lens, row_seq, qpos,
        k_scale=ks, v_scale=vs, sm_scale=sm_scale))
    real = np.asarray(qpos) >= 0
    denom = max(float(np.abs(out32[real]).max()), 1e-20)
    return float(np.abs(out8[real] - out32[real]).max()) / denom


def check_quant_drift(q, k_pages, v_pages, page_table, kv_lens, row_seq,
                      qpos, *, bound: float = QUANT_DRIFT_BOUND,
                      sm_scale: Optional[float] = None) -> float:
    """Assert the harness error stays under ``bound``; the failure
    message carries the literal ``QUANT-DRIFT`` tag tools_tier1.sh
    greps into its exit-code ladder (exit 7), so a quantization
    regression anywhere in the suite is a loud, distinct failure."""
    err = quant_parity_error(q, k_pages, v_pages, page_table, kv_lens,
                             row_seq, qpos, sm_scale=sm_scale)
    if err > bound:
        raise AssertionError(
            f"QUANT-DRIFT: int8 KV parity error {err:.4f} exceeds the "
            f"logit-error bound {bound:.4f}")
    return err


# ---------------------------------------------------------------------------
# Decode-only wrappers (v1 API, now thin views over the ragged paths)
# ---------------------------------------------------------------------------

def paged_decode_attention_reference(q, k_pages, v_pages, page_table,
                                     lengths, sm_scale: Optional[float]
                                     = None, *, k_scale=None, v_scale=None):
    """Decode-only oracle: one row per sequence at position len-1.

    q: [B, H, D]; k_pages/v_pages: [num_pages, page, H_kv, D];
    page_table: [B, max_pages_per_seq] int32; lengths: [B] int32 (the
    query's K/V already appended, so lengths INCLUDES it).  Rows with
    length 0 return an arbitrary finite value; the engine never reads
    them."""
    b = q.shape[0]
    row_seq = jnp.arange(b, dtype=jnp.int32)
    lengths = lengths.astype(jnp.int32)
    return ragged_paged_attention_reference(
        q, k_pages, v_pages, page_table, lengths, row_seq, lengths - 1,
        k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale)


def expand_decode_rows(q, qpos, rows_per_seq: int = 1):
    """Pad per-sequence decode/verify rows to whole :data:`BLOCK_ROWS`
    blocks — THE one copy of the kernel's one-sequence-per-block
    packing for decode rows (the decode wrapper and the engine's
    unified step both build on it, so the contract can't silently fork).

    ``q`` is ``[B * rows_per_seq, H, D]`` sequence-major: sequence
    ``i`` owns rows ``i*rows_per_seq .. (i+1)*rows_per_seq - 1``
    (plain decode passes 1 row per sequence; a speculative verify
    passes ``k+1``).  Each sequence's rows pad up to
    ``ceil(rows_per_seq / BLOCK_ROWS) * BLOCK_ROWS`` rows (padding
    qpos −1), so every aligned block stays single-sequence no matter
    the speculation depth.  Returns ``(q_expanded, row_seq,
    qpos_expanded)``; callers slice results back by reshaping to
    ``[B, padded_rows, ...]`` and taking ``[:, :rows_per_seq]`` (for
    ``rows_per_seq == 1`` that is the historical ``[::BLOCK_ROWS]``)."""
    rps = int(rows_per_seq)
    bt, h, d = q.shape
    b = bt // rps
    rbk = -(-rps // BLOCK_ROWS) * BLOCK_ROWS
    row_seq = jnp.repeat(jnp.arange(b, dtype=jnp.int32), rbk)
    if rbk == rps:
        return q, row_seq, qpos.astype(jnp.int32)
    pad = rbk - rps
    qe = jnp.pad(q.reshape(b, rps, h, d),
                 ((0, 0), (0, pad), (0, 0), (0, 0))).reshape(b * rbk, h, d)
    qp = jnp.pad(qpos.astype(jnp.int32).reshape(b, rps),
                 ((0, 0), (0, pad)),
                 constant_values=-1).reshape(b * rbk)
    return qe, row_seq, qp


def _paged_decode_pallas(q, k_pages, v_pages, page_table, lengths, sm_scale,
                         interpret: bool, k_scale=None, v_scale=None):
    qe, row_seq, qpos = expand_decode_rows(q, lengths.astype(jnp.int32) - 1)
    out = _ragged_pallas(qe, k_pages, v_pages, k_scale, v_scale,
                         page_table.astype(jnp.int32),
                         lengths.astype(jnp.int32), row_seq, qpos,
                         float(sm_scale), bool(interpret))
    return out[::BLOCK_ROWS]


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           interpret: Optional[bool] = None,
                           k_scale=None, v_scale=None):
    """Decode-step attention over a paged KV cache (v1 entry point,
    kept for callers that only ever decode).  Dispatch routes through
    :func:`attention_path` like everything else."""
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    path = attention_path(q.shape[-1], k_pages.shape[1],
                          num_heads=q.shape[1],
                          num_kv_heads=k_pages.shape[2],
                          quantized=k_scale is not None,
                          use_kernel=use_kernel, interpret=interpret)
    if path != "kernel":
        return paged_decode_attention_reference(
            q, k_pages, v_pages, page_table, lengths, sm_scale=sm_scale,
            k_scale=k_scale, v_scale=v_scale).astype(q.dtype)
    return _paged_decode_pallas(q, k_pages, v_pages,
                                page_table.astype(jnp.int32),
                                lengths.astype(jnp.int32),
                                float(sm_scale), bool(interpret),
                                k_scale=k_scale, v_scale=v_scale)
