"""paddle_tpu.serving — paged-KV continuous-batching inference engine.

The serving-side counterpart of the training stack: block-paged KV
storage (``kv_cache``), a ragged-page-table decode-attention kernel
(``decode_attention``), a continuous-batching scheduler with admission
control and preemption (``scheduler``), and the user-facing
:class:`ServingEngine` (``engine``) with scrapeable ``metrics``.
"""

from paddle_tpu.serving.decode_attention import (
    BLOCK_ROWS, attention_path, paged_decode_attention,
    paged_decode_attention_reference, ragged_paged_attention,
    ragged_paged_attention_reference, ragged_paged_attention_tp)
from paddle_tpu.serving.control import (DEFAULT_CLASSES, AdmissionLedger,
                                        Autoscaler, AutoscalePolicy,
                                        TenantClass, TenantRegistry,
                                        TenantSpec, WeightedFairQueue,
                                        check_control_conservation)
from paddle_tpu.serving.engine import (DecodeModel, DecoderLM, ServingEngine,
                                       greedy_decode_reference, validate_tp)
from paddle_tpu.serving.speculate import (DraftProposer, NGramProposer,
                                          SamplingParams, accept_tokens,
                                          next_token, warp_probs)
from paddle_tpu.serving.faults import (FaultPlan, FleetFaultPlan,
                                       InjectedDeviceError, ManualClock,
                                       PageLeakError)
from paddle_tpu.serving.fleet import FleetRouter, Replica, ReplicaState
from paddle_tpu.serving.kv_cache import (NULL_PAGE, KVPages, PagedKVConfig,
                                         PagePool, PrefixCache, append_token,
                                         dequantize_kv, fork_page, gather_kv,
                                         init_kv_pages, pages_for_budget,
                                         prefix_chain_hashes, quantize_kv,
                                         resolve_kv_dtype, write_prompt)
from paddle_tpu.serving.metrics import FleetMetrics, ServingMetrics
from paddle_tpu.serving.migrate import (MigrationBlob,
                                        check_migration_conservation,
                                        export_chain, export_prefix,
                                        import_chain, import_prefix)
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Request, RequestStatus,
                                          SchedulerConfig, bucket_for,
                                          pack_prefill_chunks)

__all__ = [
    "ServingEngine", "DecodeModel", "DecoderLM", "greedy_decode_reference",
    "paged_decode_attention", "paged_decode_attention_reference",
    "ragged_paged_attention", "ragged_paged_attention_reference",
    "ragged_paged_attention_tp", "attention_path", "BLOCK_ROWS",
    "validate_tp",
    "PagedKVConfig", "KVPages", "PagePool", "PrefixCache", "NULL_PAGE",
    "init_kv_pages", "append_token", "write_prompt", "gather_kv",
    "fork_page", "prefix_chain_hashes", "quantize_kv", "dequantize_kv",
    "pages_for_budget", "resolve_kv_dtype",
    "ContinuousBatchingScheduler", "Request", "RequestStatus",
    "SchedulerConfig", "bucket_for", "pack_prefill_chunks",
    "ServingMetrics", "FleetMetrics",
    "FaultPlan", "FleetFaultPlan", "ManualClock", "InjectedDeviceError",
    "PageLeakError",
    "FleetRouter", "Replica", "ReplicaState",
    "MigrationBlob", "export_chain", "import_chain", "export_prefix",
    "import_prefix", "check_migration_conservation",
    "TenantClass", "TenantSpec", "TenantRegistry", "DEFAULT_CLASSES",
    "AdmissionLedger", "WeightedFairQueue", "AutoscalePolicy", "Autoscaler",
    "check_control_conservation",
    "SamplingParams", "NGramProposer", "DraftProposer", "accept_tokens",
    "next_token", "warp_probs",
]
