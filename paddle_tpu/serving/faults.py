"""Deterministic fault injection for the serving engine.

The training side already proves its failure paths deterministically —
``master/service.py`` takes an injectable ``time_fn`` and the elastic
tests drive lease expiry with a fake clock instead of sleeping.  This
module is the serving analog: a seedable :class:`FaultPlan` threaded
through ``ServingEngine(faults=...)`` so every guardrail (deadlines,
watchdog, tick retry, NaN isolation, load shedding) is exercised by CI
without wall-clock dependence.

Injection points (all host-side, all deterministic):

- **clock** — a :class:`ManualClock` the engine reads instead of
  ``time.monotonic``; it advances ``tick_s`` per engine tick plus any
  extra from ``slow_ticks`` (tick -> added seconds), so deadline and
  queue-wait paths fire on chosen ticks.
- **decode-step exceptions** — ``decode_errors`` (tick -> number of
  attempts that raise :class:`InjectedDeviceError`) and/or a seeded
  ``decode_error_rate``; the engine's tick-level retry absorbs
  transient ones, persistent ones feed the watchdog.
- **NaN logits** — rids in ``nan_rids`` get their decode-logits row
  overwritten with NaN *before* the engine's finite-guard runs, proving
  the guard fails only the poisoned slot.
- **page-pool pressure** — ``page_pressure=(start_tick, end_tick, n)``
  steals up to ``n`` pages from the pool for the window, forcing
  growth-time preemption and admission stalls; the pages are returned
  at ``end_tick`` (or at drain) and counted by the leak checker while
  held.
- **prefix-cache hash collisions** — ``hash_collisions=True`` replaces
  the cache's chained block hash with a constant, so EVERY block keys
  identically; the cache's token verification must turn the collisions
  into misses, proving a hash break degrades throughput, never
  correctness.
- **cache eviction storm** — ``cache_storm=(start_tick, end_tick)``
  flushes every refcount-0 cached page each tick of the window,
  exercising eviction/re-insert churn and the REF-LEAK invariant.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

__all__ = ["FaultPlan", "FleetFaultPlan", "ManualClock",
           "InjectedDeviceError", "PageLeakError"]


class InjectedDeviceError(RuntimeError):
    """A fault-plan-injected transient device failure (the test stand-in
    for a TPU tick that dies: interconnect hiccup, preempted donation,
    XLA runtime error)."""


class PageLeakError(AssertionError):
    """Free-list conservation violated.  The message always contains the
    literal token ``PAGE-LEAK`` so CI wrappers (tools_tier1.sh) can grep
    the test log and fail loudly."""


class ManualClock:
    """A monotonic clock the test (or the engine, via a FaultPlan)
    advances by hand — the serving twin of ``time_fn`` in
    ``master/service.py``."""

    def __init__(self, start: float = 0.0, tick_s: float = 0.001):
        self.t = float(start)
        self.tick_s = float(tick_s)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


@dataclass
class FaultPlan:
    """A seeded, replayable schedule of injected failures.

    Mutable on purpose: rids are assigned at ``submit``, so tests poison
    them after submission (``plan.poison_nan(rid)``).  Randomized
    injection (``decode_error_rate``) draws from its own
    ``RandomState(seed)``, one draw per tick, so a plan replays
    identically across runs.
    """

    seed: int = 0
    clock: Optional[ManualClock] = None
    nan_rids: Set[int] = field(default_factory=set)
    # tick -> how many decode attempts at that tick raise (1 = transient,
    # absorbed by the engine's retry; >= retry budget = persistent)
    decode_errors: Dict[int, int] = field(default_factory=dict)
    decode_error_rate: float = 0.0
    slow_ticks: Dict[int, float] = field(default_factory=dict)
    page_pressure: Optional[Tuple[int, int, int]] = None
    held_pages: List[int] = field(default_factory=list)
    # prefix-cache faults (round 9)
    hash_collisions: bool = False
    cache_storm: Optional[Tuple[int, int]] = None
    # host-tier faults (round 21): keyed by SPILL SEQUENCE number (the
    # tier's monotonically increasing per-engine counter), not by tick —
    # a spill's commit slides under slow-I/O windows, its seq doesn't.
    # ``torn_spill_at`` zeroes the tail half of the staged V bytes at
    # commit; ``bitflip_spill_at`` XORs one seeded byte of K; both are
    # taken AFTER the checksum, so verification must catch them.
    # ``slow_host_io=(start_tick, end_tick)`` stalls the depth-one
    # writer's pump for the window (counted as spill_stall_ticks).
    torn_spill_at: Set[int] = field(default_factory=set)
    bitflip_spill_at: Set[int] = field(default_factory=set)
    slow_host_io: Optional[Tuple[int, int]] = None

    def __post_init__(self):
        self._rng = np.random.RandomState(self.seed)
        # separate stream for host-tier byte offsets (same pattern as
        # the fleet plan's migration/storm RNGs): adding host faults
        # never perturbs the decode-error schedule
        self._host_rng = np.random.RandomState(self.seed + 3)
        self._rate_fail_tick: int = -1

    # ---- hooks the engine calls ------------------------------------------

    def poison_nan(self, rid: int) -> "FaultPlan":
        self.nan_rids.add(rid)
        return self

    def tick_begin(self, tick: int) -> None:
        """Advance the injected clock for this tick (base tick_s plus any
        scheduled slowness).  No-op without a ManualClock."""
        if self.clock is not None:
            self.clock.advance(self.clock.tick_s +
                               self.slow_ticks.get(tick, 0.0))

    def decode_should_fail(self, tick: int, attempt: int) -> bool:
        budget = self.decode_errors.get(tick, 0)
        if attempt < budget:
            return True
        if self.decode_error_rate > 0.0:
            if self._rate_fail_tick < tick:
                # one draw per tick regardless of retries, so the retry
                # path doesn't perturb the random schedule
                self._rate_fail_tick = tick
                self._rate_hit = bool(self._rng.random_sample() <
                                      self.decode_error_rate)
            # a random hit poisons exactly the first attempt (transient)
            return self._rate_hit and attempt == 0 and budget == 0
        return False

    def apply_page_pressure(self, tick: int, pool) -> None:
        """Steal up to ``n`` pages across the window, return them at the
        end.  Acquisition retries every tick of the window and
        accumulates — a pool that is fully busy at the start tick still
        gets squeezed as pages free up, so the pressure engages exactly
        when contention is highest."""
        if self.page_pressure is None:
            return
        start, end, n = self.page_pressure
        if start <= tick < end:
            want = int(n) - len(self.held_pages)
            if want > 0 and pool.num_free > 0:
                got = pool.alloc(min(want, pool.num_free))
                if got:
                    self.held_pages.extend(got)
        elif tick >= end and self.held_pages:
            self.release_pressure(pool)

    def release_pressure(self, pool) -> None:
        if self.held_pages:
            pool.free(self.held_pages)
            self.held_pages = []

    def cache_hash_fn(self):
        """The prefix cache's hash override: a constant under
        ``hash_collisions`` (every block collides; token verification
        must carry correctness alone), else None (default hash)."""
        if self.hash_collisions:
            return lambda prev, block: 0xC0111DE
        return None

    def spill_is_torn(self, seq: int) -> bool:
        """True when host-tier spill number ``seq`` commits torn (its
        tail bytes never land)."""
        return seq in self.torn_spill_at

    def spill_bitflip_offset(self, seq: int, nbytes: int) -> Optional[int]:
        """Byte offset to corrupt in spill ``seq``'s K payload, or None.
        One draw from the dedicated host RNG per scheduled flip — drawn
        only for scheduled seqs, so the schedule replays identically
        regardless of how many clean spills interleave."""
        if seq not in self.bitflip_spill_at:
            return None
        return int(self._host_rng.randint(max(1, int(nbytes))))

    def host_io_stalled(self, tick: int) -> bool:
        """True inside the slow-host-I/O window: the depth-one writer's
        pump skips this tick (the staged spill rides along)."""
        if self.slow_host_io is None:
            return False
        start, end = self.slow_host_io
        return start <= tick < end

    def apply_cache_storm(self, tick: int, cache) -> int:
        """Inside the ``cache_storm`` window, flush every reclaimable
        cached page this tick; returns how many were evicted."""
        if cache is None or self.cache_storm is None:
            return 0
        start, end = self.cache_storm
        if start <= tick < end:
            return cache.flush()
        return 0


@dataclass
class FleetFaultPlan:
    """Fleet-level injected failures (``FleetRouter(faults=...)``): the
    per-engine :class:`FaultPlan` kills ticks and slots; this one kills
    REPLICAS.  Same determinism contract — one injected clock the fleet
    advances per tick, scheduled faults keyed by fleet tick, and a
    seeded RNG for the randomized flavor — so a chaos trace replays
    bit-identically.

    Injection points (all host-side):

    - **replica kill** — ``kill_at`` (fleet tick -> replica index)
      marks the replica DEAD at the top of that tick, before it steps:
      its in-flight requests resubmit to survivors.  ``kill_rate`` draws
      once per tick from ``RandomState(seed)`` and kills one seeded-
      random READY replica on a hit.
    - **slow replica** — ``slow_replicas`` (replica index -> period):
      the replica only steps every ``period`` fleet ticks, so its queue
      backs up and healthz-driven balancing must route around it.
    - **heartbeat partition** — ``partitions`` (replica index ->
      (start_tick, end_tick)): the replica's heartbeats are suppressed
      for the window.  Longer than the lease TTL, the fleet declares it
      DEAD; when the partition heals, its stale lease token can no
      longer ack (the zombie-fencing contract from master/service.py).
    - **migration drop** — ``drop_migration_at`` (migration sequence
      numbers) and/or a seeded ``migration_drop_rate``: the page blob is
      lost in flight between export and import, and the router must fall
      back to re-prefilling on the destination (counted as
      ``migration_fallbacks``) with the exactly-once token stream
      preserved.  Draws come from a SEPARATE ``RandomState(seed + 1)``
      so adding migration faults never perturbs the kill schedule.
    - **tenant storm** — ``tenant_storm`` ((tenant, start_tick,
      end_tick, multiplier)): one tenant's arrival rate multiplies by
      ``multiplier`` (plus seeded 0/+1 jitter) for every tick in the
      window — the adversarial load swing the control plane's WFQ must
      isolate.  Jitter draws come from a SEPARATE
      ``RandomState(seed + 2)`` (same pattern as the migration stream)
      so adding a storm never perturbs kill or migration schedules.
    """

    seed: int = 0
    clock: Optional[ManualClock] = None
    kill_at: Dict[int, int] = field(default_factory=dict)
    kill_rate: float = 0.0
    slow_replicas: Dict[int, int] = field(default_factory=dict)
    partitions: Dict[int, Tuple[int, int]] = field(default_factory=dict)
    # page-migration faults (round 16)
    migration_drop_rate: float = 0.0
    drop_migration_at: Set[int] = field(default_factory=set)
    # multi-tenant storm (round 17): (tenant, start_tick, end_tick,
    # multiplier) — None disables
    tenant_storm: Optional[Tuple[str, int, int, int]] = None

    def __post_init__(self):
        self._rng = np.random.RandomState(self.seed)
        self._mig_rng = np.random.RandomState(self.seed + 1)
        self._storm_rng = np.random.RandomState(self.seed + 2)

    def tick_begin(self, tick: int) -> None:
        """Advance the injected clock for this fleet tick (all replicas
        share it).  No-op without a ManualClock."""
        if self.clock is not None:
            self.clock.advance(self.clock.tick_s)

    def kills(self, tick: int, ready: List[int]) -> List[int]:
        """Replica indices to kill at this tick: the scheduled one plus
        at most one seeded-random victim from ``ready``."""
        out: List[int] = []
        if tick in self.kill_at:
            out.append(self.kill_at[tick])
        if self.kill_rate > 0.0 and ready:
            # one draw per tick whether or not it hits, so the schedule
            # is independent of fleet state
            hit = bool(self._rng.random_sample() < self.kill_rate)
            pick = int(self._rng.randint(len(ready)))
            if hit:
                out.append(ready[pick])
        return out

    def replica_steps(self, idx: int, tick: int) -> bool:
        """False when a slow replica skips this fleet tick."""
        period = self.slow_replicas.get(idx, 1)
        return period <= 1 or tick % period == 0

    def heartbeat_blocked(self, idx: int, tick: int) -> bool:
        win = self.partitions.get(idx)
        return win is not None and win[0] <= tick < win[1]

    def drop_migration(self, seq: int) -> bool:
        """True when migration number ``seq`` (the router's monotonically
        increasing per-fleet counter) loses its blob in flight.  One
        draw per call from the dedicated migration RNG, whether or not
        ``migration_drop_rate`` is set, so scheduled and randomized
        flavors replay identically when combined."""
        hit = bool(self._mig_rng.random_sample() < self.migration_drop_rate)
        return seq in self.drop_migration_at or hit

    def storm_factor(self, tick: int, tenant: str) -> int:
        """Arrival-rate multiplier for ``tenant`` at ``tick``: 1 outside
        the storm window (or for other tenants), ``multiplier`` plus
        seeded 0/+1 jitter inside it.  One jitter draw per call whenever
        a storm is configured — window hit or not — so the stream stays
        aligned across replays regardless of who asks on which tick."""
        if self.tenant_storm is None:
            return 1
        who, start, end, mult = self.tenant_storm
        jitter = int(self._storm_rng.randint(2))
        if tenant != who or not (start <= tick < end):
            return 1
        return max(1, int(mult) + jitter)
