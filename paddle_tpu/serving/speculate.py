"""Speculative decoding + real sampling for the serving engine.

Decode dominates chatty serving cost because every emitted token pays a
full fused-step dispatch.  Speculation multiplies tokens per dispatch:
a cheap *proposer* drafts ``k`` candidate tokens per running slot, the
target model scores all ``k+1`` positions in ONE widened unified step
(speculative slots contribute ``k+1`` verify rows instead of 1 — the
exact ragged shape the v2 kernel already consumes for prefill chunks),
and the engine accepts the longest agreeing prefix plus one bonus
token, rolling the rejected suffix back.  Every tick still emits at
least one token, so speculation can slow nothing down besides the
proposer's own (cheap) cost.

Two proposers, selected by ``FLAGS.serving_spec_mode``:

- :class:`NGramProposer` — prompt lookup: match the last ``n`` tokens
  of the slot's own prompt+output history against earlier occurrences
  and propose what followed.  Zero extra model cost; strong on
  repetitive/chatty traffic (quotes, code, templated replies).
- :class:`DraftProposer` — a small :class:`~engine.DecodeModel` with
  its OWN paged KV pool (same ``KVPages``/``PagePool`` machinery as
  the engine, conservation-checked the same way).  Per tick it first
  teacher-forces any history it has not yet materialized (chunked,
  bucketed rows), then drafts ``k`` tokens autoregressively; after the
  verify it rolls its state back to the accepted history.

Acceptance semantics:

- **greedy** (the default, ``sampling=None``): a draft is accepted iff
  it equals the target's argmax at its position — the emitted stream is
  token-identical to non-speculative greedy decoding by construction
  (a rejected position emits the target's own argmax; full acceptance
  emits the bonus argmax).
- **sampled** (:class:`SamplingParams` with ``temperature > 0``):
  standard speculative rejection sampling — accept draft ``d`` with
  probability ``min(1, p(d)/q(d))`` against the *warped* (temperature/
  top-k/top-p) target distribution ``p`` and proposal ``q`` (a point
  mass for the n-gram proposer), else emit a sample from the residual
  ``max(p - q, 0)`` — so the emitted distribution equals plain
  sampling from the target.  All randomness is drawn from counter-based
  per-(seed, position) RNG streams, so replays are bit-identical on
  the injected clock and resubmitted requests re-emit the same tokens
  regardless of how speculation regrouped the ticks.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.platform.flags import FLAGS

__all__ = ["SamplingParams", "NGramProposer", "DraftProposer",
           "next_token", "accept_tokens", "warp_probs", "position_rng"]


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------

# RNG stream ids: one MT19937 stream per (seed, token position, role),
# so every draw is a pure function of request seed + emitted-token
# index — replays, preemption re-prefills and fleet resubmits all
# re-derive identical draws without carrying RNG state.
_STREAM_ACCEPT = 0      # accept/residual/bonus draws (the emission side)
_STREAM_DRAFT = 1       # the draft model's own proposal draws


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy.  ``temperature <= 0`` is greedy
    (argmax — the engine default and the parity-test contract);
    ``top_k``/``top_p`` restrict the warped support (0 / 1.0 = off).
    ``seed`` keys the per-position RNG streams: two replays of the same
    request emit bit-identical tokens."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        enforce_that(self.temperature >= 0.0,
                     "temperature must be >= 0", context="serving-spec")
        enforce_that(self.top_k >= 0, "top_k must be >= 0",
                     context="serving-spec")
        enforce_that(0.0 < self.top_p <= 1.0,
                     "top_p must be in (0, 1]", context="serving-spec")

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def position_rng(seed: int, position: int, stream: int = _STREAM_ACCEPT
                 ) -> np.random.RandomState:
    """Counter-based RNG: one deterministic stream per (seed, position,
    stream).  MT19937's init_by_array seeding makes this a pure
    function of its arguments — no state is carried across tokens, so
    the draw for emitted-token ``position`` is identical whether the
    token arrived speculatively, non-speculatively, or on a replay
    after a preemption or fleet resubmit."""
    return np.random.RandomState(
        [int(seed) & 0xFFFFFFFF, int(position) & 0xFFFFFFFF,
         0x5BEC0DE ^ int(stream)])


def warp_probs(logits: np.ndarray, s: SamplingParams) -> np.ndarray:
    """The warped target/proposal distribution: temperature, then
    top-k, then nucleus (top-p) truncation, renormalized.  f64
    throughout so two replays (and the accept-vs-residual arithmetic)
    cannot diverge on rounding."""
    z = np.asarray(logits, np.float64)
    z = z / max(float(s.temperature), 1e-6)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    if s.top_k and s.top_k < p.size:
        cut = np.partition(p, -s.top_k)[-s.top_k]
        p = np.where(p >= cut, p, 0.0)
    if s.top_p < 1.0:
        order = np.argsort(-p, kind="stable")
        csum = np.cumsum(p[order])
        # keep the smallest prefix reaching top_p (always >= 1 token)
        keep_n = int(np.searchsorted(csum, s.top_p, side="left")) + 1
        mask = np.zeros_like(p)
        mask[order[:keep_n]] = 1.0
        p = p * mask
    tot = p.sum()
    if tot <= 0.0:              # degenerate logits: fall back to argmax
        p = np.zeros_like(p)
        p[int(np.argmax(logits))] = 1.0
        return p
    return p / tot


def _draw(probs: np.ndarray, rng: np.random.RandomState) -> int:
    csum = np.cumsum(probs)
    u = rng.random_sample() * csum[-1]
    return int(min(np.searchsorted(csum, u, side="right"),
                   probs.size - 1))


def next_token(logits: np.ndarray, sampling: Optional[SamplingParams],
               position: int) -> int:
    """One non-speculative emission: argmax when greedy (``sampling``
    None or temperature 0 — bit-identical to the historical engine
    behavior), else a seeded draw from the warped distribution.
    ``position`` is the index of this token in the request's generated
    stream (the RNG counter)."""
    if sampling is None or sampling.greedy:
        return int(np.argmax(logits))
    rng = position_rng(sampling.seed, position)
    return _draw(warp_probs(logits, sampling), rng)


def accept_tokens(rows: np.ndarray, drafts: Sequence[int],
                  draft_probs: Optional[np.ndarray],
                  sampling: Optional[SamplingParams],
                  position: int, eos_id: int) -> Tuple[List[int], int]:
    """The verify walk: score ``drafts`` against the target logits and
    return ``(emitted tokens, accepted draft count)``.

    ``rows`` is ``[len(drafts) + 1, V]`` — row ``i`` is the target's
    logits after history + ``drafts[:i]`` (row 0 is the plain next-token
    distribution, so with no drafts this degenerates to exactly one
    non-speculative emission).  ``draft_probs`` is the proposer's warped
    distribution per draft (``[k, V]``) or None for a point-mass
    proposer (n-gram, or any greedy draft).  ``position`` indexes the
    first emitted token in the request's generated stream.

    Greedy: accept while ``argmax(rows[i]) == drafts[i]``; the first
    disagreement emits the target's own argmax instead, full agreement
    emits the bonus ``argmax(rows[k])`` — token-identical to the
    non-speculative stream by induction.  Sampled: standard rejection
    sampling (accept w.p. ``min(1, p/q)``, residual ``max(p − q, 0)``
    renormalized, bonus sampled from ``rows[k]``), which preserves the
    target distribution exactly.  An accepted/emitted EOS ends the walk
    (nothing is emitted past it)."""
    emitted: List[int] = []
    greedy = sampling is None or sampling.greedy
    for i, d in enumerate(drafts):
        d = int(d)
        if greedy:
            g = int(np.argmax(rows[i]))
            if g != d:
                emitted.append(g)          # rejection: the target's token
                return emitted, i
        else:
            p = warp_probs(rows[i], sampling)
            if draft_probs is not None:
                # a HOST numpy row (the proposer already synced it), so
                # this asarray is a dtype view, never a device readback
                q = np.asarray(draft_probs[i], np.float64)  # lint: allow(host-sync)
            else:
                q = np.zeros(p.shape, np.float64)
                q[d] = 1.0
            rng = position_rng(sampling.seed, position + i)
            ratio = 0.0 if q[d] <= 0.0 else min(1.0, p[d] / q[d])
            if rng.random_sample() >= ratio:
                resid = np.maximum(p - q, 0.0)
                tot = resid.sum()
                # numerically-empty residual (p ~= q): any p-sample is
                # distribution-correct
                emitted.append(_draw(resid / tot if tot > 0.0 else p, rng))
                return emitted, i
        emitted.append(d)
        if d == eos_id:
            return emitted, i + 1          # accepted EOS: no bonus token
    # every draft accepted: one bonus token from the last row
    k = len(drafts)
    if greedy:
        emitted.append(int(np.argmax(rows[k])))
    else:
        rng = position_rng(sampling.seed, position + k)
        emitted.append(_draw(warp_probs(rows[k], sampling), rng))
    return emitted, k


# ---------------------------------------------------------------------------
# Proposers
# ---------------------------------------------------------------------------


class Proposer:
    """Structural proposer contract the engine drives.  ``propose``
    returns ``{rid: (drafts, warped proposal probs or None)}`` for the
    eligible requests; ``commit``/``release``/``check_conservation``
    are state hooks only the draft-model proposer needs."""

    def propose(self, requests, k_for) -> Dict[int, Tuple[List[int],
                                                          Optional[np.ndarray]]]:
        raise NotImplementedError

    def commit(self, req) -> None:      # accepted history is now truth
        pass

    def release(self, rid: int) -> None:
        pass

    def check_conservation(self) -> None:
        pass


class NGramProposer(Proposer):
    """Prompt-lookup speculation: match the last ``n`` tokens of the
    slot's own prompt+output history against earlier occurrences (most
    recent match wins; falls back to shorter suffixes down to 1) and
    propose the ``k`` tokens that followed.  Zero model cost, so even a
    low acceptance rate is pure profit; repetitive traffic (the chatty
    serving shape) accepts most drafts."""

    def __init__(self, n: Optional[int] = None):
        self.n = int(n if n is not None else FLAGS.serving_spec_ngram)
        enforce_that(self.n >= 1, "n-gram size must be >= 1",
                     context="serving-spec")

    def propose_one(self, history: Sequence[int], k: int) -> List[int]:
        h = list(history)
        ln = len(h)
        if k <= 0 or ln < 2:
            return []
        for size in range(min(self.n, ln - 1), 0, -1):
            tail = h[ln - size:]
            # most recent earlier occurrence WITH a full k-token
            # continuation wins (scan match ends backwards, stop at the
            # first full one); matches truncated by the history end —
            # ubiquitous inside repeated runs, where the nearest match
            # sits one period back — only win if nothing fuller exists
            best = None
            for end in range(ln - 1, size - 1, -1):
                if h[end - size:end] == tail:
                    cont = min(k, ln - end)
                    if best is None or cont > best[1]:
                        best = (end, cont)
                    if cont >= k:
                        break
            if best is not None:
                end, cont = best
                return h[end:end + cont]
            # no match at this size: try a shorter suffix
        return []

    def propose(self, requests, k_for):
        out = {}
        for req in requests:
            drafts = self.propose_one(req.cache_tokens, k_for(req))
            if drafts:
                out[req.rid] = (drafts, None)
        return out


@dataclass
class _DraftSeq:
    """Per-request draft-model cache state: ``tokens`` is the history
    whose KV is materialized in ``pages`` (positions 0..len-1)."""

    tokens: List[int]
    pages: List[int]


class DraftProposer(Proposer):
    """Draft-model speculation: a small :class:`DecodeModel` sharing
    the engine's page/pool machinery via its OWN ``KVPages`` pool.

    Per tick the engine hands it the running slots; for each it (1)
    teacher-forces any history tokens its cache has not materialized —
    batched across slots, chunked to a small row-bucket ladder so the
    jitted draft step compiles a bounded number of shapes — and (2)
    drafts ``k`` tokens autoregressively (greedy argmax, or seeded
    draws from its warped distribution when the request samples,
    returning the warped proposal rows for rejection sampling).  After
    the verify, :meth:`commit` rolls the state back to the accepted
    history (longest common prefix — accepted drafts stay materialized,
    rejected ones are overwritten next catch-up) and frees lookahead
    pages past it, so the draft pool obeys the same conservation
    arithmetic as the main pool (:meth:`check_conservation`)."""

    # catch-up row buckets per slot (rows beyond the top loop extra
    # dispatches); drafting itself always uses the 1-row shape
    CATCHUP_BUCKETS = (1, 8, 32, 128)

    def __init__(self, model, params, *, page_size: int, num_pages: int,
                 max_pages_per_seq: int, max_slots: int,
                 use_kernel: bool = False):
        from paddle_tpu.analysis.retrace import SiteContract, audit_jit
        from paddle_tpu.serving.kv_cache import PagedKVConfig, PagePool, \
            init_kv_pages

        self.model = model
        self.params = params
        self.cfg = PagedKVConfig(
            num_layers=model.num_layers, num_heads=model.num_heads,
            head_dim=model.head_dim, page_size=int(page_size),
            num_pages=int(num_pages),
            max_pages_per_seq=int(max_pages_per_seq),
            num_kv_heads=int(getattr(model, "num_kv_heads", 0)
                             or model.num_heads))
        self._kv = init_kv_pages(self.cfg)
        self.pool = PagePool(int(num_pages))
        self.max_slots = int(max_slots)
        self._use_kernel = bool(use_kernel)
        self._state: Dict[int, _DraftSeq] = {}
        self._fns: Dict[int, object] = {}
        self.steps = 0               # draft-model dispatches
        self.step_time_s = 0.0       # wall time inside draft dispatches
        # the draft pool is donated exactly like the engine's (the
        # returned pool overwrites self._kv every call); budgets are
        # generous guardrails like the engine's own
        self._contract = SiteContract(
            per_tick=True, donate=(1,),
            peak_bytes=4 * self.cfg.kv_bytes() + (1 << 26),
            flops=1e12)
        self._audit_jit = audit_jit

    # ---- compiled draft step --------------------------------------------

    def _fn(self, rows: int):
        fn = self._fns.get(rows)
        if fn is not None:
            return fn
        from paddle_tpu.serving.decode_attention import \
            ragged_paged_attention
        from paddle_tpu.serving.kv_cache import NULL_PAGE, append_token

        import jax.numpy as jnp

        model, cfg = self.model, self.cfg
        b, page, r = self.max_slots, cfg.page_size, int(rows)
        use_kernel = self._use_kernel or None

        def raw(params, kv, tokens, pos, valid, table, att_lens):
            # tokens/pos/valid: [B, R] slot-major rows; att_lens: [B]
            # valid KV per slot AFTER this step's writes.  Returns
            # logits for EVERY row ([B, R, V]) — catch-up reads only
            # each slot's last valid row, drafting reads row 0.
            t = tokens.reshape(-1)
            p = jnp.maximum(pos.reshape(-1), 0)
            v = valid.reshape(-1)
            seq = jnp.repeat(jnp.arange(b), r)
            x = model.embed(params, t, p)
            pages = jnp.where(v, table[seq, p // page], NULL_PAGE)
            offs = p % page
            qpos = jnp.where(v, p, -1)
            wmask = v[:, None, None]
            for l in range(cfg.num_layers):
                q, k, vv = model.qkv(params, l, x)
                kv = append_token(kv, l, jnp.where(wmask, k, 0.0),
                                  jnp.where(wmask, vv, 0.0), pages, offs)
                ctx = ragged_paged_attention(
                    q, kv.k[l], kv.v[l], table, att_lens, seq, qpos,
                    k_scale=kv.k_scale[l] if kv.k_scale is not None
                    else None,
                    v_scale=kv.v_scale[l] if kv.v_scale is not None
                    else None, use_kernel=use_kernel)
                x = model.attn_out(params, l, ctx, x)
            logits = model.logits(params, x)
            return logits.reshape(b, r, -1), kv

        fn = self._audit_jit(raw, site="serving.draft",
                             donate_argnums=(1,),
                             xla_contract=self._contract)
        self._fns[rows] = fn
        return fn

    def _dispatch(self, rows: int, tokens, pos, valid, table, att_lens):
        import jax.numpy as jnp

        t0 = time.perf_counter()   # lint: allow(wall-clock) — honest
        #                            device timing of the draft step
        #                            (a metric, never a control input)
        logits, self._kv = self._fn(rows)(
            self.params, self._kv, jnp.asarray(tokens), jnp.asarray(pos),
            jnp.asarray(valid), jnp.asarray(table),
            jnp.asarray(att_lens))
        out = np.asarray(logits)
        self.steps += 1
        self.step_time_s += time.perf_counter() - t0  # lint: allow(wall-clock)
        return out

    # ---- host-side state ------------------------------------------------

    def _ensure_pages(self, st: _DraftSeq, upto_len: int) -> bool:
        """Grow ``st.pages`` to cover ``upto_len`` tokens; False if the
        draft pool is dry or the table is full (the caller skips the
        slot this tick — speculation, not correctness)."""
        page = self.cfg.page_size
        while len(st.pages) * page < upto_len:
            if len(st.pages) >= self.cfg.max_pages_per_seq:
                return False
            got = self.pool.alloc(1)
            if got is None:
                return False
            st.pages.extend(got)
        return True

    def _bucket(self, need: int) -> int:
        for bkt in self.CATCHUP_BUCKETS:
            if need <= bkt:
                return bkt
        return self.CATCHUP_BUCKETS[-1]

    def propose(self, requests, k_for):
        reqs = [r for r in requests if k_for(r) > 0]
        for req in reqs:
            if req.rid not in self._state:
                self._state[req.rid] = _DraftSeq(tokens=[], pages=[])
        # ---- phase 1: batched teacher-forced catch-up -------------------
        while True:
            needs = {}
            for req in reqs:
                st = self._state[req.rid]
                hist = req.cache_tokens
                # a diverged stored suffix (rejected drafts) is simply
                # re-forced: truncate to the common prefix first
                cp = _common_prefix(st.tokens, hist)
                del st.tokens[cp:]
                gap = len(hist) - len(st.tokens)
                if gap > 0:
                    needs[req.rid] = gap
            if not needs:
                break
            bkt = self._bucket(max(needs.values()))
            tokens = np.zeros((self.max_slots, bkt), np.int32)
            pos = np.zeros((self.max_slots, bkt), np.int32)
            valid = np.zeros((self.max_slots, bkt), bool)
            table = np.zeros((self.max_slots, self.cfg.max_pages_per_seq),
                             np.int32)
            att = np.zeros((self.max_slots,), np.int32)
            rows_of = {}
            for slot, req in enumerate(reqs):
                gap = needs.get(req.rid, 0)
                if gap <= 0:
                    continue
                st = self._state[req.rid]
                n = min(gap, bkt)
                start = len(st.tokens)
                if not self._ensure_pages(st, start + n):
                    needs.pop(req.rid, None)   # dry pool: skip this slot
                    continue
                hist = req.cache_tokens
                tokens[slot, :n] = hist[start:start + n]
                pos[slot, :n] = np.arange(start, start + n)
                valid[slot, :n] = True
                table[slot, :len(st.pages)] = st.pages
                att[slot] = start + n
                rows_of[slot] = (req, n)
            if not rows_of:
                break
            self._dispatch(bkt, tokens, pos, valid, table, att)
            for slot, (req, n) in rows_of.items():
                st = self._state[req.rid]
                hist = req.cache_tokens
                st.tokens.extend(hist[len(st.tokens):len(st.tokens) + n])
        # ---- phase 2: autoregressive drafting ---------------------------
        out: Dict[int, Tuple[List[int], Optional[np.ndarray]]] = {}
        live = []
        for req in reqs:
            st = self._state[req.rid]
            if st.tokens and st.tokens == list(req.cache_tokens):
                live.append(req)
        if not live:
            return out
        drafts = {req.rid: [] for req in live}
        probs: Dict[int, List[np.ndarray]] = {req.rid: [] for req in live}
        kmax = max(k_for(r) for r in live)
        for step in range(kmax):
            tokens = np.zeros((self.max_slots, 1), np.int32)
            pos = np.zeros((self.max_slots, 1), np.int32)
            valid = np.zeros((self.max_slots, 1), bool)
            table = np.zeros((self.max_slots, self.cfg.max_pages_per_seq),
                             np.int32)
            att = np.zeros((self.max_slots,), np.int32)
            active = []
            for slot, req in enumerate(live):
                if len(drafts[req.rid]) < step:
                    continue            # this slot stopped drafting
                if step >= k_for(req):
                    continue
                st = self._state[req.rid]
                # the row feeds the LAST known token; its logits draft
                # the next.  Position = len-1's successor slot...
                feed = (st.tokens + drafts[req.rid])[-1]
                p = len(st.tokens) + len(drafts[req.rid]) - 1
                if not self._ensure_pages(st, p + 1):
                    continue
                tokens[slot, 0] = feed
                pos[slot, 0] = p
                valid[slot, 0] = True
                table[slot, :len(st.pages)] = st.pages
                att[slot] = p + 1
                active.append((slot, req))
            if not active:
                break
            logits = self._dispatch(1, tokens, pos, valid, table, att)
            for slot, req in active:
                row = logits[slot, 0]
                s = req.sampling
                base = len(req.generated)
                if s is None or s.greedy:
                    tok = int(np.argmax(row))
                    probs[req.rid] = None   # point mass: exact-match walk
                else:
                    wp = warp_probs(row, s)
                    rng = position_rng(s.seed, base + step, _STREAM_DRAFT)
                    tok = _draw(wp, rng)
                    probs[req.rid].append(wp)
                drafts[req.rid].append(tok)
        for req in live:
            dr = drafts[req.rid]
            if not dr:
                continue
            pr = probs[req.rid]
            out[req.rid] = (list(dr), np.stack(pr) if pr else None)
            # record as materialized ONLY the drafts whose KV was
            # actually written: drafting step j FEEDS (and writes)
            # token j-1, so the LAST draft was produced but never fed —
            # claiming it would leave a zero-KV hole at its position
            # that every later draft would silently attend over
            self._state[req.rid].tokens.extend(dr[:-1])
        return out

    def commit(self, req) -> None:
        """Verify finished: roll the draft state back to the accepted
        history (a rejected suffix keeps its pages' junk — it is simply
        re-forced over next tick) and free lookahead pages past it."""
        st = self._state.get(req.rid)
        if st is None:
            return
        hist = req.cache_tokens
        cp = _common_prefix(st.tokens, hist)
        del st.tokens[cp:]
        page = self.cfg.page_size
        needed = -(-len(st.tokens) // page)
        if len(st.pages) > needed:
            extra = st.pages[needed:]
            del st.pages[needed:]
            self.pool.free(extra)

    def release(self, rid: int) -> None:
        st = self._state.pop(rid, None)
        if st is not None and st.pages:
            self.pool.free(st.pages)

    def check_conservation(self) -> None:
        """The draft pool's REF-LEAK twin: pages held by live draft
        states must equal the pool's refcounts (no sharing, no cache —
        refcounts are all 1)."""
        from paddle_tpu.serving.faults import PageLeakError

        held = sum(len(st.pages) for st in self._state.values())
        if held != self.pool.total_refs:
            raise PageLeakError(
                f"REF-LEAK: draft pool held={held} "
                f"refs={self.pool.total_refs} free={self.pool.num_free} "
                f"usable={self.pool.num_usable}")
        if self.pool.num_free + self.pool.num_in_use != \
                self.pool.num_usable:
            raise PageLeakError(
                f"PAGE-LEAK: draft pool free={self.pool.num_free} "
                f"in_use={self.pool.num_in_use} "
                f"usable={self.pool.num_usable}")


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i
