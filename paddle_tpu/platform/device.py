"""Device & mesh discovery — the TPUPlace/DeviceContext analog.

Reference: paddle/platform/place.h (CPUPlace/GPUPlace) and paddle.init()
(python/paddle/v2/__init__.py:65-86) which parsed use_gpu/trainer_count into
gflags. On TPU the analog is: discover the chips JAX sees, build a
``jax.sharding.Mesh`` over them (ICI within a slice, DCN across slices), and
hold it as the process-global default mesh every parallel component uses.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.platform.flags import FLAGS

_state = {
    "initialized": False,
    "mesh": None,
    "devices": None,
}


def _parse_mesh_flags() -> Tuple[Optional[Tuple[int, ...]], Tuple[str, ...]]:
    shape = None
    if FLAGS.mesh_shape:
        shape = tuple(int(x) for x in str(FLAGS.mesh_shape).split(",") if x)
    axes = tuple(a.strip() for a in str(FLAGS.mesh_axes).split(",") if a.strip())
    return shape, axes


def init(**kwargs) -> None:
    """Initialize the framework: set flags, discover devices, build the mesh.

    ``paddle.init(use_gpu=..., trainer_count=...)`` analog. Keyword args are
    flag overrides (see platform.flags); mesh construction reads ``mesh_shape``
    / ``mesh_axes``. Safe to call more than once — later calls rebuild the mesh.

    Multi-host: pass ``coordinator_address=`` (plus optional
    ``num_processes=``/``process_id=``) to join a multi-host job via
    JAX's coordination service — the etcd-registration analog
    (go/pserver/etcd_client.go:67-166); afterwards jax.devices() spans
    every host and meshes/collectives ride ICI within a slice and DCN
    across (see parallel.mesh.hybrid_mesh).
    """
    import jax  # deferred so flag 'platform' can take effect first

    coord = kwargs.pop("coordinator_address", None)
    nproc = kwargs.pop("num_processes", None)
    pid = kwargs.pop("process_id", None)
    enforce_that(coord is not None or (nproc is None and pid is None),
                 "num_processes/process_id need coordinator_address= — "
                 "refusing to silently run single-host", context="init")
    if coord is not None:
        prev = _state.get("distributed")
        enforce_that(prev is None or prev == coord,
                     f"jax.distributed already initialized against {prev}; "
                     f"cannot re-initialize against {coord}", context="init")
        if prev is None:
            dist_kw = {"coordinator_address": coord}
            if nproc is not None:
                dist_kw["num_processes"] = int(nproc)
            if pid is not None:
                dist_kw["process_id"] = int(pid)
            try:
                jax.distributed.initialize(**dist_kw)
            except RuntimeError as e:
                # most common cause: some paddle/jax API already touched
                # the backend (jax.devices() etc.) — surface the ordering
                # requirement instead of the deep-JAX error
                raise EnforceError(
                    "paddle.init(coordinator_address=...) must be the "
                    "FIRST paddle/jax call in the process (the JAX "
                    f"backend is already initialized): {e}",
                    context="init") from e
            _state["distributed"] = coord

    FLAGS.update(**kwargs)
    if FLAGS.platform:
        jax.config.update("jax_platforms", FLAGS.platform)
    if FLAGS.check_nan:
        jax.config.update("jax_debug_nans", True)

    devices = jax.devices()
    _state["devices"] = devices

    shape, axes = _parse_mesh_flags()
    if shape is None:
        shape = (len(devices),)
    if len(axes) < len(shape):
        raise EnforceError(
            f"mesh_axes {axes} shorter than mesh_shape {shape}", context="init"
        )
    axes = axes[: len(shape)]
    n_needed = int(np.prod(shape))
    enforce_that(
        n_needed <= len(devices),
        f"mesh_shape {shape} needs {n_needed} devices, found {len(devices)}",
        context="init",
    )
    mesh_devices = np.asarray(devices[:n_needed]).reshape(shape)
    _state["mesh"] = jax.sharding.Mesh(mesh_devices, axes)
    _state["initialized"] = True


def is_initialized() -> bool:
    return _state["initialized"]


def _ensure_init() -> None:
    if not _state["initialized"]:
        init()


def default_mesh():
    """The process-global device mesh (builds a 1-D 'data' mesh on demand)."""
    _ensure_init()
    return _state["mesh"]


def set_default_mesh(mesh) -> None:
    _state["mesh"] = mesh
    _state["initialized"] = True


def device_count() -> int:
    _ensure_init()
    return len(_state["devices"])


def devices() -> Sequence:
    _ensure_init()
    return list(_state["devices"])


def platform_name() -> str:
    _ensure_init()
    return _state["devices"][0].platform
