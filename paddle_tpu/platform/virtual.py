"""Virtual-CPU platform forcing, shared by every driver-facing entry
point (__graft_entry__, bench.py, tests/conftest.py).

The simulation trick: XLA's host platform splits into N virtual devices
when ``--xla_force_host_platform_device_count=N`` is set BEFORE the CPU
client is created — the in-process multi-node test strategy (reference:
pserver/test/test_ParameterServer2.cpp spins servers+clients in one
process). Two environment hazards make this fiddly:

- jax may already be imported (sitecustomize) with its config snapshotted,
  so the JAX_PLATFORMS env var alone is read too late — jax.config must
  be updated too;
- XLA_FLAGS may already carry a DIFFERENT device count, which must be
  replaced, not merely detected.
"""

from __future__ import annotations

import re
from typing import Dict, MutableMapping, Optional

_FLAG = "--xla_force_host_platform_device_count"


def set_device_count_flag(environ: MutableMapping[str, str],
                          n_devices: int) -> None:
    """Set (or REPLACE) the virtual-device-count flag in environ['XLA_FLAGS'].

    Presence-checking is not enough: a pre-existing `=1` from some other
    harness would silently win and the n-device mesh build would fail."""
    flags = environ.get("XLA_FLAGS", "")
    if _FLAG in flags:
        flags = re.sub(rf"{_FLAG}=\d+", f"{_FLAG}={n_devices}", flags)
    else:
        flags = f"{flags} {_FLAG}={n_devices}".strip()
    environ["XLA_FLAGS"] = flags


def virtual_cpu_env(base_env: Dict[str, str], n_devices: int,
                    extra_pythonpath: Optional[str] = None) -> Dict[str, str]:
    """Child-process env with an n-device CPU platform forced and any
    TPU-relay site hook (.axon_site) stripped — a pure-CPU child must not
    spend its timeout budget probing a tunnel."""
    env = dict(base_env)
    env["JAX_PLATFORMS"] = "cpu"
    set_device_count_flag(env, n_devices)
    parts = ([extra_pythonpath] if extra_pythonpath else []) \
        + env.get("PYTHONPATH", "").split(":")
    env["PYTHONPATH"] = ":".join(
        p for p in parts if p and ".axon_site" not in p)
    return env


def force_cpu_inproc(n_devices: int) -> bool:
    """Force an n-device virtual CPU platform in THIS process.

    Returns True when the current process can run on the virtual CPU mesh;
    False when a non-CPU backend is already initialized (too late — the
    caller must re-exec in a clean subprocess, see virtual_cpu_env)."""
    import os

    set_device_count_flag(os.environ, n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    from jax._src import xla_bridge

    if not bool(getattr(xla_bridge, "_backends", None)):
        # env alone is not enough: jax may be pre-imported (sitecustomize)
        # with its config already snapshotted — set it explicitly
        jax.config.update("jax_platforms", "cpu")
        return True
    try:
        return (jax.default_backend() == "cpu"
                and jax.device_count() >= n_devices)
    except Exception:
        return False
