"""Error machinery — the ``PADDLE_ENFORCE`` analog.

Reference: paddle/platform/enforce.h (PADDLE_ENFORCE/PADDLE_THROW macros that
raise EnforceNotMet with source context). Here: a small exception type plus
check helpers that format rich messages; used across the framework instead of
bare asserts so user errors carry layer/op context.
"""

from __future__ import annotations


class EnforceError(RuntimeError):
    """Raised when a framework invariant or user-facing check fails."""

    def __init__(self, message: str, *, context: str | None = None):
        self.context = context
        if context:
            message = f"[{context}] {message}"
        super().__init__(message)


def enforce_that(cond: bool, message: str = "enforce failed", *, context: str | None = None) -> None:
    if not cond:
        raise EnforceError(message, context=context)


def enforce_eq(a, b, message: str = "", *, context: str | None = None) -> None:
    if a != b:
        raise EnforceError(f"expected {a!r} == {b!r}. {message}", context=context)


def enforce_in(value, allowed, message: str = "", *, context: str | None = None) -> None:
    if value not in allowed:
        raise EnforceError(
            f"expected one of {list(allowed)!r}, got {value!r}. {message}", context=context
        )


def enforce_rank(shape, rank: int, message: str = "", *, context: str | None = None) -> None:
    if len(shape) != rank:
        raise EnforceError(
            f"expected rank-{rank} shape, got {tuple(shape)} (rank {len(shape)}). {message}",
            context=context,
        )
