"""Named timer/stat system — the REGISTER_TIMER analog.

Reference: paddle/utils/Stat.h:114,230-297 (REGISTER_TIMER* macros feeding a
global StatSet printed periodically; REGISTER_GPU_PROFILER windows for nvprof).
Here: a context-manager/decorator timer aggregating into a global table, plus
hooks into the jax profiler for trace windows (the cudaProfiler analog).

Note on semantics: JAX dispatch is async — a timer around a jitted call
measures dispatch unless the caller blocks. ``timer(..., block=<result
pytree or zero-arg callable>)`` calls ``block_until_ready`` on it before
the clock stops, for honest device timings.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class StatEntry:
    total: float = 0.0
    count: int = 0
    max: float = 0.0
    min: float = float("inf")

    def add(self, seconds: float) -> None:
        self.total += seconds
        self.count += 1
        self.max = max(self.max, seconds)
        self.min = min(self.min, seconds)

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0


class StatSet:
    def __init__(self):
        self._lock = threading.Lock()
        # entries AND the StatEntry counters inside them: get()/
        # snapshot() copy under the lock precisely because a timer on
        # another thread mutates (count, total) as a pair
        self._entries: Dict[str, StatEntry] = {}   # guarded_by(_lock)

    @contextlib.contextmanager
    def timer(self, name: str, block=None):
        """Time a window into the named entry.  JAX dispatch is async,
        so a bare timer measures dispatch latency; pass ``block=`` (an
        array/pytree, or a zero-arg callable returning one — the result
        usually doesn't exist yet at ``with`` time) to sync on it
        before the clock stops, recording honest device time::

            with stats.timer("train_step", block=lambda: out[0]):
                out[0] = step_fn(params, batch)
        """
        start = time.perf_counter()
        ok = False
        try:
            yield
            ok = True
        finally:
            # sync ONLY when the body completed: on an exception the
            # result usually doesn't exist, and evaluating block()
            # would raise from the finally clause and MASK the real
            # error (the elapsed dispatch time is still recorded)
            if ok and block is not None:
                import jax

                # the POINT of block=: ONE deliberate end-of-window
                # sync so the recorded time covers device execution
                jax.block_until_ready(   # lint: allow(host-sync)
                    block() if callable(block) else block)
            elapsed = time.perf_counter() - start
            with self._lock:
                self._entries.setdefault(name, StatEntry()).add(elapsed)

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._entries.setdefault(name, StatEntry()).add(seconds)

    def get(self, name: str) -> Optional[StatEntry]:
        """Snapshot of one entry.  Takes the lock and returns a COPY:
        the previous lock-free read handed back the live mutable entry,
        so a reader summing ``total``/``count`` while a timer thread
        called ``add`` could see a torn pair (count bumped, total not
        yet) — the two-thread stress test in tests/test_obs.py pins the
        fixed behavior."""
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                return None
            return StatEntry(total=e.total, count=e.count, max=e.max,
                             min=e.min)

    def snapshot(self) -> Dict[str, StatEntry]:
        """Copied view of every entry (same locking contract as
        :meth:`get` — safe to iterate while timers run)."""
        with self._lock:
            return {name: StatEntry(total=e.total, count=e.count,
                                    max=e.max, min=e.min)
                    for name, e in self._entries.items()}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()

    def publish(self, registry, prefix: str = "stat_", **labels) -> None:
        """Publish every timer into an obs
        :class:`~paddle_tpu.obs.registry.MetricsRegistry` — the scrape
        path that replaces ad-hoc :meth:`report` prints: per timer name,
        ``<prefix>seconds_total`` / ``<prefix>calls`` /
        ``<prefix>seconds_max`` gauges labeled ``name=<timer>``."""
        for name, e in sorted(self.snapshot().items()):
            lbl = dict(labels, name=name)
            registry.gauge(prefix + "seconds_total").labels(**lbl).set(
                e.total)
            registry.gauge(prefix + "calls").labels(**lbl).set(e.count)
            registry.gauge(prefix + "seconds_max").labels(**lbl).set(e.max)

    def report(self) -> str:
        """Formatted table like the reference's StatSet print
        (Stat.h:114).  DEPRECATED as a scrape surface: prefer
        :meth:`publish` into the obs registry (one text/snapshot export
        for timers, serving metrics, and fleet counters alike); this
        stays for interactive debugging."""
        lines = ["======= StatSet ======="]
        lines.append(f"{'name':<40} {'calls':>8} {'total(ms)':>12} {'avg(ms)':>10} {'max(ms)':>10}")
        for name, e in sorted(self.snapshot().items()):
            lines.append(
                f"{name:<40} {e.count:>8} {e.total * 1e3:>12.3f} "
                f"{e.avg * 1e3:>10.3f} {e.max * 1e3:>10.3f}"
            )
        return "\n".join(lines)


_GLOBAL = StatSet()


def timer(name: str, block=None):
    """``with timer('forwardBackward'): ...`` — aggregates into the
    global set; ``block=`` as in :meth:`StatSet.timer` (sync on the
    result for honest device timings)."""
    return _GLOBAL.timer(name, block=block)


def add_sample(name: str, seconds: float) -> None:
    _GLOBAL.add(name, seconds)


def timer_stats() -> StatSet:
    return _GLOBAL


def reset_stats() -> None:
    _GLOBAL.reset()


@contextlib.contextmanager
def profiler_window(logdir: str = "/tmp/paddle_tpu_trace"):
    """jax profiler trace window — the REGISTER_GPU_PROFILER analog.

    Produces an xplane trace viewable in TensorBoard/Perfetto instead of an
    nvprof window (reference: utils/Stat.h:293-297).
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
