"""Logging wrapper — the glog-style utils/Logging.h analog."""

from __future__ import annotations

import logging
import sys

_LOGGER = None


def logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        from paddle_tpu.platform.flags import FLAGS

        log = logging.getLogger("paddle_tpu")
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s] %(message)s", "%H:%M:%S")
        )
        log.addHandler(handler)
        log.setLevel(getattr(logging, str(FLAGS.log_level).upper(), logging.INFO))
        log.propagate = False
        _LOGGER = log
    return _LOGGER


def info(msg, *args):
    logger().info(msg, *args)


def warning(msg, *args):
    logger().warning(msg, *args)


def error(msg, *args):
    logger().error(msg, *args)


def debug(msg, *args):
    logger().debug(msg, *args)
