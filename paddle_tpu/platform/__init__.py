"""Platform substrate: device/mesh discovery, flags, logging, timers, errors.

TPU-native analog of the reference's ``paddle/platform`` (Place/DeviceContext/
dynload), ``paddle/utils`` (Flags.cpp, Logging.h, Stat.h) and ``paddle/memory``.
On TPU, XLA/PJRT owns device memory and streams, so the substrate here is about
*mesh topology*, configuration, observability and error machinery rather than
allocators and cuda handles.
"""

from paddle_tpu.platform import device
from paddle_tpu.platform import enforce
from paddle_tpu.platform import flags
from paddle_tpu.platform import stats
from paddle_tpu.platform.device import init, default_mesh, device_count
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.platform.stats import timer, timer_stats, reset_stats
