"""Global flag registry — the gflags analog.

Reference: paddle/utils/Flags.cpp:18-81 centralizes process flags (use_gpu,
trainer_count, ports, log_period, ...) and python/paddle/v2/__init__.py:65-86
surfaces them via ``paddle.init(**kwargs)`` + ``PADDLE_INIT_*`` env vars.

Here flags are a typed registry populated from defaults < environment
(``PADDLE_TPU_<NAME>``) < ``init(**kwargs)``. TPU-era flags replace the GPU/
pserver ones: mesh axis sizes instead of trainer_count/num_gradient_servers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict

from paddle_tpu.platform.enforce import EnforceError

_ENV_PREFIX = "PADDLE_TPU_"


@dataclass
class _FlagSpec:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


class _Flags:
    """Typed global flags with attribute access (``FLAGS.log_period``)."""

    def __init__(self):
        object.__setattr__(self, "_specs", {})
        object.__setattr__(self, "_values", {})

    def define(self, name: str, default: Any, help: str = "", parser=None) -> None:
        if parser is None:
            if isinstance(default, bool):
                parser = _parse_bool
            elif isinstance(default, int):
                parser = int
            elif isinstance(default, float):
                parser = float
            else:
                parser = str
        self._specs[name] = _FlagSpec(name, default, parser, help)
        env = os.environ.get(_ENV_PREFIX + name.upper())
        self._values[name] = parser(env) if env is not None else default

    def set(self, name: str, value: Any) -> None:
        if name not in self._specs:
            raise EnforceError(f"unknown flag {name!r}", context="flags")
        self._values[name] = value

    def update(self, **kwargs) -> None:
        for k, v in kwargs.items():
            self.set(k, v)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)

    def __getattr__(self, name: str) -> Any:
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        self.set(name, value)


FLAGS = _Flags()

# Core process flags (reference: paddle/utils/Flags.cpp:18-81, re-scoped for TPU).
FLAGS.define("seed", 0, "global RNG seed (0 = nondeterministic per-process)")
FLAGS.define("log_period", 100, "print batch stats every N batches")
FLAGS.define("test_period", 0, "run the tester every N batches (0 = per pass)")
FLAGS.define("show_layer_stat", False, "print per-layer output stats each log period")
FLAGS.define("show_parameter_stats_period", 0, "print per-parameter grad stats every N batches")
FLAGS.define("check_nan", False, "enable jax debug_nans (FE_INVALID tripwire analog)")
FLAGS.define("platform", "", "force a jax platform ('cpu'/'tpu'); empty = auto")
FLAGS.define("mesh_shape", "", "comma dims for the device mesh, e.g. '8' or '2,4'")
FLAGS.define("mesh_axes", "data", "comma axis names matching mesh_shape")
FLAGS.define("use_bf16", True, "compute matmuls/convs in bfloat16 on TPU")
FLAGS.define("use_pallas", True,
             "use hand-written pallas TPU kernels for the hot ops "
             "(flash-attention backward, fused LSTM cell); off = plain "
             "JAX/XLA fallbacks with identical semantics")
FLAGS.define("bf16_activations", True,
             "store inter-layer image activations in bfloat16 (halves HBM "
             "traffic between fused conv blocks; stats/losses stay f32). "
             "Only active when use_bf16 is also on.")
FLAGS.define("bf16_dense_activations", False,
             "store fc/embedding/attention outputs (the transformer "
             "residual stream) in bfloat16. Norm statistics and losses "
             "still reduce in f32. Off by default: flip for bandwidth-"
             "bound dense models. Only active when use_bf16 is also on.")
FLAGS.define("attn_block", 0,
             "flash-attention tile edge (query AND key block size). 0 = "
             "auto: the largest of 512/256/128 that divides the sequence "
             "(small/ragged seqs clamp to the sequence length). A nonzero "
             "value is tried first, falling through the same ladder when "
             "it does not divide. Larger tiles amortize per-block "
             "overhead; VMEM use is O(block^2) so 256/512 still fit.",
             parser=int)
FLAGS.define("attn_pv_f32", False,
             "keep the flash-attention PV-matmul operands (softmax probs "
             "and V, plus the backward dS/P operands) in f32 instead of "
             "the tiles' native dtype. Removes the bf16 softmax-prob "
             "rounding for accuracy-sensitive runs at the cost of the "
             "slower f32 MXU path for those matmuls.")
FLAGS.define("zero_stage", 0,
             "cross-replica sharded weight update (arXiv 2004.13336): "
             "0 = replicated optimizer state (default), 1 = ZeRO-1 — "
             "reduce-scatter grads, update a 1/N optimizer-state shard "
             "per replica over the 'data' mesh axis, all-gather updated "
             "weights. Per-trainer override: SGD(zero=...).")
FLAGS.define("pipeline_stages", 0,
             "pipeline-parallel stage count S for SGD(pipeline=...). 0 = "
             "derive: the PipelineConfig's num_stages, else the mesh's "
             "'stage' axis size, else every visible device. The model's "
             "layer count must divide by S (each stage holds L/S "
             "consecutive blocks).", parser=int)
FLAGS.define("pipeline_microbatches", 8,
             "GPipe microbatch count M per pipeline-parallel train step "
             "(PipelineConfig(microbatches=0) reads this). The batch "
             "must divide by M; bubble fraction is (S-1)/(M+S-1), so "
             "larger M amortizes the fill/drain bubble at the cost of "
             "smaller per-microbatch matmuls.", parser=int)
FLAGS.define("serving_page_size", 128,
             "paged-KV cache page size in tokens (serving engine). 128 "
             "matches the TPU lane width so a page's K/V tile feeds the "
             "MXU without padding; tests and small models may pass a "
             "smaller explicit page_size to ServingEngine.")
FLAGS.define("serving_max_pages", 512,
             "total pages in the serving KV pool (page 0 is reserved as "
             "the null page that masked/inactive writes land on). "
             "HBM cost = 2 * layers * pages * page_size * heads * "
             "head_dim * dtype bytes.")
FLAGS.define("serving_max_slots", 8,
             "maximum concurrently-decoding sequences per serving engine "
             "tick (the static batch dimension of the fused decode step)")
FLAGS.define("serving_prefill_buckets", "32,64,128,256,512",
             "comma ladder of padded prefill lengths: each admitted "
             "prompt — or, under chunked prefill, each chunk of at most "
             "serving_prefill_chunk tokens — is padded to the smallest "
             "bucket that holds it so the prefill jit specializes once "
             "per bucket, not once per distinct length")
FLAGS.define("serving_prefix_cache", True,
             "automatic prefix caching: full KV pages are indexed by "
             "chained token-block hashes and refcount-shared, so a "
             "prompt whose prefix is cached skips re-forwarding it "
             "(admission charges only the NEW pages; a full-cover hit "
             "copy-on-write-forks the last shared page and recomputes "
             "only the final token). Cached pages at refcount 0 stay "
             "reclaimable and are LRU-evicted under pool pressure. "
             "Hits are token-verified, so hash collisions degrade to "
             "misses, never to corruption.")
FLAGS.define("serving_prefill_chunk", 256,
             "chunked prefill: a prompt (or cache-miss tail) longer "
             "than this many tokens is prefilled in chunks of at most "
             "this size, ONE chunk per engine tick, interleaved with "
             "the fused decode step so a long prefill stops stalling "
             "running slots' inter-token latency. Each chunk is padded "
             "to the serving_prefill_buckets ladder, so the chunk size "
             "should be a ladder value (a chunk of C pads to the "
             "smallest bucket >= C; a chunk above the top bucket rounds "
             "up and wastes the excess). 0 disables chunking "
             "(whole-prompt single-shot prefill).", parser=int)
FLAGS.define("serving_kv_dtype", "float32",
             "storage dtype of the paged KV pool: float32 | bfloat16 | "
             "int8. bfloat16 halves and int8 roughly quarters the bytes "
             "per page (int8 adds per-token, per-kv-head f32 scale "
             "arrays — amax/127 symmetric quantization applied on every "
             "write, dequantized in-register by the ragged attention "
             "kernel and by the gather fallback, so the oracle and the "
             "kernel read identical stored values). At a fixed pool "
             "byte budget (ServingEngine(pool_bytes=...)) the smaller "
             "dtypes admit proportionally more pages, which multiplies "
             "prefix-cache capacity and admissible concurrency. "
             "Per-engine override: ServingEngine(kv_dtype=...).")
FLAGS.define("serving_host_tier_bytes", 0,
             "hierarchical KV cache: byte budget of the host-RAM spill "
             "tier under the device page pool. When > 0 (and the prefix "
             "cache is on), LRU-evicted reclaimable pages demote to host "
             "memory — checksummed over stored bytes + scales — instead "
             "of being destroyed, and a prefix lookup that runs off the "
             "device index swaps the verified continuation back in. "
             "When the budget is exceeded the tier LRU-drops (the third "
             "rung of the degradation ladder: device evict -> host "
             "spill -> host drop -> shed/preempt). 0 disables (prior "
             "behavior: eviction destroys). Per-engine override: "
             "ServingEngine(host_tier_bytes=...).", parser=int)
FLAGS.define("serving_swap_in_budget", 8,
             "host-tier swap-in charge per engine tick, in pages: at "
             "most this many verified host pages are promoted back to "
             "the device pool per tick for the head-of-queue request — "
             "the chunk-prefill charging model, so a long host-resident "
             "chain warms over several ticks and never blocks decode. "
             "0 disables swap-in (spill-only tier). Per-engine "
             "override: ServingEngine(swap_in_budget=...).", parser=int)
FLAGS.define("serving_host_kv_dtype", "stored",
             "host-tier storage format: 'stored' keeps the device "
             "pool's stored bytes verbatim (swap-in is bit-identical); "
             "'int8' transcodes float payloads to int8 + per-token "
             "f32 scales on spill (amax/127, the pool's own "
             "quantization rule), so the same serving_host_tier_bytes "
             "holds ~4x the f32 pages at quantization fidelity — "
             "dequantized on swap-in. An int8 device pool spills "
             "verbatim either way. Per-engine override: "
             "ServingEngine(host_kv_dtype=...).")
FLAGS.define("serving_spec_mode", "off",
             "speculative decoding: off | ngram | draft. 'ngram' drafts "
             "by prompt-lookup (match the last serving_spec_ngram "
             "tokens of a slot's own prompt+output history against "
             "earlier occurrences and propose what followed — zero "
             "extra model cost); 'draft' runs a small draft DecodeModel "
             "(ServingEngine(draft_model=, draft_params=)) with its own "
             "paged KV pool. Either way ONE fused target-model step "
             "verifies all k+1 positions per slot per tick (speculative "
             "slots contribute k+1 rows instead of 1), the longest "
             "agreeing prefix is accepted (greedy: exact match; "
             "sampled: rejection sampling against the target "
             "distribution) and rejected tokens roll back via COW page "
             "forks, so greedy output stays token-identical to "
             "non-speculative decoding. Per-engine override: "
             "ServingEngine(spec_mode=...).")
FLAGS.define("serving_spec_k", 4,
             "speculation depth: drafted tokens per slot per tick. The "
             "verify step compiles once per (prefill_bucket, k+1) pair "
             "— k is a jit dimension, so keep it fixed per engine. "
             "Lookahead KV pages are charged opportunistically (never "
             "by preemption) and speculation is suspended per-slot "
             "under page pressure. Per-engine override: "
             "ServingEngine(spec_k=...).", parser=int)
FLAGS.define("serving_spec_ngram", 3,
             "n-gram size of the prompt-lookup proposer: the longest "
             "history suffix matched against earlier history (falls "
             "back to shorter suffixes down to 1). Per-engine override: "
             "ServingEngine(spec_ngram=...).", parser=int)
FLAGS.define("serving_queue_deadline_s", 0.0,
             "default per-request admission deadline: a request still "
             "queued this many seconds after submit is shed as TIMED_OUT "
             "(slot/pages were never held). 0 disables; per-request "
             "override: ServingEngine.submit(queue_deadline_s=...).",
             parser=float)
FLAGS.define("serving_preempt_budget", 3,
             "max re-prefill recomputes per request. A request preempted "
             "this many times escalates: it requeues ahead of every "
             "non-escalated request and is never chosen as a preemption "
             "victim again, so youngest-first eviction cannot livelock a "
             "long prompt. 0 = unlimited.", parser=int)
FLAGS.define("serving_watchdog_ticks", 16,
             "decode-progress watchdog: a RUNNING request that emits no "
             "token for this many engine ticks (persistent device "
             "errors, stuck slot) is FAILED and its pages freed, keeping "
             "the rest of the fused batch alive. 0 disables.", parser=int)
FLAGS.define("serving_fleet_replicas", 4,
             "default replica count for FleetRouter: N ServingEngine "
             "replicas behind one prefix-affinity front-door. Traffic "
             "routes by chained prompt-block hash (the PrefixCache key "
             "chain) with healthz-driven load balancing as tiebreak and "
             "overflow; a dead replica's in-flight requests resubmit to "
             "survivors.", parser=int)
FLAGS.define("serving_fleet_heartbeat_s", 1.0,
             "fleet replica lease scale on the fleet's (possibly "
             "injected) clock: the lease TTL is 3x this. Leases renew "
             "every fleet tick (renewal is a cheap host op; only a "
             "heartbeat-partition fault blocks it), so a replica dies "
             "when its renewals stop for the TTL — then its token is "
             "dropped (a zombie can never ack after its slot is "
             "reclaimed) and its in-flight requests resubmit. On a "
             "wall clock set this above the worst-case single tick "
             "(first-compile spikes), since a tick longer than the TTL "
             "lapses every lease mid-tick.", parser=float)
FLAGS.define("serving_fleet_resubmit_budget", 2,
             "max death-driven resubmits per fleet request. A request "
             "whose replica dies is resubmitted to a survivor with its "
             "ORIGINAL absolute deadline at most this many times, then "
             "FAILED — bounded recovery, never an infinite "
             "kill->resubmit loop. 0 = fail on the first death.",
             parser=int)
FLAGS.define("serving_fleet_roles", "",
             "comma-separated replica role list for a disaggregated "
             "fleet ('prefill,prefill,decode,decode'); shorter lists "
             "pad with 'unified', empty = every replica unified (the "
             "classic fleet). Prompts route to prefill/unified "
             "replicas; a prefill-class replica hands each request off "
             "to the least-loaded decode-class replica after its first "
             "token via the page-migration plane (export_chain/"
             "import_chain), so long prefills never steal verify-row "
             "budget from chatty decoders.")
FLAGS.define("serving_migrate_budget", 16,
             "page-migration admission budget: KV pages a DESTINATION "
             "replica accepts per fleet tick across in-flight "
             "migrations (chain handoffs and cross-replica prefix "
             "seeds). Charged like chunked prefill — a blob of n pages "
             "waits ceil(n/budget) ticks in the destination's transfer "
             "queue and never blocks its decode tick. 0 disables "
             "migration (prefill-class replicas then decode their own "
             "requests to completion).", parser=int)
FLAGS.define("serving_tenant_classes", "",
             "multi-tenant SLO registry for the fleet control plane "
             "(serving/control.py): a comma list of 'name:class' pairs "
             "('alice:interactive,bulk:batch'; a bare name means "
             "standard). Classes bind latency-tier deadlines "
             "(interactive 0.5s / standard 2s / batch none), WFQ "
             "weights (4/2/1) and preemption precedence (batch slots "
             "are victimized first). Empty = no registry: submits keep "
             "their explicit deadlines, quotas and precedence are off. "
             "Unknown tenants auto-register as standard on first "
             "touch.")
FLAGS.define("serving_wfq", False,
             "weighted fair queuing at the FleetRouter: submits buffer "
             "in per-tenant virtual-time queues (prompt-token-weighted "
             "service, weights from the tenant registry) and release "
             "to dispatch each tick bounded by the READY replicas' "
             "admission slack — one tenant's 10x prompt storm backlogs "
             "only its own queue while other tenants keep their "
             "deadline SLO. Off = the classic submit->dispatch FIFO.")
FLAGS.define("serving_autoscale", False,
             "fleet autoscaler policy loop (serving/control.py "
             "Autoscaler) on the fleet's injected clock: joins a "
             "replica when any pressure signal breaches its hi "
             "threshold (queue_wait_ms_p95, live-page fraction, "
             "prefill backlog, WFQ backlog, fresh deadline misses) and "
             "drains the newest idle replica when the fleet is "
             "provably idle — never the last prefill-capable replica "
             "of a disaggregated fleet. Hysteresis via "
             "serving_autoscale_cooldown.")
FLAGS.define("serving_autoscale_cooldown", 10,
             "autoscaler hysteresis: fleet ticks with NO scaling "
             "action after any join/drain, so one pressure spike "
             "cannot flap the fleet size tick-over-tick.", parser=int)
FLAGS.define("obs_trace", False,
             "request-scoped span tracing (paddle_tpu.obs): when on, "
             "ServingEngine/FleetRouter construct a real Tracer on "
             "their injected clock and every request lifecycle edge "
             "(submit/route/admit/prefill chunk/decode tick/preempt/"
             "resubmit/terminal), fleet lease/fence/reap transition, "
             "and PagePool alloc/ref/free lands on one exportable "
             "timeline (python -m paddle_tpu.obs export -> Perfetto). "
             "Checked at CONSTRUCTION time (the audit_jit idiom): set "
             "it before building the engine/fleet being traced. Off = "
             "the shared NULL_TRACER, a true no-op — zero events, zero "
             "clock reads, zero extra compiles or host syncs on the "
             "decode tick.")
FLAGS.define("obs_keep_all", True,
             "flag-built tracers retain the FULL event list for export "
             "(the replay/debug default). A long-running service should "
             "set this off: only the bounded flight-recorder ring "
             "(obs_ring_size) is kept, so tracing memory cannot grow "
             "without bound; save()/export then cover the ring's most-"
             "recent window.")
FLAGS.define("obs_ring_size", 4096,
             "flight-recorder depth: the tracer keeps this many most-"
             "recent events in a bounded ring, dumped to a postmortem "
             "file whenever a conservation invariant (PAGE-LEAK/"
             "REF-LEAK/FLEET-LEAK) trips.", parser=int)
FLAGS.define("obs_dump_dir", "/tmp/paddle_tpu_obs",
             "directory for flight-recorder postmortem dumps; each dump "
             "prints a grep-able 'OBS-POSTMORTEM: <path>' line that "
             "tools_tier1.sh surfaces on any ladder exit >= 3.")
FLAGS.define("fluid_verify", "warn",
             "static program verification before Executor.run compiles "
             "a fluid Program: 'warn' (default) logs every diagnostic "
             "the paddle_tpu.analysis verifier finds, 'strict' raises "
             "on ERROR diagnostics (shape/dtype conflicts, "
             "def-before-use, dangling fetches, duplicate writers), "
             "'off' disables.  Runs once per compiled (program, "
             "feed-shape) specialization, so steady state pays nothing.")
FLAGS.define("jit_audit", False,
             "retrace auditing: when on, audit_jit-instrumented call "
             "sites (serving decode/prefill, trainer steps, inference, "
             "ZeRO placement, fluid executor) record abstract-signature "
             "-> compile events in paddle_tpu.analysis.retrace.auditor() "
             "and flag compiles after seal() — or recompiles of an "
             "already-compiled signature — as RETRACE diagnostics.  "
             "Checked at wrap time: set it BEFORE constructing the "
             "engine/trainer being audited.  Off = bare jax.jit, zero "
             "overhead.")
FLAGS.define("xla_audit_const_bytes", 65536,
             "const-capture threshold for the jaxpr auditor (python -m "
             "paddle_tpu.analysis xla): an array larger than this many "
             "bytes baked into an audited site's executable as a jaxpr "
             "const (instead of an argument) is an XLA-AUDIT error — "
             "consts are re-baked on every compile, duplicated per "
             "specialization, and invisible to donation. Per-site "
             "override: SiteContract(const_bytes=...).", parser=int)
FLAGS.define("xla_audit_big_arg_bytes", 1048576,
             "donation-candidate threshold for the jaxpr auditor: a "
             "non-donated argument larger than this many bytes whose "
             "avals all match unclaimed outputs is reported (WARNING) "
             "as a donation candidate — if the caller overwrites it "
             "with the result (the repo's step idiom), donating saves "
             "a full copy. Per-site override: "
             "SiteContract(big_arg_bytes=...).", parser=int)
FLAGS.define("conc_audit_max_schedules", 64,
             "per-drive schedule budget for the concurrency auditor's "
             "schedule-permutation explorer (python -m "
             "paddle_tpu.analysis concurrency): each chaos drive "
             "replays at most this many permuted intra-tick schedules "
             "(single-tick deltas first, then depth-2 combinations) "
             "against its canonical fingerprint. The default explores "
             "well past the >=50-interleavings-per-drive bar the audit "
             "documents; raise it for deeper soak runs, lower it only "
             "for quick smoke iterations.", parser=int)
FLAGS.define("shard_audit_virtual_devices", 8,
             "virtual CPU device count the sharding-audit CLI (python "
             "-m paddle_tpu.analysis sharding) forces before backend "
             "init, so its ZeRO placement drive runs on a real "
             "multi-device 'data' axis without TPU hardware (the "
             "tests/conftest.py trick). Only effective when the jax "
             "backend has not initialized yet; <=1 disables the "
             "forcing and the placement drive degrades to a loud "
             "'not audited' notice.", parser=int)
FLAGS.define("train_bad_step_policy", "off",
             "default bad-step guard for trainer.SGD (per-trainer "
             "override: SGD(guard=BadStepGuard(...))): 'off' = the "
             "classic unguarded step; 'skip' = fuse a global-norm + "
             "finiteness check over the gradients into the jitted step "
             "and skip bad steps in-graph (params, optimizer slots and "
             "model state untouched, counted lazily — no new per-step "
             "host sync); 'rollback' = skip, plus K consecutive bad "
             "steps (train_bad_step_window) dump a flight-recorder "
             "postmortem and raise BadStepRollback so the resilience "
             "supervisor restarts from the last verified checkpoint.")
FLAGS.define("train_bad_step_max_norm", 0.0,
             "bad-step guard: global gradient-norm ceiling — a FINITE "
             "step whose grad norm exceeds this is also skipped "
             "(0 = finiteness check only). Unlike "
             "gradient_clipping_threshold this does not rescale; it "
             "refuses the step.", parser=float)
FLAGS.define("train_bad_step_window", 3,
             "bad-step guard hysteresis: under policy 'rollback', this "
             "many CONSECUTIVE bad steps trigger the rollback. Also the "
             "default host-readback cadence for the on-device "
             "consecutive counter (BadStepGuard.check_every).",
             parser=int)
FLAGS.define("train_ckpt_async", False,
             "write training checkpoints on a background thread "
             "(resilience.AsyncCheckpointer): the train loop stalls "
             "only for the device->host snapshot, never the "
             "tar/pkl/md5/meta disk commit. Depth-one pipelined — a new "
             "save first waits out the previous write, and the elastic "
             "trainer acks master tasks only past that durability "
             "barrier. Per-call override: train(async_save=...).")
FLAGS.define("train_ckpt_keep", 2,
             "checkpoint prune budget for step-granular training saves: "
             "keep this many newest VERIFIED checkpoints (corrupt dirs "
             "never count toward the budget, so torn young saves cannot "
             "reap the only good artifact). 0 disables pruning. "
             "Per-call override: train(keep=...).", parser=int)
FLAGS.define("save_dir", "./output", "default checkpoint output directory")
FLAGS.define("log_level", "INFO", "logging level")
FLAGS.define("prealloc_mem", False, "let XLA preallocate the whole HBM arena")
