"""Prebuilt network helpers (reference: trainer_config_helpers/networks.py —
simple_img_conv_pool, img_conv_group, vgg_16_network, simple_lstm,
bidirectional_lstm/gru, simple_gru, simple_attention:1304,
dot_product_attention:1402)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from paddle_tpu import activation as A
from paddle_tpu import layer as L
from paddle_tpu import pooling as P
from paddle_tpu.topology import LayerOutput, unique_name

__all__ = ["simple_img_conv_pool", "img_conv_group", "vgg_16_network",
           "sequence_conv_pool", "simple_lstm", "simple_gru",
           "bidirectional_lstm", "bidirectional_gru", "simple_attention",
           "dot_product_attention"]


def sequence_conv_pool(input, context_len: int, hidden_size: int,
                       name: Optional[str] = None, context_start: int = None,
                       pool_type=None, fc_act=None) -> LayerOutput:
    """Text convolution pooling group (reference: networks.py:40
    sequence_conv_pool): context projection -> fc -> pooling — the text-CNN
    used by the quick_start cnn config (v1_api_demo/quick_start/
    trainer_config.cnn.py)."""
    name = name or unique_name("seq_conv_pool")
    ctx = L.mixed(size=input.size * context_len,
                  input=[L.context_projection(input, context_len=context_len,
                                              context_start=context_start)],
                  name=f"{name}_ctx")
    hidden = L.fc(input=ctx, size=hidden_size, act=fc_act or "tanh",
                  name=f"{name}_fc")
    return L.pooling(input=hidden, pooling_type=pool_type or P.MaxPooling(),
                     name=name)


def simple_img_conv_pool(input, filter_size: int, num_filters: int,
                         pool_size: int, pool_stride: int = None,
                         num_channel: int = None, act=None,
                         padding: int = None, pool_type=None,
                         name: Optional[str] = None) -> LayerOutput:
    padding = padding if padding is not None else (filter_size - 1) // 2
    conv = L.img_conv(input=input, filter_size=filter_size,
                      num_filters=num_filters, num_channels=num_channel,
                      padding=padding, act=act, name=name)
    return L.img_pool(input=conv, pool_size=pool_size,
                      stride=pool_stride or pool_size, pool_type=pool_type)


def img_conv_group(input, conv_num_filter: Sequence[int], conv_filter_size=3,
                   conv_act=None, conv_with_batchnorm=False,
                   pool_size: int = 2, pool_stride: int = 2,
                   pool_type=None, num_channels: int = None) -> LayerOutput:
    tmp = input
    for i, nf in enumerate(conv_num_filter):
        tmp = L.img_conv(input=tmp, filter_size=conv_filter_size,
                         num_filters=nf, padding=(conv_filter_size - 1) // 2,
                         num_channels=num_channels if i == 0 else None,
                         act=None if conv_with_batchnorm else (conv_act or "relu"))
        if conv_with_batchnorm:
            tmp = L.batch_norm(input=tmp, act=conv_act or "relu")
    return L.img_pool(input=tmp, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type)


def vgg_16_network(input_image, num_channels: int, num_classes: int = 1000
                   ) -> LayerOutput:
    """VGG-16 (reference: networks.py vgg_16_network)."""
    tmp = input_image
    for filters, n in [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]:
        tmp = img_conv_group(tmp, [filters] * n, conv_act="relu",
                             num_channels=num_channels if filters == 64 else None)
    tmp = L.fc(input=tmp, size=4096, act="relu")
    tmp = L.dropout(tmp, 0.5)
    tmp = L.fc(input=tmp, size=4096, act="relu")
    tmp = L.dropout(tmp, 0.5)
    return L.fc(input=tmp, size=num_classes, act="softmax")


def simple_lstm(input, size: int, reverse: bool = False, act=None,
                gate_act=None, state_act=None, name: Optional[str] = None,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None) -> LayerOutput:
    """fc(4H) + lstmemory (reference: networks.py simple_lstm)."""
    name = name or unique_name("simple_lstm")
    proj = L.fc(input=input, size=size * 4, name=f"{name}_input_proj",
                param_attr=mat_param_attr, bias_attr=bias_param_attr or True)
    return L.lstmemory(input=proj, size=size, reverse=reverse, act=act,
                       gate_act=gate_act, state_act=state_act,
                       name=name, param_attr=inner_param_attr)


def simple_gru(input, size: int, reverse: bool = False, act=None,
               gate_act=None, name: Optional[str] = None, **kw) -> LayerOutput:
    name = name or unique_name("simple_gru")
    proj = L.fc(input=input, size=size * 3, name=f"{name}_input_proj")
    return L.grumemory(input=proj, size=size, reverse=reverse, act=act,
                       gate_act=gate_act, name=name)


def bidirectional_lstm(input, size: int, name: Optional[str] = None,
                       return_seq: bool = True, **kw) -> LayerOutput:
    """Forward+backward LSTM concat (reference: networks.py bidirectional_lstm)."""
    name = name or unique_name("bidirectional_lstm")
    fwd = simple_lstm(input, size, reverse=False, name=f"{name}_fwd")
    bwd = simple_lstm(input, size, reverse=True, name=f"{name}_bwd")
    if return_seq:
        return L.concat(input=[fwd, bwd])
    return L.concat(input=[L.last_seq(fwd), L.first_seq(bwd)])


def bidirectional_gru(input, size: int, name: Optional[str] = None,
                      return_seq: bool = True, **kw) -> LayerOutput:
    name = name or unique_name("bidirectional_gru")
    fwd = simple_gru(input, size, reverse=False, name=f"{name}_fwd")
    bwd = simple_gru(input, size, reverse=True, name=f"{name}_bwd")
    if return_seq:
        return L.concat(input=[fwd, bwd])
    return L.concat(input=[L.last_seq(fwd), L.first_seq(bwd)])


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name: Optional[str] = None) -> LayerOutput:
    """Bahdanau-style additive attention (reference: networks.py:1304).

    score = v . tanh(enc_proj + W s); context = sum_t softmax(score)_t * enc_t
    """
    name = name or unique_name("attention")
    dec_proj = L.fc(input=decoder_state, size=encoded_proj.size,
                    name=f"{name}_decoder_proj", param_attr=transform_param_attr,
                    bias_attr=False)
    expanded = L.expand(input=dec_proj, expand_as=encoded_sequence,
                        name=f"{name}_expand")
    combined = L.addto(input=[encoded_proj, expanded], act="tanh",
                       name=f"{name}_combine")
    scores = L.fc(input=combined, size=1, act=None, bias_attr=False,
                  param_attr=softmax_param_attr, name=f"{name}_scores")
    weights = L.mixed(size=1, input=[L.identity_projection(scores)],
                      act=A.SequenceSoftmaxActivation(), name=f"{name}_softmax")
    scaled = L.dotmul_bcast(encoded_sequence, weights, name=f"{name}_scale")
    return L.pooling(input=scaled, pooling_type=P.SumPooling(),
                     name=f"{name}_context")


def dot_product_attention(encoded_sequence, attended_sequence, transformed_state,
                          name: Optional[str] = None) -> LayerOutput:
    """Dot-product attention (reference: networks.py:1402)."""
    name = name or unique_name("dot_attention")
    expanded = L.expand(input=transformed_state, expand_as=encoded_sequence,
                        name=f"{name}_expand")
    scores_tok = L.dotmul(expanded, encoded_sequence, name=f"{name}_dot")
    scores = L.fc(input=scores_tok, size=1, bias_attr=False, name=f"{name}_sum")
    weights = L.mixed(size=1, input=[L.identity_projection(scores)],
                      act=A.SequenceSoftmaxActivation(), name=f"{name}_softmax")
    scaled = L.dotmul_bcast(attended_sequence, weights, name=f"{name}_scale")
    return L.pooling(input=scaled, pooling_type=P.SumPooling(),
                     name=f"{name}_context")
