"""Static GSPMD sharding-propagation auditor with collective-cost budgets.

PR 9's jaxpr auditor (:mod:`paddle_tpu.analysis.xla`) can see the
collectives GSPMD *already inserted* — but only after the partitioner
has made its placement decisions, and it cannot say whether a declared
``NamedSharding`` plan is even consistent, where an implicit all-gather
will materialize, or what a resharding costs in bytes over the links.
This module answers those questions *statically*, before anything runs
on chips: it re-materializes each captured ``audit_jit`` signature
(the same :class:`~paddle_tpu.analysis.retrace.CapturedCall` plumbing
the xla auditor uses), seeds every input with the ``PartitionSpec``
declared in the site's :class:`SiteContract` (``in_specs`` /
``out_specs`` / ``mesh_axes`` — see retrace.py), and walks the jaxpr
with a GSPMD-style propagation model:

- **elementwise** ops preserve shardings (conflicting placements on one
  dim mean GSPMD must all-gather an operand);
- **dot_general** contracting over a dim sharded the same way on both
  operands produces *partial sums* — a pending ``psum`` that a
  downstream ``sharding_constraint`` over the same axis lowers into the
  cheaper reduce-scatter (exactly how ``parallel/zero.py`` gets its
  reduce-scatter/all-gather pair out of ``with_sharding_constraint``);
- **reshape/transpose/pad/slice** of a sharded dim either preserve the
  placement (prefix-product-preserving reshape, permutation) or force a
  resharding;
- **gather/scatter** (the paged-KV layout ops) are safe when the
  sharded dims are operand *batching* dims and a forced gather when the
  sharded dim is indexed or collapsed across shards;
- explicit collectives and ``sharding_constraint`` eqns are costed with
  the distributed-TPU model of arXiv 2112.09017: for an ``N``-way axis
  and a tensor of ``b`` bytes, all-gather and reduce-scatter move
  ``b*(N-1)/N`` bytes per device, an all-reduce (psum) moves
  ``2*b*(N-1)/N``, an all-to-all ``b*(N-1)/N`` and a ppermute ``b``.

Findings are :class:`Diagnostic`\\ s tagged ``SHARD-AUDIT`` naming
rule + site + eqn:

- **contract-mismatch** — inferred output placement differs from the
  declared ``out_specs``, or a declared spec names an axis the
  ``mesh_axes`` don't have;
- **implicit-all-gather** — a sharded operand is forced replicated
  (conflicting elementwise placements, one-side-sharded contraction,
  non-preserving reshape, sliced/indexed sharded dim), with the
  materialized bytes in the message;
- **accidental-replication** — an ``expect_sharded`` argument arrives
  replicated, or a weight-shaped const is baked replicated into a site
  whose contract shards anything (consts can never be sharded);
- **axis-collision** — the same mesh axis consumed twice in one
  contraction (two output dims, or a declared spec using one axis for
  two dims of one tensor);
- **comm-budget** — the estimated collective bytes per call exceed the
  ``comm_bytes`` budget declared next to the jit (the serving step
  declares 0: a single-replica decode tick must not pay interconnect;
  the TP serving PR flips that to a derived ``model``-axis budget).

``python -m paddle_tpu.analysis sharding`` drives the same sealed
serving + trainer steady states as the xla gate, plus the ZeRO
placement jits on a virtual-8 mesh, declares the (still trivial)
pipeline/MoE contracts so their uncaptured sites print a loud notice,
and exits 0 clean / 1 findings / 2 crash — ``tools_tier1.sh`` ladder
exit 9.

Model limits (documented, all conservative): unknown ops produce
unknown placements and unknown placements never produce findings —
conflicts are proofs, not guesses (the program_check philosophy);
``shard_map`` bodies are walked only for their explicit collectives
(per-shard byte semantics); ``while`` bodies count one trip and
``scan`` bodies multiply by the trip count; pending partial-sums pass
through linear ops only and are charged as a full psum at their first
non-linear consumer or at the outputs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import (Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.retrace import (CapturedCall, SiteContract,
                                         SiteRecord, auditor, declare_site)
from paddle_tpu.analysis.xla import _aval_bytes, _sub_jaxprs

__all__ = ["audit_sharding_sites", "audit_record_sharding", "ShardReport",
           "RULE_NAMES", "normalize_spec", "apply_spec",
           "all_gather_bytes", "reduce_scatter_bytes", "all_reduce_bytes",
           "drive_zero_placement", "drive_serving_tp_steady_state",
           "drive_pipeline_moe_train_step",
           "replay_serving_tp", "ensure_virtual_devices",
           "run_sharding_audit"]

TAG = "SHARD-AUDIT"

RULE_NAMES = ("contract-mismatch", "implicit-all-gather",
              "accidental-replication", "axis-collision", "comm-budget")

_DEFAULT_CONTRACT = SiteContract()

_COLLECTIVES = {"psum": "ar", "psum2": "ar", "all_reduce": "ar",
                "all_gather": "ag", "all_gather_invariant": "ag",
                "psum_scatter": "rs", "reduce_scatter": "rs",
                "all_to_all": "a2a", "ppermute": "pp", "pshuffle": "pp"}

# ops a pending partial-sum may pass through without materializing the
# psum (linear in the pending operand, or pure data movement)
_PENDING_PASS = {"add", "sub", "add_any", "neg", "mul", "div",
                 "reshape", "transpose", "convert_element_type",
                 "broadcast_in_dim", "pad", "slice", "concatenate",
                 "squeeze", "expand_dims", "rev", "copy", "reduce_sum",
                 "dot_general", "stop_gradient"}


# ---------------------------------------------------------------------------
# spec plumbing
# ---------------------------------------------------------------------------


class VSpec(NamedTuple):
    """Inferred placement of one jaxpr var: ``dims`` is a per-dim tuple
    of mesh-axis names (None = replicated on that dim) or None when the
    placement is unknown; ``pending`` carries the mesh axes over which
    the value is a *partial sum* awaiting a psum/reduce-scatter."""

    dims: Optional[Tuple[Optional[str], ...]]
    pending: frozenset = frozenset()


def _repl(ndim: int) -> VSpec:
    return VSpec(dims=(None,) * ndim)


_UNKNOWN = VSpec(dims=None)


def normalize_spec(spec) -> Optional[Tuple[Optional[str], ...]]:
    """PartitionSpec / tuple / None -> per-dim tuple of single axis
    names.  Multi-axis dim entries (``("x", "y")``) collapse to their
    first axis — the repo shards one axis per dim."""
    if spec is None:
        return None
    out: List[Optional[str]] = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, str):
            out.append(e)
        elif isinstance(e, (tuple, list)) and e:
            out.append(str(e[0]))
        else:
            out.append(None)
    return tuple(out)


def _spec_for(specs: Optional[Tuple], i: int, n: int):
    """The declared spec for position ``i`` of ``n``: a length-1 specs
    tuple broadcasts to every position; missing positions are None."""
    if specs is None:
        return None
    if len(specs) == 1:
        return specs[0]
    return specs[i] if i < len(specs) else None


def apply_spec(spec, shape, axes: Dict[str, int]
               ) -> Tuple[VSpec, List[Tuple[str, str]]]:
    """Apply a declared spec to one array leaf; returns (VSpec,
    problems) where problems are (rule, message) pairs.  A spec applies
    only when the leaf has enough dims and every sharded dim divides by
    the axis size; otherwise the leaf is replicated (the documented
    broadcast-over-leaves semantics — optimizer scalars under a flat
    ZeRO spec must not error)."""
    probs: List[Tuple[str, str]] = []
    entries = normalize_spec(spec)
    if entries is None:
        return _UNKNOWN, probs
    nd = len(shape)
    dims: List[Optional[str]] = [None] * nd
    seen: Dict[str, int] = {}
    if len(entries) > nd:
        return _repl(nd), probs
    for d, ax in enumerate(entries):
        if ax is None:
            continue
        if ax in seen:
            probs.append((
                "axis-collision",
                f"declared spec {entries} uses mesh axis {ax!r} for two "
                f"dims ({seen[ax]} and {d}) of one tensor — an axis can "
                "shard at most one dim"))
            continue
        seen[ax] = d
        if axes and ax not in axes:
            probs.append((
                "contract-mismatch",
                f"declared spec names mesh axis {ax!r} but mesh_axes "
                f"declares only {sorted(axes)}"))
            continue
        n = axes.get(ax)
        if n is not None and (int(shape[d]) % int(n)) != 0:
            continue                    # leaf too small: replicated
        dims[d] = ax
    return VSpec(dims=tuple(dims)), probs


# ---------------------------------------------------------------------------
# collective cost model (arXiv 2112.09017 ring costs, bytes per device)
# ---------------------------------------------------------------------------


def _factor(n: Optional[int]) -> float:
    """(N-1)/N for a known axis size; 1.0 (the upper bound) unknown."""
    if n is None or n <= 1:
        return 1.0 if n is None else 0.0
    return (n - 1) / n


def all_gather_bytes(nbytes: float, n: Optional[int]) -> float:
    return nbytes * _factor(n)


def reduce_scatter_bytes(nbytes: float, n: Optional[int]) -> float:
    return nbytes * _factor(n)


def all_reduce_bytes(nbytes: float, n: Optional[int]) -> float:
    return 2.0 * nbytes * _factor(n)


def all_to_all_bytes(nbytes: float, n: Optional[int]) -> float:
    return nbytes * _factor(n)


# ---------------------------------------------------------------------------
# the propagation walk
# ---------------------------------------------------------------------------


def _diag(sev: Severity, rule: str, site: str, msg: str,
          where: str = "") -> Diagnostic:
    loc = f" eqn {where}" if where else ""
    return Diagnostic(sev, TAG, f"[{rule}] site {site!r}{loc}: {msg}",
                      vars=(site, rule))


@dataclass
class _Walk:
    """Mutable state shared across one signature's (recursive) walk."""

    site: str
    contract: SiteContract
    axes: Dict[str, int]
    diags: List[Diagnostic] = field(default_factory=list)
    comm: float = 0.0
    _charged: set = field(default_factory=set)   # (id(var), axis)

    def report(self, sev: Severity, rule: str, msg: str,
               where: str = "") -> None:
        self.diags.append(_diag(sev, rule, self.site, msg, where=where))

    def size(self, axis: str) -> Optional[int]:
        return self.axes.get(axis)

    def charge_pending(self, var, vs: VSpec, where: str) -> VSpec:
        """Materialize a var's pending partial-sums as full psums (a
        non-linear consumer, or the jaxpr outputs) — charged once per
        (var, axis)."""
        if not vs.pending:
            return vs
        b = _aval_bytes(getattr(var, "aval", None))
        for axis in vs.pending:
            key = (id(var), axis)
            if key not in self._charged:
                self._charged.add(key)
                self.comm += all_reduce_bytes(b, self.size(axis))
        return vs._replace(pending=frozenset())

    def gather(self, rule_msg: str, nbytes: float, axis: str,
               where: str) -> None:
        """One implicit-all-gather finding + its cost."""
        self.comm += all_gather_bytes(nbytes, self.size(axis))
        self.report(
            Severity.ERROR, "implicit-all-gather",
            f"{rule_msg} — GSPMD must materialize "
            f"~{all_gather_bytes(nbytes, self.size(axis)):.0f} bytes "
            f"over the {axis!r} links (all-gather of a "
            f"{int(nbytes)}-byte operand)", where=where)


def _shape(v) -> Tuple[int, ...]:
    return tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())


def _union_pending(ins: Sequence[VSpec]) -> frozenset:
    out: frozenset = frozenset()
    for vs in ins:
        out = out | vs.pending
    return out


def _prod(xs) -> int:
    n = 1
    for x in xs:
        n *= int(x)
    return n


def _eltwise(st: _Walk, eqn, ins: List[VSpec], path: str,
             linear: bool) -> List[VSpec]:
    """Default rule for shape-broadcasting ops: merge operand specs
    dim-by-dim (aligned from the right); conflicting placements force
    an implicit all-gather of the later operand."""
    out_shape = _shape(eqn.outvars[0])
    nd = len(out_shape)
    if linear:
        pend = _union_pending(ins)
    else:
        for v, vs in zip(eqn.invars, ins):
            st.charge_pending(v, vs, path)
        pend = frozenset()
    unknown = any(vs.dims is None and _prod(_shape(v)) > 1
                  for v, vs in zip(eqn.invars, ins))
    dims: List[Optional[str]] = [None] * nd
    axis_dim: Dict[str, int] = {}
    for oi, (v, vs) in enumerate(zip(eqn.invars, ins)):
        if vs.dims is None:
            continue
        ish = _shape(v)
        off = nd - len(ish)
        for d, ax in enumerate(vs.dims):
            if ax is None:
                continue
            od = d + off
            if od < 0 or ish[d] != out_shape[od] or out_shape[od] <= 1:
                continue
            prev_dim = axis_dim.get(ax)
            if dims[od] is None and prev_dim is None:
                dims[od] = ax
                axis_dim[ax] = od
            elif dims[od] == ax:
                continue
            else:
                # conflict: same dim different axes, or same axis on a
                # different dim — the later operand gets gathered
                if dims[od] is not None:
                    clash = (f"dim {od} of the result is already "
                             f"placed on axis {dims[od]!r}")
                else:
                    clash = (f"axis {ax!r} already shards dim "
                             f"{prev_dim} of the result")
                st.gather(
                    f"operand {oi} of {eqn.primitive.name} is sharded "
                    f"{ax!r}@dim{d} but {clash}",
                    _aval_bytes(v.aval), ax,
                    where=f"{path} ({eqn.primitive.name})")
    if unknown:
        return [VSpec(None, pend) for _ in eqn.outvars]
    return [VSpec(tuple(dims), pend)] + \
        [VSpec(tuple(dims)) for _ in eqn.outvars[1:]]


def _rule_dot_general(st: _Walk, eqn, ins: List[VSpec],
                      path: str) -> List[VSpec]:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs_v, rhs_v = eqn.invars[0], eqn.invars[1]
    lvs, rvs = ins[0], ins[1]
    where = f"{path} (dot_general)"
    # pending: dot is linear in each operand separately; both pending
    # would double-count a product of partials — materialize both then
    if lvs.pending and rvs.pending:
        lvs = st.charge_pending(lhs_v, lvs, path)
        rvs = st.charge_pending(rhs_v, rvs, path)
    pend = lvs.pending | rvs.pending
    if lvs.dims is None or rvs.dims is None:
        return [VSpec(None, pend)]
    lsh, rsh = _shape(lhs_v), _shape(rhs_v)
    ld, rd = list(lvs.dims), list(rvs.dims)
    # contraction dims: sharded-both-sides (same axis) => partial sums;
    # sharded one side (or differently) => that operand gets gathered
    for li, ri in zip(lc, rc):
        la, ra = ld[li], rd[ri]
        if la is not None and la == ra:
            pend = pend | {la}
        elif la is not None or ra is not None:
            if la is not None:
                st.gather(
                    f"contraction dim {li} of the lhs is sharded "
                    f"{la!r} but the rhs contraction dim is not",
                    _aval_bytes(lhs_v.aval), la, where=where)
                ld[li] = None
            if ra is not None:
                st.gather(
                    f"contraction dim {ri} of the rhs is sharded "
                    f"{ra!r} but the lhs contraction dim is not",
                    _aval_bytes(rhs_v.aval), ra, where=where)
                rd[ri] = None
    out_dims: List[Optional[str]] = []
    used: Dict[str, str] = {}

    def _take(ax: Optional[str], origin: str) -> Optional[str]:
        if ax is None:
            return None
        if ax in pend:
            st.report(
                Severity.ERROR, "axis-collision",
                f"mesh axis {ax!r} is consumed by the contraction "
                f"(partial sums) AND shards the {origin} — one axis "
                "cannot do both in one dot_general", where=where)
            return None
        if ax in used:
            st.report(
                Severity.ERROR, "axis-collision",
                f"mesh axis {ax!r} shards both the {used[ax]} and the "
                f"{origin} of one dot_general — the output would be "
                "sharded twice over one axis", where=where)
            return None
        used[ax] = origin
        return ax

    # batch dims: must agree; they lead the output
    for li, ri in zip(lb, rb):
        la, ra = ld[li], rd[ri]
        ax = la if la == ra else None
        if la != ra and (la is not None or ra is not None):
            bad_v, bad_ax = (rhs_v, ra) if ra is not None else (lhs_v, la)
            st.gather(
                f"batch dims of dot_general are sharded inconsistently "
                f"({la!r} vs {ra!r})", _aval_bytes(bad_v.aval),
                bad_ax, where=where)
            ax = None
        out_dims.append(_take(ax, "batch dims"))
    for i in range(len(lsh)):
        if i not in lc and i not in lb:
            out_dims.append(_take(ld[i], "lhs free dims"))
    for i in range(len(rsh)):
        if i not in rc and i not in rb:
            out_dims.append(_take(rd[i], "rhs free dims"))
    return [VSpec(tuple(out_dims), pend)]


def _reshape_groups(ish: Tuple[int, ...], osh: Tuple[int, ...]):
    """Contiguous factor groups of a reshape: ``[(in_dims, out_dims)]``
    pairs with equal products, two-pointer walk.  None when the shapes
    don't decompose (zero-sized dims etc.) — callers fall back to the
    conservative gather."""
    groups: List[Tuple[List[int], List[int]]] = []
    i = j = 0
    ni, nj = len(ish), len(osh)
    while i < ni or j < nj:
        if i < ni and int(ish[i]) == 1 and (j >= nj or int(osh[j]) != 1):
            groups.append(([i], []))        # dangling size-1 in dim
            i += 1
            continue
        if j < nj and int(osh[j]) == 1 and (i >= ni or int(ish[i]) != 1):
            groups.append(([], [j]))        # dangling size-1 out dim
            j += 1
            continue
        if i >= ni or j >= nj:
            return None
        pi, pj = int(ish[i]), int(osh[j])
        di, dj = [i], [j]
        i += 1
        j += 1
        while pi != pj:
            if pi < pj:
                if i >= ni:
                    return None
                pi *= int(ish[i])
                di.append(i)
                i += 1
            else:
                if j >= nj:
                    return None
                pj *= int(osh[j])
                dj.append(j)
                j += 1
        if pi <= 0:
            return None
        groups.append((di, dj))
    return groups


def _rule_reshape(st: _Walk, eqn, ins: List[VSpec],
                  path: str) -> List[VSpec]:
    """GSPMD-compatible reshape propagation: a sharded dim survives when
    it is the MAJOR (first >1) dim of its contiguous factor group and
    the group's major output dim holds a whole number of shards — the
    shard boundary stays contiguous, so merging ``[H, D] -> [H*D]`` or
    splitting ``[E] -> [H, D]`` keeps a head-sharded placement (the
    megatron Q/K/V reshapes).  A sharded dim that is minor in its group,
    or whose target major dim doesn't divide by the axis size, still
    forces the all-gather."""
    vs = ins[0]
    if vs.dims is None:
        return [VSpec(None, vs.pending)]
    in_shape = _shape(eqn.invars[0])
    out_shape = _shape(eqn.outvars[0])
    out_dims: List[Optional[str]] = [None] * len(out_shape)
    groups = _reshape_groups(tuple(in_shape), tuple(out_shape))

    def lose(d: int, ax: str) -> None:
        st.gather(
            f"reshape {tuple(in_shape)} -> {tuple(out_shape)} "
            f"splits/merges the {ax!r}-sharded dim {d}",
            _aval_bytes(eqn.invars[0].aval), ax,
            where=f"{path} (reshape)")

    if groups is None:
        for d, ax in enumerate(vs.dims):
            if ax is not None:
                lose(d, ax)
        return [VSpec(tuple(out_dims), vs.pending)]
    for di, dj in groups:
        major_in = next((d for d in di if int(in_shape[d]) > 1),
                        di[0] if di else None)
        major_out = next((d for d in dj if int(out_shape[d]) > 1),
                         dj[0] if dj else None)
        for d in di:
            ax = vs.dims[d]
            if ax is None:
                continue
            n = st.size(ax)
            if d == major_in and major_out is not None and \
                    (n is None or int(out_shape[major_out]) % int(n) == 0):
                out_dims[major_out] = ax
            else:
                lose(d, ax)
    return [VSpec(tuple(out_dims), vs.pending)]


def _rule_transpose(st: _Walk, eqn, ins: List[VSpec],
                    path: str) -> List[VSpec]:
    vs = ins[0]
    if vs.dims is None:
        return [VSpec(None, vs.pending)]
    perm = eqn.params["permutation"]
    return [VSpec(tuple(vs.dims[p] for p in perm), vs.pending)]


def _rule_broadcast(st: _Walk, eqn, ins: List[VSpec],
                    path: str) -> List[VSpec]:
    vs = ins[0]
    out_shape = _shape(eqn.outvars[0])
    if vs.dims is None:
        return [VSpec(None, vs.pending)]
    in_shape = _shape(eqn.invars[0])
    bdims = eqn.params["broadcast_dimensions"]
    out_dims: List[Optional[str]] = [None] * len(out_shape)
    for i, od in enumerate(bdims):
        if vs.dims[i] is not None \
                and int(in_shape[i]) == int(out_shape[od]):
            out_dims[od] = vs.dims[i]
    return [VSpec(tuple(out_dims), vs.pending)]


def _rule_pad(st: _Walk, eqn, ins: List[VSpec], path: str) -> List[VSpec]:
    vs = ins[0]
    if vs.dims is None:
        return [VSpec(None, vs.pending)]
    cfg = eqn.params["padding_config"]
    out_dims = list(vs.dims)
    for d, (lo, hi, interior) in enumerate(cfg):
        if out_dims[d] is not None and (lo or hi or interior):
            st.gather(
                f"pad widens the {out_dims[d]!r}-sharded dim {d}",
                _aval_bytes(eqn.invars[0].aval), out_dims[d],
                where=f"{path} (pad)")
            out_dims[d] = None
    return [VSpec(tuple(out_dims), vs.pending)]


def _rule_slice(st: _Walk, eqn, ins: List[VSpec],
                path: str) -> List[VSpec]:
    vs = ins[0]
    if vs.dims is None:
        return [VSpec(None, vs.pending)]
    in_shape = _shape(eqn.invars[0])
    out_shape = _shape(eqn.outvars[0])
    out_dims = list(vs.dims)
    for d in range(len(in_shape)):
        if out_dims[d] is not None \
                and int(out_shape[d]) != int(in_shape[d]):
            st.gather(
                f"{eqn.primitive.name} cuts the {out_dims[d]!r}-sharded "
                f"dim {d} ({in_shape[d]} -> {out_shape[d]})",
                _aval_bytes(eqn.invars[0].aval), out_dims[d],
                where=f"{path} ({eqn.primitive.name})")
            out_dims[d] = None
    return [VSpec(tuple(out_dims), vs.pending)]


def _rule_squeeze(st: _Walk, eqn, ins: List[VSpec],
                  path: str) -> List[VSpec]:
    vs = ins[0]
    if vs.dims is None:
        return [VSpec(None, vs.pending)]
    drop = set(eqn.params["dimensions"])
    return [VSpec(tuple(ax for d, ax in enumerate(vs.dims)
                        if d not in drop), vs.pending)]


def _rule_concat(st: _Walk, eqn, ins: List[VSpec],
                 path: str) -> List[VSpec]:
    cdim = eqn.params["dimension"]
    for oi, (v, vs) in enumerate(zip(eqn.invars, ins)):
        if vs.dims is not None and len(vs.dims) > cdim \
                and vs.dims[cdim] is not None:
            st.gather(
                f"operand {oi} of concatenate is sharded "
                f"{vs.dims[cdim]!r} on the concat dim {cdim}",
                _aval_bytes(v.aval), vs.dims[cdim],
                where=f"{path} (concatenate)")
            ins[oi] = VSpec(tuple(None if d == cdim else ax
                                  for d, ax in enumerate(vs.dims)),
                            vs.pending)
    out = _eltwise_nonbroadcast_merge(st, eqn, ins, path, skip_dim=cdim)
    return out


def _eltwise_nonbroadcast_merge(st: _Walk, eqn, ins, path,
                                skip_dim: int) -> List[VSpec]:
    out_shape = _shape(eqn.outvars[0])
    nd = len(out_shape)
    dims: List[Optional[str]] = [None] * nd
    unknown = False
    for v, vs in zip(eqn.invars, ins):
        if vs.dims is None:
            unknown = True
            continue
        for d, ax in enumerate(vs.dims):
            if ax is None or d == skip_dim or d >= nd:
                continue
            if dims[d] is None:
                dims[d] = ax
            elif dims[d] != ax:
                st.gather(
                    f"concatenate operands disagree on dim {d} "
                    f"({dims[d]!r} vs {ax!r})", _aval_bytes(v.aval), ax,
                    where=f"{path} (concatenate)")
    pend = _union_pending(ins)
    return [VSpec(None if unknown else tuple(dims), pend)]


def _rule_reduce(st: _Walk, eqn, ins: List[VSpec],
                 path: str) -> List[VSpec]:
    vs = ins[0]
    axes = eqn.params.get("axes", ())
    name = eqn.primitive.name
    linear = name in ("reduce_sum",)
    if not linear:
        vs = st.charge_pending(eqn.invars[0], vs, path)
    if vs.dims is None:
        return [VSpec(None, vs.pending) for _ in eqn.outvars]
    pend = vs.pending
    out_dims = []
    for d, ax in enumerate(vs.dims):
        if d in axes:
            if ax is not None:
                # reducing over a sharded dim leaves per-device partial
                # results: a pending cross-replica reduce
                pend = pend | {ax}
        else:
            out_dims.append(ax)
    return [VSpec(tuple(out_dims), pend) for _ in eqn.outvars]


def _rule_gather(st: _Walk, eqn, ins: List[VSpec],
                 path: str) -> List[VSpec]:
    vs = ins[0]
    if vs.dims is None:
        return [_UNKNOWN]
    dn = eqn.params["dimension_numbers"]
    in_shape = _shape(eqn.invars[0])
    slice_sizes = tuple(eqn.params.get("slice_sizes", ()) or ())
    batching = set(getattr(dn, "operand_batching_dims", ()) or ())
    indexed = set(dn.start_index_map) | set(dn.collapsed_slice_dims)
    for d, ax in enumerate(vs.dims):
        if ax is None or d in batching:
            continue
        if d in indexed:
            st.gather(
                f"gather indexes the {ax!r}-sharded operand dim {d} "
                "(not a batching dim): every shard needs every other "
                "shard's rows", _aval_bytes(eqn.invars[0].aval), ax,
                where=f"{path} (gather)")
    out_shape = _shape(eqn.outvars[0])
    out_dims: List[Optional[str]] = [None] * len(out_shape)
    # batching dims lead the output and keep their placement
    for i, d in enumerate(sorted(batching)):
        if i < len(out_dims) and vs.dims[d] is not None:
            out_dims[i] = vs.dims[d]
    # window (offset) dims pass the operand placement through when the
    # slice keeps the WHOLE dim — the paged-KV reads (k_pages[table]:
    # page/head/head_dim are full-window dims) stay head-sharded, which
    # is what lets the walk prove the TP decode path reduce-not-gather.
    # A partial slice of a sharded dim is a real re-layout: gather it.
    window = [d for d in range(len(in_shape))
              if d not in dn.collapsed_slice_dims and d not in batching]
    offset = tuple(dn.offset_dims)
    for od, d in zip(offset, window):
        ax = vs.dims[d]
        if ax is None or d in indexed:
            # indexed dims were already reported (and charged) above —
            # an indexed-but-uncollapsed dim is also a window dim, and
            # double-charging it would inflate the comm estimate 2x
            continue
        full = (d < len(slice_sizes)
                and int(slice_sizes[d]) == int(in_shape[d]))
        if full and od < len(out_dims) and out_dims[od] is None:
            out_dims[od] = ax
        elif not full:
            st.gather(
                f"gather slices the {ax!r}-sharded operand dim {d} "
                f"({in_shape[d]} -> "
                f"{slice_sizes[d] if d < len(slice_sizes) else '?'})",
                _aval_bytes(eqn.invars[0].aval), ax,
                where=f"{path} (gather)")
    return [VSpec(tuple(out_dims), vs.pending)]


def _rule_scatter(st: _Walk, eqn, ins: List[VSpec],
                  path: str) -> List[VSpec]:
    vs = ins[0]
    if vs.dims is None:
        return [_UNKNOWN]
    dn = eqn.params["dimension_numbers"]
    batching = set(getattr(dn, "operand_batching_dims", ()) or ())
    touched = set(dn.scatter_dims_to_operand_dims) \
        | set(dn.inserted_window_dims)
    for d, ax in enumerate(vs.dims):
        if ax is None or d in batching:
            continue
        if d in touched:
            st.gather(
                f"{eqn.primitive.name} writes across the {ax!r}-sharded "
                f"operand dim {d} (not a batching dim)",
                _aval_bytes(eqn.invars[0].aval), ax,
                where=f"{path} ({eqn.primitive.name})")
    # scatter preserves the operand's shape and placement
    return [VSpec(vs.dims, _union_pending(ins))]


def _sharding_spec_of(sharding) -> Tuple[Optional[Tuple], Dict[str, int]]:
    """(normalized dims, axis sizes) of a NamedSharding-like object;
    (None, {}) when the sharding type is opaque (GSPMD bytes)."""
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return None, {}
    try:
        sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:
        sizes = {}
    return normalize_spec(tuple(spec)), sizes


def _apply_constraint(st: _Walk, var, vs: VSpec, tgt_dims, sizes,
                      where: str) -> VSpec:
    """Cost the transition ``vs`` -> ``tgt_dims`` (a sharding
    constraint or an enforced out_sharding): pending partial-sums over
    an axis the target shards become reduce-scatters (the ZeRO trick),
    other pendings full psums; a sharded axis the target drops is an
    all-gather; replicated -> sharded is a free local slice."""
    for a, n in sizes.items():
        st.axes.setdefault(a, n)
    b = _aval_bytes(getattr(var, "aval", None))
    nd = len(_shape(var))
    tgt = list(tgt_dims) + [None] * (nd - len(tgt_dims)) \
        if tgt_dims is not None else None
    if tgt is None:
        return st.charge_pending(var, vs, where)
    tgt_axes = {a for a in tgt if a is not None}
    for axis in vs.pending:
        key = (id(var), axis)
        if key in st._charged:
            continue
        st._charged.add(key)
        if axis in tgt_axes:
            st.comm += reduce_scatter_bytes(b, st.size(axis))
        else:
            st.comm += all_reduce_bytes(b, st.size(axis))
    if vs.dims is not None:
        src_axes = {a for a in vs.dims if a is not None}
        for axis in src_axes - tgt_axes:
            st.comm += all_gather_bytes(b, st.size(axis))
        for axis in src_axes & tgt_axes:
            if vs.dims.index(axis) != tgt.index(axis):
                # moved to a different dim: an all-to-all-ish reshard
                st.comm += all_to_all_bytes(b, st.size(axis))
    return VSpec(tuple(tgt))


def _rule_constraint(st: _Walk, eqn, ins: List[VSpec],
                     path: str) -> List[VSpec]:
    tgt_dims, sizes = _sharding_spec_of(eqn.params.get("sharding"))
    return [_apply_constraint(st, eqn.invars[0], ins[0], tgt_dims, sizes,
                              f"{path} (sharding_constraint)")]


def _rule_collective(st: _Walk, eqn, ins: List[VSpec],
                     path: str) -> List[VSpec]:
    kind = _COLLECTIVES[eqn.primitive.name]
    axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(axes, (str, int)):
        axes = (axes,)
    for v in eqn.invars:
        b = _aval_bytes(getattr(v, "aval", None))
        for ax in axes:
            n = st.size(str(ax))
            if kind == "ar":
                st.comm += all_reduce_bytes(b, n)
            elif kind == "ag":
                # cost on the gathered OUTPUT bytes
                ob = sum(_aval_bytes(o.aval) for o in eqn.outvars)
                st.comm += all_gather_bytes(ob, n)
            elif kind == "rs":
                st.comm += reduce_scatter_bytes(b, n)
            elif kind == "a2a":
                st.comm += all_to_all_bytes(b, n)
            else:                                      # ppermute
                st.comm += float(b)
    return [_UNKNOWN for _ in eqn.outvars]


_EQN_RULES: Dict[str, Callable] = {
    "dot_general": _rule_dot_general,
    "reshape": _rule_reshape,
    "transpose": _rule_transpose,
    "broadcast_in_dim": _rule_broadcast,
    "pad": _rule_pad,
    "slice": _rule_slice,
    "dynamic_slice": _rule_slice,
    "squeeze": _rule_squeeze,
    "concatenate": _rule_concat,
    "gather": _rule_gather,
    "scatter": _rule_scatter,
    "scatter-add": _rule_scatter,
    "scatter_add": _rule_scatter,
    "sharding_constraint": _rule_constraint,
}
for _name in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
              "reduce_and", "reduce_or", "argmax", "argmin"):
    _EQN_RULES[_name] = _rule_reduce


# ---------------------------------------------------------------------------
# recursive jaxpr walk
# ---------------------------------------------------------------------------


def _as_closed(obj):
    """Jaxpr-or-ClosedJaxpr -> (jaxpr, consts)."""
    jaxpr = getattr(obj, "jaxpr", obj)
    consts = getattr(obj, "consts", ())
    return jaxpr, consts


def _walk_jaxpr(st: _Walk, obj, in_specs: Sequence[VSpec],
                path: str = "") -> List[VSpec]:
    """Propagate VSpecs through one (possibly nested) jaxpr; returns
    the outvars' VSpecs.  ``in_specs`` aligns positionally with the
    jaxpr's invars (missing/short -> unknown)."""
    import jax

    jaxpr, _consts = _as_closed(obj)
    env: Dict[int, VSpec] = {}
    for cv in jaxpr.constvars:
        # jaxpr consts are baked into the executable: replicated by
        # construction on every device
        env[id(cv)] = _repl(len(_shape(cv)))
    for i, v in enumerate(jaxpr.invars):
        vs = in_specs[i] if i < len(in_specs) else _UNKNOWN
        env[id(v)] = vs if vs is not None else _UNKNOWN

    def read(v) -> VSpec:
        if isinstance(v, jax.core.Literal):
            return _repl(len(_shape(v)))
        return env.get(id(v), _UNKNOWN)

    for i, eqn in enumerate(jaxpr.eqns):
        here = f"{path}{i}"
        ins = [read(v) for v in eqn.invars]
        outs = _run_eqn(st, eqn, ins, here)
        for o, vs in zip(eqn.outvars, outs):
            env[id(o)] = vs if vs is not None else _UNKNOWN
    return [read(v) for v in jaxpr.outvars]


def _align_last(ins: List[VSpec], n: int) -> List[VSpec]:
    """Align outer operand specs onto ``n`` inner invars the way the
    drift rule does: the LAST n operands map positionally (pjit and
    custom_* calls pass consts first)."""
    if n <= len(ins):
        return ins[-n:]
    return [_UNKNOWN] * (n - len(ins)) + ins


def _run_eqn(st: _Walk, eqn, ins: List[VSpec], path: str) -> List[VSpec]:
    name = eqn.primitive.name
    rule = _EQN_RULES.get(name)
    if rule is not None:
        return rule(st, eqn, ins, path)
    if name in _COLLECTIVES:
        return _rule_collective(st, eqn, ins, path)
    if name == "pjit" or name == "closed_call" or name == "remat" \
            or name == "checkpoint" or name == "custom_jvp_call" \
            or name == "custom_vjp_call" or name == "custom_vjp_call_jaxpr":
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
            or eqn.params.get("fun_jaxpr")
        if inner is None:
            return [_UNKNOWN for _ in eqn.outvars]
        n_in = len(_as_closed(inner)[0].invars)
        outs = _walk_jaxpr(st, inner, _align_last(ins, n_in),
                           path=f"{path}.")
        # primal outputs lead; anything extra (residuals) stays unknown
        return (outs + [_UNKNOWN] * len(eqn.outvars))[:len(eqn.outvars)]
    if name == "cond":
        branches = eqn.params.get("branches", ())
        merged: Optional[List[VSpec]] = None
        best_comm = 0.0
        for br in branches:
            sub = _Walk(site=st.site, contract=st.contract,
                        axes=dict(st.axes))
            n_in = len(_as_closed(br)[0].invars)
            outs = _walk_jaxpr(sub, br, _align_last(ins[1:], n_in),
                               path=f"{path}.")
            st.diags.extend(sub.diags)
            best_comm = max(best_comm, sub.comm)
            if merged is None:
                merged = list(outs)
            else:
                merged = [a if (a.dims is not None and a.dims == b.dims)
                          else VSpec(None, a.pending | b.pending)
                          for a, b in zip(merged, outs)]
        st.comm += best_comm
        outs = merged or []
        return (outs + [_UNKNOWN] * len(eqn.outvars))[:len(eqn.outvars)]
    if name == "while":
        body = eqn.params.get("body_jaxpr")
        if body is not None:
            n_in = len(_as_closed(body)[0].invars)
            _walk_jaxpr(st, body, _align_last(ins, n_in),
                        path=f"{path}.")           # one trip, like xla
        return [_UNKNOWN for _ in eqn.outvars]
    if name == "scan":
        inner = eqn.params.get("jaxpr")
        if inner is None:
            return [_UNKNOWN for _ in eqn.outvars]
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        length = max(1, int(eqn.params.get("length", 1)))
        n_in = len(_as_closed(inner)[0].invars)
        seed = list(ins[:nc + ncar])               # xs slices: unknown
        seed += [_UNKNOWN] * (n_in - len(seed))
        sub = _Walk(site=st.site, contract=st.contract,
                    axes=dict(st.axes))
        outs = _walk_jaxpr(sub, inner, seed[:n_in], path=f"{path}.")
        st.diags.extend(sub.diags)
        st.comm += sub.comm * length               # per-trip collectives
        carries = outs[:ncar]                      # stacked ys: unknown
        res = carries + [_UNKNOWN] * (len(eqn.outvars) - ncar)
        return res[:len(eqn.outvars)]
    if name == "shard_map":
        inner = eqn.params.get("jaxpr")
        if inner is not None:
            sub = _Walk(site=st.site, contract=st.contract,
                        axes=dict(st.axes))
            mesh = eqn.params.get("mesh")
            if mesh is not None:
                try:
                    for a, n in dict(mesh.shape).items():
                        sub.axes.setdefault(str(a), int(n))
                except Exception:
                    pass
            n_in = len(_as_closed(inner)[0].invars)
            # manual region: per-shard shapes, named specs don't apply —
            # walk only to collect the explicit collectives' bytes
            _walk_jaxpr(sub, inner, [_UNKNOWN] * n_in, path=f"{path}.")
            st.comm += sub.comm
        return [_UNKNOWN for _ in eqn.outvars]
    subs = _sub_jaxprs(eqn)
    if subs:
        # unrecognized higher-order op: collect collective costs from
        # the inside, propagate nothing
        for s in subs:
            sub = _Walk(site=st.site, contract=st.contract,
                        axes=dict(st.axes))
            _walk_jaxpr(sub, s, [_UNKNOWN] * len(_as_closed(s)[0].invars),
                        path=f"{path}.")
            st.diags.extend(sub.diags)
            st.comm += sub.comm
        return [_UNKNOWN for _ in eqn.outvars]
    # default: elementwise when the shapes broadcast; unknown otherwise
    out_shape = _shape(eqn.outvars[0]) if eqn.outvars else ()
    if eqn.invars and all(_broadcasts(_shape(v), out_shape)
                          for v in eqn.invars):
        linear = name in _PENDING_PASS
        return _eltwise(st, eqn, ins, path, linear=linear)
    if not eqn.invars:
        return [_repl(len(_shape(o))) for o in eqn.outvars]
    for v, vs in zip(eqn.invars, ins):
        st.charge_pending(v, vs, path)
    return [_UNKNOWN for _ in eqn.outvars]


def _broadcasts(ish: Tuple[int, ...], osh: Tuple[int, ...]) -> bool:
    if len(ish) > len(osh):
        return False
    for i, o in zip(reversed(ish), reversed(osh)):
        if int(i) != 1 and int(i) != int(o):
            return False
    return True


# ---------------------------------------------------------------------------
# per-capture audit
# ---------------------------------------------------------------------------


def _leaf_path_key(path) -> str:
    """Pytree key path -> a stable lookup string: dict keys / sequence
    indices / attr names joined by '/'.  A flat ``{name: array}`` param
    dict yields exactly ``name``."""
    parts = []
    for p in path:
        for attr in ("key", "idx", "name"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def _resolve_leaf_specs(arg, spec):
    """Pairs of (per-leaf spec, leaf) for one positional arg.  A plain
    spec broadcasts over every leaf (the documented semantics); a DICT
    spec maps pytree key paths to per-leaf specs — the TP serving step
    declares its params this way, one megatron placement per weight —
    with unmatched leaves left None (undeclared, never a finding)."""
    import jax

    if not isinstance(spec, dict):
        return [(spec, leaf) for leaf in jax.tree.leaves(arg)]
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(arg)[0]:
        key = _leaf_path_key(path)
        s = spec.get(key)
        if s is None and "/" in key:
            s = spec.get(key.rsplit("/", 1)[-1])
        out.append((s, leaf))
    return out


def _leaf_specs_for_call(st: _Walk, cap: CapturedCall,
                         contract: SiteContract) -> List[VSpec]:
    """Per-invar seed VSpecs: each positional arg's declared spec
    (broadcast rule; dict specs resolve per leaf by pytree key) applied
    to every one of its array leaves, in the same flatten order
    ``make_jaxpr`` uses; kwargs leaves are unknown.  Contract problems
    (bad axis, duplicate axis, replicated expect_sharded arg) are
    reported here."""
    axes = st.axes
    seeds: List[VSpec] = []
    n_args = len(cap.args)
    for i, arg in enumerate(cap.args):
        spec = _spec_for(contract.in_specs, i, n_args)
        any_sharded = False
        has_leaf = False
        for leaf_spec, leaf in _resolve_leaf_specs(arg, spec):
            if hasattr(leaf, "shape"):
                has_leaf = True
                vs, probs = apply_spec(leaf_spec, tuple(leaf.shape), axes)
                for rule, msg in probs:
                    st.report(Severity.ERROR, rule,
                              f"arg {i}: {msg}")
                if vs.dims is not None \
                        and any(a is not None for a in vs.dims):
                    any_sharded = True
                seeds.append(vs)
            else:
                seeds.append(_UNKNOWN)
        if i in contract.expect_sharded and has_leaf and not any_sharded:
            st.report(
                Severity.ERROR, "accidental-replication",
                f"arg {i} is declared expect_sharded but its effective "
                "input spec carries no mesh axis — the plan's sharding "
                "never reached this argument (every device holds a full "
                "replica)")
    import jax

    for leaf in jax.tree.leaves(cap.kwargs):
        seeds.append(_UNKNOWN)
    return seeds


def _declares_sharding(contract: SiteContract) -> bool:
    for specs in (contract.in_specs, contract.out_specs):
        if not specs:
            continue
        for s in specs:
            entries = s.values() if isinstance(s, dict) else (s,)
            for e in entries:
                ns = normalize_spec(e)
                if ns and any(a is not None for a in ns):
                    return True
    return False


def _out_sharding_targets(st: _Walk, cap: CapturedCall, n_out: int):
    """Per-output (dims, sizes) enforced by the jit's requested
    ``out_shardings`` kwarg (the zero placement identities), or None."""
    import jax

    osh = cap.jit_kwargs.get("out_shardings")
    if osh is None:
        return None
    leaves = jax.tree.leaves(osh, is_leaf=lambda x: hasattr(x, "spec")
                             or isinstance(x, (tuple,)) and not x)
    if not leaves:
        return None
    out = []
    for i in range(n_out):
        leaf = leaves[i] if i < len(leaves) else leaves[-1] \
            if len(leaves) == 1 else None
        if leaf is None:
            out.append((None, {}))
        else:
            out.append(_sharding_spec_of(leaf))
    return out


def _audit_capture(site: str, cap: CapturedCall, contract: SiteContract,
                   closed) -> Tuple[List[Diagnostic], float]:
    """Run the propagation walk over ONE materialized signature;
    returns (diagnostics, estimated collective bytes per call)."""
    from paddle_tpu.platform.flags import FLAGS

    st = _Walk(site=site, contract=contract,
               axes={a: int(n) for a, n in contract.mesh_axes})
    seeds = _leaf_specs_for_call(st, cap, contract)
    if len(seeds) != len(closed.jaxpr.invars):
        # flatten-order mismatch (exotic pytree): audit without seeds —
        # unknowns never produce findings, so this degrades safely
        seeds = [_UNKNOWN] * len(closed.jaxpr.invars)
    # weight-shaped consts are replicated by construction: in a site
    # whose contract shards anything, that IS the accidental replication
    if _declares_sharding(contract):
        limit = contract.big_arg_bytes if contract.big_arg_bytes \
            is not None else int(FLAGS.xla_audit_big_arg_bytes)
        for cv, c in zip(closed.jaxpr.constvars, closed.consts):
            nbytes = getattr(c, "nbytes", 0) or 0
            if nbytes > limit:
                st.report(
                    Severity.ERROR, "accidental-replication",
                    f"{tuple(getattr(c, 'shape', ()))} "
                    f"{getattr(c, 'dtype', '?')} ({nbytes} bytes) is a "
                    "jaxpr const — consts replicate onto every device, "
                    "so a sharded site pays a full copy per chip; pass "
                    "it as an argument with a declared spec",
                    where="consts")
    outs = _walk_jaxpr(st, closed, seeds)
    # the jit's own out_shardings are an enforced final resharding
    # (the zero placement identities' all-gather lives here)
    targets = _out_sharding_targets(st, cap, len(closed.jaxpr.outvars))
    if targets is not None:
        outs = [_apply_constraint(st, v, vs, dims, sizes, "out")
                for v, vs, (dims, sizes)
                in zip(closed.jaxpr.outvars, outs, targets)]
    # leftover partial sums cross the jit boundary: GSPMD inserts the
    # all-reduce before returning (the data-parallel grad psum)
    outs = [st.charge_pending(v, vs, "out")
            for v, vs in zip(closed.jaxpr.outvars, outs)]
    n_out = len(outs)
    for i, (v, vs) in enumerate(zip(closed.jaxpr.outvars, outs)):
        declared = normalize_spec(_spec_for(contract.out_specs, i, n_out))
        if declared is None or vs.dims is None:
            continue
        nd = len(_shape(v))
        want = (tuple(declared) + (None,) * nd)[:nd]
        if tuple(vs.dims) != want:
            st.report(
                Severity.ERROR, "contract-mismatch",
                f"output {i} is inferred {_fmt_dims(vs.dims)} but the "
                f"contract declares {_fmt_dims(want)} — the site's "
                "declared plan and the compiled program disagree")
    if contract.comm_bytes is not None and st.comm > contract.comm_bytes:
        st.report(
            Severity.ERROR, "comm-budget",
            f"estimated {st.comm:.0f} collective bytes per call exceed "
            f"the declared comm_bytes budget {contract.comm_bytes:.0f} "
            "— an unplanned resharding/collective entered the compiled "
            "step")
    elif st.comm > 0:
        if contract.comm_bytes is not None:
            st.report(
                Severity.INFO, "comm-budget",
                f"estimated {st.comm:.0f} collective bytes per call "
                f"(within the declared {contract.comm_bytes:.0f}-byte "
                "budget)")
        else:
            st.report(
                Severity.INFO, "comm-budget",
                f"estimated {st.comm:.0f} collective bytes per call "
                "(unbudgeted; declare SiteContract(comm_bytes=...) to "
                "gate)")
    return st.diags, st.comm


def _fmt_dims(dims) -> str:
    return "P(" + ", ".join(str(a) for a in dims) + ")"


# ---------------------------------------------------------------------------
# site / auditor surface
# ---------------------------------------------------------------------------


@dataclass
class ShardReport:
    """Sharding-audit result for one site across its signatures."""

    site: str
    signatures: int = 0
    comm_bytes: float = 0.0             # max over signatures, per call
    diagnostics: List[Diagnostic] = field(default_factory=list)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity is Severity.ERROR]


def audit_record_sharding(name: str, rec: SiteRecord,
                          rules: Optional[Sequence[str]] = None
                          ) -> ShardReport:
    """Audit every captured signature of one site through its OWN
    captured contract (xla.py's per-capture fallback chain); dedupe by
    message across signatures; stamp the comm estimate onto the record
    so ``auditor().publish`` lands it as ``comm_bytes_total{site=}``."""
    from paddle_tpu.analysis.xla import materialize_jaxpr

    rep = ShardReport(site=name)
    seen: set = set()
    for _sig, cap in list(rec.captured.items()):
        contract = cap.contract or rec.contract or _DEFAULT_CONTRACT
        closed = materialize_jaxpr(cap)
        diags, comm = _audit_capture(name, cap, contract, closed)
        rep.signatures += 1
        rep.comm_bytes = max(rep.comm_bytes, comm)
        for d in diags:
            if rules is not None and d.vars[1] not in rules:
                continue
            if d.message not in seen:
                seen.add(d.message)
                rep.diagnostics.append(d)
    rec.comm_bytes = rep.comm_bytes
    return rep


def audit_sharding_sites(aud=None, sites: Optional[Sequence[str]] = None,
                         rules: Optional[Sequence[str]] = None
                         ) -> Dict[str, ShardReport]:
    """Audit every captured ``audit_jit`` site; {site: ShardReport}.
    Sites with no captures are skipped here — the driver prints the
    loud 'declared but not audited' notice for the contract-bearing
    ones, so a stub plan cannot silently pass."""
    aud = aud if aud is not None else auditor()
    out: Dict[str, ShardReport] = {}
    for name, rec in sorted(aud.sites.items()):
        if sites is not None and name not in sites:
            continue
        if not rec.captured:
            continue
        out[name] = audit_record_sharding(name, rec, rules=rules)
    return out


# ---------------------------------------------------------------------------
# drives (CLI + clean-run test pins share them)
# ---------------------------------------------------------------------------


def ensure_virtual_devices(n: int) -> int:
    """Force ``n`` virtual CPU devices for a CLI run (same trick as
    tests/conftest.py) — must run before the first backend
    initialization; returns the actual device count either way."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if n > 1 and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            flags + f" --xla_force_host_platform_device_count={int(n)}"
    import jax

    return len(jax.devices())


def drive_zero_placement(n_devices: Optional[int] = None):
    """Exercise the ZeRO placement jits (``zero.reshard`` /
    ``zero.replicate``) on a data mesh: place a host optimizer state
    into the flat sharded layout, RE-place the already-flat device
    state (the compiled reshard), and gather it back layout-independent
    (the compiled all-gather the checkpoint save pays).  Requires
    ``FLAGS.jit_audit`` on before the call.  Returns the plan (or None
    when only one device is available — nothing shards)."""
    import jax
    import numpy as np

    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.zero import build_zero_plan

    devs = jax.devices()
    n = int(n_devices or min(8, len(devs)))
    if n < 2:
        return None
    mesh = make_mesh((n,), ("data",), devs[:n])
    params = {"w": np.zeros((8, 8), np.float32),
              "b": np.zeros((9,), np.float32)}       # padding case
    plan = build_zero_plan(mesh, params)
    state = {"slots": {"momentum": {
        k: np.ones_like(v) for k, v in params.items()}}}
    placed = plan.shard_state(state)                 # host -> flat shards
    replaced = plan.shard_state(placed)              # zero.reshard site
    gathered = plan.gather_state(replaced)           # zero.replicate site
    for k, v in params.items():
        np.testing.assert_allclose(
            np.asarray(gathered["slots"]["momentum"][k]),
            np.ones_like(v))
    return plan


def drive_serving_tp_steady_state(tp: int = 2, kv_dtype: str = "int8"):
    """The tensor-parallel serving steady state the gate audits IN
    ADDITION to the replicated one: a ``model``-axis mesh of ``tp``
    chips, int8 pool, GQA heads — warmup covers every (decode, prefill)
    pair bucket the replay uses, a full-cover cache hit exercises the
    sharded COW fork and a fault-poisoned request the sharded scrub, so
    ``serving.step``/``fork_page``/``zero_pages`` all capture TP
    signatures under the flipped model-axis contracts.  The model
    geometry deliberately differs from the replicated drive's (H4/KVH2
    vs H2) so the two engines' signatures never collide at the shared
    sites.  Requires ``FLAGS.jit_audit`` on before the call; returns
    the engine (None when fewer than ``tp`` devices exist — the CLI's
    virtual-8 guarantee makes that a test-environment case only)."""
    import jax
    import numpy as np

    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.serving import DecoderLM, ServingEngine
    from paddle_tpu.serving.faults import FaultPlan

    devs = jax.devices()
    if len(devs) < tp:
        return None
    mesh = make_mesh((tp,), ("model",), devs[:tp])
    model = DecoderLM(vocab_size=50, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=8, max_positions=128)
    params = model.init_params(jax.random.PRNGKey(1))
    faults = FaultPlan()
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=64, max_pages_per_seq=12, max_slots=4,
                        buckets=(4, 8, 16), prefill_chunk=8,
                        kv_dtype=kv_dtype, faults=faults, mesh=mesh)
    rng = np.random.RandomState(1)
    shared = rng.randint(2, 50, size=8).tolist()   # two FULL pages
    eng.submit(shared, max_tokens=6)
    eng.run(max_ticks=200)
    eng.submit(shared, max_tokens=6)               # full-cover hit: fork
    eng.run(max_ticks=200)
    eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=12)
    eng.step()
    eng.submit(rng.randint(2, 50, size=20).tolist(), max_tokens=8)
    eng.run(max_ticks=300)
    # poisoned decode: the sharded FAILED scrub (serving.zero_pages)
    bad = eng.submit(rng.randint(2, 50, size=5).tolist(), max_tokens=6)
    eng.step()
    faults.poison_nan(bad)
    eng.run(max_ticks=200)
    return eng


def replay_serving_tp(eng) -> None:
    """The sealed steady-state replay for the TP engine — fresh traffic
    over the same pair buckets, so 'TP adds no compile dimension' is
    checked by the same RETRACE fold-in as the replicated replay."""
    import numpy as np

    rng = np.random.RandomState(9)
    eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=10)
    eng.step()
    eng.submit(rng.randint(2, 50, size=17).tolist(), max_tokens=6)
    eng.run(max_ticks=300)


def drive_page_migration(eng):
    """Exercise ``serving.import_pages``: export one RUNNING request's
    page chain from ``eng`` and splice it straight back in
    (migrate.import_chain), so the donated import scatter captures
    under the KV contract instead of standing as a declared-but-dead
    site.  Returns the imported rid (or None if the engine never made
    the request migratable — a scheduler-pressure case the caller
    surfaces as a coverage notice)."""
    import numpy as np

    from paddle_tpu.serving.migrate import export_chain, import_chain

    rng = np.random.RandomState(11)
    rid = eng.submit(rng.randint(2, 50, size=9).tolist(), max_tokens=8)
    for _ in range(60):
        if rid in eng.migratable_rids():
            break
        eng.step()
    else:
        eng.cancel(rid)
        return None
    blob = export_chain(eng, rid)
    rid2 = import_chain(eng, blob)
    eng.cancel(rid)
    if rid2 is not None:
        eng.cancel(rid2)
    return rid2


def drive_pipeline_moe_train_step(stages: int = 4, microbatches: int = 4):
    """Drive a REAL pipeline-parallel train step plus an expert-parallel
    MoE forward/backward so ``parallel.pipeline`` and ``parallel.moe``
    capture under their closed-form contracts (budget == estimate — any
    extra collective trips the gate):

    - a 4-layer transformer LM on a ``(data=2, stage=4)`` mesh through
      ``trainer.SGD(pipeline=PipelineConfig(...), zero=1)`` — one
      guardable jitted step running the GPipe fill+drain schedule with
      ZeRO-sharded boundary-param optimizer state (the 4D composition);
    - a top-2-routed ``moe_ffn`` with drop-rate stats (fwd+grad) and a
      top-1 forward on an 8-way ``expert`` mesh.

    Requires ``FLAGS.jit_audit`` on before the call.  Returns the
    trainer (None when fewer than ``2 * stages`` devices exist — the
    CLI's virtual-8 guarantee makes that a test-environment case)."""
    import jax
    import numpy as np

    devs = jax.devices()
    if len(devs) < 2 * stages:
        return None
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu import trainer as ptrainer
    from paddle_tpu.models import transformer
    from paddle_tpu.parallel.pipeline import PipelineConfig

    vocab, d, n_layers, n_heads, t = 64, 32, 4, 2, 16
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=n_layers, n_heads=n_heads,
        max_len=t)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = ptrainer.SGD(cost=cost, parameters=params,
                       update_equation=popt.Adam(learning_rate=1e-3),
                       pipeline=PipelineConfig(num_stages=stages,
                                               microbatches=microbatches,
                                               n_layers=n_layers,
                                               n_heads=n_heads),
                       zero=1)
    step = sgd._build_step()
    rng = np.random.RandomState(3)
    samples = []
    for _ in range(2 * microbatches):
        toks = rng.randint(0, vocab, size=t)
        samples.append((toks.tolist(), list(range(t)),
                        np.roll(toks, -1).tolist()))
    feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
    feeds = sgd._shard_feeds(feeder.feed(samples))
    step(sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state,
         jax.random.PRNGKey(0), feeds)

    from paddle_tpu.parallel import moe as pmoe
    from paddle_tpu.parallel.mesh import make_mesh

    n = min(8, len(devs))
    mesh = make_mesh((n,), ("expert",), devs[:n])
    mp = pmoe.init_moe_params(jax.random.PRNGKey(5), d_model=16,
                              hidden=32, num_experts=n)
    x = jax.random.normal(jax.random.PRNGKey(6), (8 * n, 16))

    def moe_loss(p, xx):
        y, aux, stats = pmoe.moe_ffn(mesh, xx, p, top_k=2,
                                     return_stats=True)
        return (y * y).mean() + 0.01 * aux, stats

    (_, stats), _ = jax.value_and_grad(moe_loss, has_aux=True)(mp, x)
    pmoe.record_moe_stats(stats)        # the metrics-registry seam
    # top-1 wrap key too — distinct token count, so the two dispatch
    # geometries stay distinct signatures at the shared site (the
    # RETRACE fold would flag same-signature recompiles)
    pmoe.moe_ffn(mesh, x[:4 * n], mp, top_k=1)
    return sgd


def run_sharding_audit(printer: Callable[[str], None] = print,
                       rules: Optional[Sequence[str]] = None
                       ) -> Tuple[Dict[str, ShardReport],
                                  List[Diagnostic]]:
    """The acceptance run: flip ``FLAGS.jit_audit`` on, drive the same
    serving + trainer steady states as the xla gate PLUS the ZeRO
    placement jits, the pipeline-parallel train step and the
    expert-parallel MoE dispatch (closed-form contracts, budget ==
    estimate), seal, and replay a steady-state serving burst — then run
    the sharding rules over every captured site.  Returns (reports,
    all_diagnostics); RETRACE diagnostics from the sealed replay fold
    in, same contract as the xla gate."""
    from paddle_tpu.analysis.xla import (drive_serving_steady_state,
                                         drive_trainer_step)
    from paddle_tpu.platform.flags import FLAGS

    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    aud = auditor()
    aud.reset()
    try:
        eng = drive_serving_steady_state(seal=False)
        drive_trainer_step()
        plan = drive_zero_placement()
        # the tensor-parallel steady state rides the same gate: its
        # model-axis contracts (megatron param specs, sharded pool,
        # closed-form psum budget) audit next to the replicated
        # baseline, so an implicit all-gather or comm-budget regression
        # on the TP decode hot path fails tier-1 through the SAME
        # ladder exit as any other sharding finding
        tp_eng = drive_serving_tp_steady_state()
        pipe_sgd = drive_pipeline_moe_train_step()
        migrated = drive_page_migration(eng)
        aud.seal()
        import numpy as np

        rng = np.random.RandomState(7)
        eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=12)
        eng.step()
        eng.submit(rng.randint(2, 50, size=17).tolist(), max_tokens=8)
        eng.run(max_ticks=300)
        if tp_eng is not None:
            # sealed TP replay: TP must not add a compile dimension
            replay_serving_tp(tp_eng)
        reports = audit_sharding_sites(aud, rules=rules)
    finally:
        FLAGS.jit_audit = old
    diags: List[Diagnostic] = []
    for name, rep in reports.items():
        printer(f"== {name}: {rep.signatures} signature(s), "
                f"est {rep.comm_bytes:.0f} collective bytes/call")
        for d in rep.diagnostics:
            printer(f"  {d}")
        diags.extend(rep.diagnostics)
    if plan is None:
        printer("== zero placement: <2 devices, nothing shards — the "
                "ZeRO reduce-scatter/all-gather pair was NOT audited "
                "(run with virtual devices to cover it)")
    if tp_eng is None:
        printer("== serving tp: <2 devices — the tensor-parallel "
                "serving contracts were NOT audited (run with virtual "
                "devices to cover them)")
    if pipe_sgd is None:
        printer("== pipeline/moe: <8 devices — the pipeline-parallel "
                "train step and expert-parallel MoE contracts were NOT "
                "audited (run with virtual devices to cover them)")
    if migrated is None:
        printer("== page migration: the export/import splice never ran "
                "(request not migratable) — serving.import_pages was "
                "NOT audited this run")
    # a contract-bearing site the drives never compiled is a coverage
    # hole, not a pass — the pipeline/MoE stubs land here by design
    for name, rec in sorted(aud.sites.items()):
        if rec.contract is not None and not rec.captured:
            printer(f"== {name}: declared a sharding contract but "
                    "captured no signatures this run — its plan was "
                    "NOT audited (stub or dead site)")
    retraces = list(aud.diagnostics)
    for d in retraces:
        printer(f"  {d}")
    diags.extend(retraces)
    return reports, diags
