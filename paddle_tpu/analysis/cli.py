"""``python -m paddle_tpu.analysis`` — the two analysis CLIs.

``program <script.py> [--fetch NAME ...] [--feed NAME ...] [--strict]``
    Execute the script (a fluid graph-building file) under a fresh
    default program and verify every ``fluid.Program`` it leaves behind:
    the default program plus any Program bound to a module-level name.
    Exit 1 when any ERROR diagnostic fires (``--strict``: any finding).

``lint [paths...] [--rule NAME ...]``
    Run the repo-invariant linter (default: the whole ``paddle_tpu``
    package).  Findings print one per line; a nonzero count ends with a
    ``LINT-FAIL`` tagged line and exit 1 — ``tools_tier1.sh`` greps the
    tag and turns it into exit code 5.

``xla [--rule NAME ...] [--strict]``
    Drive the sealed mixed serving steady state (int8 KV, prefix cache
    on) plus one trainer step under ``FLAGS.jit_audit``, then audit the
    jaxpr of every captured ``audit_jit`` site against its declared
    :class:`~paddle_tpu.analysis.retrace.SiteContract` (donation, dtype
    drift, host transfers, const capture, collectives, memory/FLOP
    budgets).  Exit 0 = clean, 1 = XLA-AUDIT findings, 2 = the auditor
    itself crashed — ``tools_tier1.sh`` branches on the exit status and
    turns 1/2 into ladder exit 8.

``sharding [--rule NAME ...] [--strict]``
    Static GSPMD sharding-propagation audit: the same sealed serving +
    trainer steady states as the xla gate plus the ZeRO placement jits
    on a virtual-8 mesh (``FLAGS.shard_audit_virtual_devices`` forced
    before backend init), checked against each site's declared
    ``PartitionSpec`` contract — contract-mismatch, implicit
    all-gathers, accidental replication, axis collisions, and the
    collective-bytes budget (``SHARD-AUDIT`` findings).  Exit 0 =
    clean, 1 = findings, 2 = crash — ladder exit 9.

``concurrency [--rule NAME ...] [--strict]``
    The concurrency auditor: ``guarded-by`` (CONC-AUDIT lock-discipline
    checker over the ``# guarded_by(...)`` annotations), ``state-table``
    (PROTO-AUDIT static check of every literal assignment site against
    the declared lifecycle state machines), ``transition-runtime`` (the
    same machines checked dynamically through the transition recorder
    while the seeded chaos drives run), and ``schedule-permute``
    (SCHED-AUDIT: replay each chaos drive under permuted intra-tick
    schedules and fail on any terminal-fingerprint divergence, dumping
    an OBS-POSTMORTEM for the minimal divergent prefix).  Exit 0 =
    clean, 1 = findings, 2 = crash — ladder exit 14.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def cmd_program(args) -> int:
    import runpy

    from paddle_tpu.analysis.diagnostics import Severity, format_report
    from paddle_tpu.analysis.program_check import verify_program
    from paddle_tpu.fluid.framework import (Program, default_main_program,
                                            reset_default_program)

    reset_default_program()
    mod = runpy.run_path(args.script)
    programs = {"<default program>": default_main_program()}
    for name, val in mod.items():
        if isinstance(val, Program):
            programs[name] = val
    # an untouched default program is noise when the script builds its
    # own Programs explicitly
    if len(programs) > 1 and not default_main_program().global_block().ops:
        programs.pop("<default program>")

    worst = 0
    for name, prog in programs.items():
        # --fetch/--feed describe ONE run contract; applying them to
        # every module-level Program would fabricate dangling-fetch
        # errors on programs (pruned test graphs, sub-builds) they never
        # belonged to — so they bind to the default program only, unless
        # the script builds exactly one Program
        scoped = name == "<default program>" or len(programs) == 1
        fetch = (args.fetch or None) if scoped else None
        feed = (args.feed or None) if scoped else None
        diags = verify_program(prog, fetch_names=fetch, feed_names=feed)
        print(format_report(
            diags, title=f"== {args.script} :: {name} "
                         f"({len(prog.global_block().ops)} ops)"))
        errs = [d for d in diags if d.severity is Severity.ERROR]
        if errs or (args.strict and diags):
            worst = 1
    return worst


def cmd_lint(args) -> int:
    from paddle_tpu.analysis.lint import RULES, run_lint

    unknown = [r for r in (args.rule or []) if r not in RULES]
    if unknown:
        print(f"unknown rule(s) {unknown}; known: {sorted(RULES)}",
              file=sys.stderr)
        return 2
    findings = run_lint(paths=args.paths or None, rules=args.rule or None)
    for d in findings:
        print(f"{d.message}  [{d.code}]")
    if findings:
        print(f"LINT-FAIL: {len(findings)} finding(s) — fix, or annotate "
              "a justified exception with `# lint: allow(<rule>)`")
        return 1
    print("lint ok: 0 findings")
    return 0


def cmd_xla(args) -> int:
    from paddle_tpu.analysis.diagnostics import Severity
    from paddle_tpu.analysis.xla import RULES, run_compiled_path_audit

    unknown = [r for r in (args.rule or []) if r not in RULES]
    if unknown:
        print(f"unknown rule(s) {unknown}; known: {sorted(RULES)}",
              file=sys.stderr)
        return 2
    try:
        # --rule restricts which rules RUN, so printed findings, the
        # summary and the exit status all agree (RETRACE diagnostics
        # from the sealed replay are always folded in)
        reports, diags = run_compiled_path_audit(
            rules=args.rule or None)
    except Exception as e:      # crash != findings: distinct exit code
        print(f"xla audit crashed: {e!r}")
        return 2
    errs = [d for d in diags if d.severity is Severity.ERROR]
    if errs or (args.strict and diags):
        strict_note = ""
        if args.strict and len(diags) > len(errs):
            strict_note = (f" + {len(diags) - len(errs)} non-ERROR "
                           "finding(s) failing under --strict")
        print(f"XLA-AUDIT: {len(errs)} ERROR finding(s){strict_note} "
              f"across {len(reports)} audited site(s) — fix the site, "
              "or declare the intent in its SiteContract")
        return 1
    print(f"xla audit ok: {len(reports)} site(s), 0 ERROR findings "
          f"({len(diags)} informational)")
    return 0


def cmd_sharding(args) -> int:
    # virtual devices FIRST: the ZeRO placement drive needs a real
    # multi-device data axis, and XLA_FLAGS only counts before the
    # first backend initialization
    from paddle_tpu.analysis.sharding import (RULE_NAMES,
                                              ensure_virtual_devices)

    unknown = [r for r in (args.rule or []) if r not in RULE_NAMES]
    if unknown:
        print(f"unknown rule(s) {unknown}; known: {sorted(RULE_NAMES)}",
              file=sys.stderr)
        return 2
    from paddle_tpu.platform.flags import FLAGS

    ensure_virtual_devices(int(FLAGS.shard_audit_virtual_devices))
    from paddle_tpu.analysis.diagnostics import Severity
    from paddle_tpu.analysis.sharding import run_sharding_audit

    try:
        reports, diags = run_sharding_audit(rules=args.rule or None)
    except Exception as e:      # crash != findings: distinct exit code
        print(f"sharding audit crashed: {e!r}")
        return 2
    errs = [d for d in diags if d.severity is Severity.ERROR]
    if errs or (args.strict and diags):
        strict_note = ""
        if args.strict and len(diags) > len(errs):
            strict_note = (f" + {len(diags) - len(errs)} non-ERROR "
                           "finding(s) failing under --strict")
        print(f"SHARD-AUDIT: {len(errs)} ERROR finding(s){strict_note} "
              f"across {len(reports)} audited site(s) — fix the plan, "
              "or declare the intent in the site's SiteContract")
        return 1
    print(f"sharding audit ok: {len(reports)} site(s), 0 ERROR findings "
          f"({len(diags)} informational)")
    return 0


def cmd_concurrency(args) -> int:
    from paddle_tpu.analysis.concurrency import (RULE_NAMES,
                                                 run_concurrency_audit)
    from paddle_tpu.analysis.diagnostics import Severity

    unknown = [r for r in (args.rule or []) if r not in RULE_NAMES]
    if unknown:
        print(f"unknown rule(s) {unknown}; known: {sorted(RULE_NAMES)}",
              file=sys.stderr)
        return 2
    try:
        diags = run_concurrency_audit(rules=args.rule or None)
    except Exception as e:      # crash != findings: distinct exit code
        print(f"concurrency audit crashed: {e!r}")
        return 2
    for d in diags:
        print(d)
    errs = [d for d in diags if d.severity is Severity.ERROR]
    if errs or (args.strict and diags):
        strict_note = ""
        if args.strict and len(diags) > len(errs):
            strict_note = (f" + {len(diags) - len(errs)} non-ERROR "
                           "finding(s) failing under --strict")
        print(f"CONC-AUDIT: {len(errs)} ERROR finding(s){strict_note} — "
              "fix the access/transition/order, or annotate the "
              "justified exception")
        return 1
    print(f"concurrency audit ok: 0 ERROR findings "
          f"({len(diags)} informational)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="static program verifier + repo-invariant linter")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("program",
                       help="verify fluid Programs built by a script")
    p.add_argument("script", help="python file that builds the program(s)")
    p.add_argument("--fetch", action="append", default=[],
                   help="fetch target name (enables dangling-fetch and "
                        "dead-var checks); repeatable.  Binds to the "
                        "default program (or the script's single "
                        "Program) — other module-level Programs get the "
                        "structural checks only")
    p.add_argument("--feed", action="append", default=[],
                   help="feed name the run will provide; repeatable "
                        "(same scoping as --fetch)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on ANY diagnostic, not just ERRORs")
    p.set_defaults(fn=cmd_program)

    p = sub.add_parser("lint", help="run the repo-invariant linter")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: paddle_tpu/)")
    p.add_argument("--rule", action="append", default=[],
                   help="restrict to the named rule(s); repeatable")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "xla", help="audit the compiled jaxprs of every audit_jit site "
                    "over a sealed serving steady state + one train step")
    p.add_argument("--rule", action="append", default=[],
                   help="restrict the audit to the named rule(s); "
                        "repeatable (RETRACE diagnostics from the "
                        "sealed replay are always included)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on ANY diagnostic, not just ERRORs")
    p.set_defaults(fn=cmd_xla)

    p = sub.add_parser(
        "sharding", help="static GSPMD sharding-propagation audit over "
                         "every audit_jit site's declared PartitionSpec "
                         "contract, with collective-cost budgets")
    p.add_argument("--rule", action="append", default=[],
                   help="restrict the audit to the named rule(s); "
                        "repeatable (RETRACE diagnostics from the "
                        "sealed replay are always included)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on ANY diagnostic, not just ERRORs")
    p.set_defaults(fn=cmd_sharding)

    p = sub.add_parser(
        "concurrency",
        help="lock-discipline checker + lifecycle state machines + "
             "schedule-permutation model checker over the seeded chaos "
             "drives")
    p.add_argument("--rule", action="append", default=[],
                   help="restrict the audit to the named rule(s); "
                        "repeatable")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on ANY diagnostic, not just ERRORs")
    p.set_defaults(fn=cmd_concurrency)

    args = parser.parse_args(argv)
    return args.fn(args)
