"""Static program verifier: abstract interpretation over fluid Programs.

Reference analog: the Gen-2 Fluid design validates a ``ProgramDesc``
before execution (InferShape / InferVarType passes over each OpDesc);
our rebuild traced programs straight into XLA with no static checking,
so a shape mismatch or def-before-use bug only surfaced as a runtime
failure deep inside a jit trace.  This pass walks ``Program`` /
``Block`` / ``Operator`` with a per-op-type shape+dtype inference
registry and reports structured :class:`Diagnostic`\\ s:

- ``undefined-var``   — an op reads a name no block in scope declares;
- ``def-before-use``  — an op reads a name whose only writers come later
  in the same block (a misordered graph);
- ``dangling-fetch``  — a fetch target nothing produces or stores;
- ``unknown-feed``    — a feed name no block declares (a typo that
  today would be *silently ignored*);
- ``dead-var``        — an op none of whose outputs reach a fetch,
  a persistable store, or a stateful slot (only checked when the fetch
  list is known — severity WARNING, the prune() candidate set);
- ``duplicate-writer``— two ops write one name (gradient fan-in
  ``@GRAD`` accumulation, declared stateful outputs, and in-place
  updates through an op's own input are the three sanctioned aliases);
- ``shape-mismatch`` / ``dtype-mismatch`` — per-op inference rules
  prove the op cannot execute (matmul inner dims, conv channels,
  non-broadcastable elementwise, integer labels expected, ...).

Shapes are abstract: ``None`` marks an unknown dim (``-1`` batch dims
normalize to it) and a var may be wholly unknown — declared shapes of
intermediate temporaries are builder hints, often empty, so inference
trusts only leaf declarations (feeds, parameters, persistables) and
per-op rules.  Ops without a registered rule produce unknown outputs;
the verifier NEVER guesses, so a clean report means "provably
well-formed where the registry has a rule", not "no rule fired".

Entry points: :func:`verify_program` (used inline by ``Executor.run``
behind ``FLAGS.fluid_verify`` and by the CLI) and
:func:`verify_topology` for the layer-DSL graphs in
``paddle_tpu.models``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

# abstract shape: tuple of int-or-None, or None for "wholly unknown"
AbsShape = Optional[Tuple[Optional[int], ...]]


class VarState:
    """Abstract value: best-known shape and dtype ('' = unknown)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape: AbsShape = None, dtype: str = ""):
        self.shape = shape
        self.dtype = dtype

    def __repr__(self):
        s = "?" if self.shape is None else \
            "[" + ",".join("?" if d is None else str(d)
                           for d in self.shape) + "]"
        return f"{s}:{self.dtype or '?'}"


def _declared_state(var) -> VarState:
    """Leaf state from a declared Variable: -1 / 0 dims become unknown;
    an empty declared shape on a non-scalar builder temp is treated as
    wholly unknown (builders use ``_tmp()`` without shapes)."""
    shape = tuple(None if s <= 0 else int(s) for s in var.shape)
    return VarState(shape if shape else None, var.dtype)


def _known(shape: AbsShape) -> bool:
    return shape is not None and all(d is not None for d in shape)


def _is_float(dtype: str) -> bool:
    return dtype.startswith(("float", "bfloat"))


def _is_int(dtype: str) -> bool:
    return dtype.startswith(("int", "uint", "bool"))


# ---------------------------------------------------------------------------
# per-op-type shape+dtype inference registry
# ---------------------------------------------------------------------------
# rule(ins: {slot: [VarState]}, attrs, emit) -> {slot: [VarState]}
# ``emit(severity, code, message, *vars)`` reports a conflict; the rule
# still returns its best-effort outputs so inference continues.

_RULES: Dict[str, Callable] = {}


def rule(*op_types):
    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn
    return deco


def _one(ins, slot) -> VarState:
    vs = ins.get(slot) or [VarState()]
    return vs[0]


def _bcast_shapes(x: AbsShape, y: AbsShape, axis: int) -> AbsShape:
    """Reference elementwise broadcast (ops._bcast): y matches a
    contiguous slice of x's dims starting at ``axis``.  Returns the
    result shape, or raises ValueError when provably incompatible."""
    if x is None or y is None:
        return x or y
    if len(x) == len(y):
        out = []
        for a, b in zip(x, y):
            if a is not None and b is not None and a != b and 1 not in (a, b):
                raise ValueError(f"dims {a} vs {b}")
            # a known dim-1 broadcasts away; an UNKNOWN dim against 1
            # must stay unknown (guessing 1 would fabricate downstream
            # element-count conflicts on valid programs)
            if a == 1:
                out.append(b)
            elif b == 1:
                out.append(a)
            else:
                out.append(a if a is not None else b)
        return tuple(out)
    big, small = (x, y) if len(x) > len(y) else (y, x)
    off = axis if (axis != -1 and len(x) > len(y)) else len(big) - len(small)
    for i, d in enumerate(small):
        j = off + i
        if j >= len(big):
            raise ValueError("rank overflow under axis broadcast")
        b = big[j]
        if d is not None and b is not None and d != b and 1 not in (d, b):
            raise ValueError(f"dim {d} vs {b} at axis {j}")
    return big


@rule("elementwise_add", "elementwise_sub", "elementwise_mul",
      "elementwise_div", "elementwise_pow", "elementwise_max",
      "elementwise_min", "minus")
def _r_elementwise(ins, attrs, emit):
    x, y = _one(ins, "X"), _one(ins, "Y")
    out_shape: AbsShape = None
    try:
        out_shape = _bcast_shapes(x.shape, y.shape,
                                  int(attrs.get("axis", -1)))
    except ValueError as e:
        emit(Severity.ERROR, "shape-mismatch",
             f"elementwise operands do not broadcast: "
             f"{x!r} vs {y!r} ({e})")
    if x.dtype and y.dtype and _is_float(x.dtype) != _is_float(y.dtype):
        emit(Severity.ERROR, "dtype-mismatch",
             f"elementwise mixes float and integer operands "
             f"({x.dtype} vs {y.dtype}); insert a cast op")
    return {"Out": [VarState(out_shape, x.dtype or y.dtype)]}


@rule("sigmoid", "logsigmoid", "exp", "relu", "tanh", "sqrt", "abs",
      "reciprocal", "log", "square", "softsign", "brelu", "soft_relu",
      "pow", "stanh", "leaky_relu", "relu6", "softplus", "hard_shrink",
      "soft_shrink", "elu", "sign", "floor", "ceil", "round", "scale",
      "clip", "softmax", "dropout", "increment", "fill_zeros_like",
      "sequence_softmax")
def _r_same_shape(ins, attrs, emit):
    x = _one(ins, "X")
    out = {"Out": [VarState(x.shape, x.dtype)]}
    out["Mask"] = [VarState(x.shape, x.dtype)]   # dropout's co-output
    return out


@rule("cast")
def _r_cast(ins, attrs, emit):
    x = _one(ins, "X")
    return {"Out": [VarState(x.shape, str(attrs.get("out_dtype", "")))]}


@rule("mul")
def _r_mul(ins, attrs, emit):
    import numpy as np

    x, y = _one(ins, "X"), _one(ins, "Y")
    xn, yn = int(attrs.get("x_num_col_dims", 1)), \
        int(attrs.get("y_num_col_dims", 1))
    out_shape: AbsShape = None
    if x.shape is not None and y.shape is not None:
        xk, yk = x.shape[xn:], y.shape[:yn]
        if _known(xk) and _known(yk) and \
                int(np.prod(xk)) != int(np.prod(yk)):
            emit(Severity.ERROR, "shape-mismatch",
                 f"mul inner dims differ: X{list(x.shape)} flattened at "
                 f"{xn} gives {int(np.prod(xk))} cols, Y{list(y.shape)} "
                 f"flattened at {yn} gives {int(np.prod(yk))} rows")
        out_shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    if x.dtype and y.dtype and x.dtype != y.dtype:
        emit(Severity.WARNING, "dtype-mismatch",
             f"mul operand dtypes differ ({x.dtype} vs {y.dtype})")
    return {"Out": [VarState(out_shape, x.dtype or y.dtype)]}


@rule("matmul")
def _r_matmul(ins, attrs, emit):
    x, y = _one(ins, "X"), _one(ins, "Y")
    xs, ys = x.shape, y.shape
    if xs is not None and attrs.get("transpose_X", False):
        xs = xs[:-2] + (xs[-1], xs[-2]) if len(xs) >= 2 else xs
    if ys is not None and attrs.get("transpose_Y", False):
        ys = ys[:-2] + (ys[-1], ys[-2]) if len(ys) >= 2 else ys
    out_shape: AbsShape = None
    if xs is not None and ys is not None and len(xs) >= 2 and len(ys) >= 2:
        k1, k2 = xs[-1], ys[-2]
        if k1 is not None and k2 is not None and k1 != k2:
            emit(Severity.ERROR, "shape-mismatch",
                 f"matmul contraction dims differ: {k1} vs {k2} "
                 f"(X{list(xs)} @ Y{list(ys)})")
        out_shape = tuple(xs[:-1]) + (ys[-1],)
    return {"Out": [VarState(out_shape, x.dtype or y.dtype)]}


def _conv_out(hw, k, stride, pad, dil=1):
    if hw is None:
        return None
    return (hw + 2 * pad - dil * (k - 1) - 1) // stride + 1


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))


@rule("conv2d")
def _r_conv2d(ins, attrs, emit):
    x, w = _one(ins, "Input"), _one(ins, "Filter")
    groups = int(attrs.get("groups", 1))
    out_shape: AbsShape = None
    if x.shape is not None and w.shape is not None and \
            len(x.shape) == 4 and len(w.shape) == 4:
        cin, wcin = x.shape[1], w.shape[1]
        if cin is not None and wcin is not None and cin != wcin * groups:
            emit(Severity.ERROR, "shape-mismatch",
                 f"conv2d channel mismatch: input has {cin} channels, "
                 f"filter expects {wcin} x groups={groups}")
        s, p = _pair(attrs.get("strides", 1)), _pair(attrs.get("paddings", 0))
        d = _pair(attrs.get("dilations", 1))
        out_shape = (x.shape[0], w.shape[0],
                     _conv_out(x.shape[2], w.shape[2] or 1, s[0], p[0], d[0])
                     if w.shape[2] is not None else None,
                     _conv_out(x.shape[3], w.shape[3] or 1, s[1], p[1], d[1])
                     if w.shape[3] is not None else None)
    return {"Output": [VarState(out_shape, x.dtype)]}


@rule("pool2d")
def _r_pool2d(ins, attrs, emit):
    x = _one(ins, "X")
    out_shape: AbsShape = None
    if x.shape is not None and len(x.shape) == 4:
        if attrs.get("global_pooling", False):
            out_shape = (x.shape[0], x.shape[1], 1, 1)
        else:
            k = _pair(attrs.get("ksize", 2))
            s = _pair(attrs.get("strides", 1) or k)
            p = _pair(attrs.get("paddings", 0))
            out_shape = (x.shape[0], x.shape[1],
                         _conv_out(x.shape[2], k[0], s[0], p[0]),
                         _conv_out(x.shape[3], k[1], s[1], p[1]))
    return {"Out": [VarState(out_shape, x.dtype)]}


@rule("batch_norm")
def _r_batch_norm(ins, attrs, emit):
    x, scale = _one(ins, "X"), _one(ins, "Scale")
    layout = attrs.get("data_layout", "NCHW")
    if x.shape is not None and scale.shape is not None and len(x.shape) >= 2:
        c = x.shape[1 if layout == "NCHW" else -1]
        sc = scale.shape[0] if len(scale.shape) == 1 else None
        if c is not None and sc is not None and c != sc:
            emit(Severity.ERROR, "shape-mismatch",
                 f"batch_norm channel mismatch: input has {c} channels "
                 f"({layout}), Scale has {sc}")
    stat = VarState(scale.shape, x.dtype)
    return {"Y": [VarState(x.shape, x.dtype)], "MeanOut": [stat],
            "VarianceOut": [stat], "SavedMean": [stat],
            "SavedVariance": [stat]}


@rule("layer_norm", "lrn")
def _r_norm_same(ins, attrs, emit):
    x = _one(ins, "X")
    return {"Y": [VarState(x.shape, x.dtype)],
            "Out": [VarState(x.shape, x.dtype)]}


def _label_check(label: VarState, soft: bool, emit, op: str):
    if not soft and label.dtype and not _is_int(label.dtype):
        emit(Severity.ERROR, "dtype-mismatch",
             f"{op} with soft_label=False needs integer labels, got "
             f"{label.dtype}")


@rule("cross_entropy")
def _r_cross_entropy(ins, attrs, emit):
    x, label = _one(ins, "X"), _one(ins, "Label")
    _label_check(label, attrs.get("soft_label", False), emit,
                 "cross_entropy")
    n = x.shape[0] if x.shape else None
    return {"Y": [VarState((n, 1), x.dtype)]}


@rule("softmax_with_cross_entropy")
def _r_softmax_xent(ins, attrs, emit):
    logits, label = _one(ins, "Logits"), _one(ins, "Label")
    _label_check(label, attrs.get("soft_label", False), emit,
                 "softmax_with_cross_entropy")
    n = logits.shape[0] if logits.shape else None
    return {"Softmax": [VarState(logits.shape, logits.dtype)],
            "Loss": [VarState((n, 1), logits.dtype)]}


@rule("squared_l2_distance")
def _r_sq_l2(ins, attrs, emit):
    x, y = _one(ins, "X"), _one(ins, "Y")
    try:
        _bcast_shapes(x.shape, y.shape, -1)
    except ValueError:
        emit(Severity.ERROR, "shape-mismatch",
             f"squared_l2_distance operands differ: {x!r} vs {y!r}")
    n = x.shape[0] if x.shape else None
    return {"sub_result": [VarState(x.shape, x.dtype)],
            "Out": [VarState((n, 1), x.dtype)]}


@rule("mean", "squared_l2_norm")
def _r_scalarize(ins, attrs, emit):
    x = _one(ins, "X")
    return {"Out": [VarState((), x.dtype)]}


@rule("sum")
def _r_sum(ins, attrs, emit):
    xs = ins.get("X") or [VarState()]
    shape = None
    for v in xs:
        if v.shape is None:
            continue
        if shape is None:
            shape = v.shape
        elif _known(shape) and _known(v.shape) and shape != v.shape:
            emit(Severity.ERROR, "shape-mismatch",
                 f"sum inputs disagree: {list(shape)} vs {list(v.shape)}")
    return {"Out": [VarState(shape, xs[0].dtype)]}


@rule("reduce_sum", "reduce_mean", "reduce_max", "reduce_min")
def _r_reduce(ins, attrs, emit):
    x = _one(ins, "X")
    dim = attrs.get("dim")
    if attrs.get("reduce_all", dim is None) or x.shape is None:
        return {"Out": [VarState((), x.dtype)]}
    d = int(dim) % len(x.shape) if x.shape else 0
    if attrs.get("keep_dim", False):
        shape = tuple(1 if i == d else s for i, s in enumerate(x.shape))
    else:
        shape = tuple(s for i, s in enumerate(x.shape) if i != d)
    return {"Out": [VarState(shape, x.dtype)]}


@rule("reshape")
def _r_reshape(ins, attrs, emit):
    import numpy as np

    x = _one(ins, "X")
    target = tuple(int(s) for s in attrs.get("shape", ()))
    if _known(x.shape) and target and all(s > 0 for s in target):
        if int(np.prod(x.shape)) != int(np.prod(target)):
            emit(Severity.ERROR, "shape-mismatch",
                 f"reshape changes element count: {list(x.shape)} "
                 f"({int(np.prod(x.shape))}) -> {list(target)} "
                 f"({int(np.prod(target))})")
    shape = tuple(None if s < 0 else s for s in target) if target else None
    return {"Out": [VarState(shape, x.dtype)]}


@rule("transpose")
def _r_transpose(ins, attrs, emit):
    x = _one(ins, "X")
    perm = [int(p) for p in attrs.get("axis", ())]
    shape: AbsShape = None
    if x.shape is not None and perm:
        if sorted(perm) != list(range(len(x.shape))):
            emit(Severity.ERROR, "shape-mismatch",
                 f"transpose perm {perm} does not match rank "
                 f"{len(x.shape)} input")
        else:
            shape = tuple(x.shape[p] for p in perm)
    return {"Out": [VarState(shape, x.dtype)]}


@rule("concat")
def _r_concat(ins, attrs, emit):
    xs = ins.get("X") or [VarState()]
    axis = int(attrs.get("axis", 0))
    shapes = [v.shape for v in xs]
    if any(s is None for s in shapes):
        return {"Out": [VarState(None, xs[0].dtype)]}
    rank = len(shapes[0])
    ax = axis % rank if rank else 0
    for s in shapes[1:]:
        if len(s) != rank:
            emit(Severity.ERROR, "shape-mismatch",
                 f"concat rank mismatch: {list(shapes[0])} vs {list(s)}")
            return {"Out": [VarState(None, xs[0].dtype)]}
        for i in range(rank):
            if i != ax and s[i] is not None and shapes[0][i] is not None \
                    and s[i] != shapes[0][i]:
                emit(Severity.ERROR, "shape-mismatch",
                     f"concat non-axis dim {i} differs: "
                     f"{list(shapes[0])} vs {list(s)} (axis {ax})")
    cat = 0
    for s in shapes:
        if s[ax] is None:
            cat = None
            break
        cat += s[ax]
    shape = tuple(cat if i == ax else shapes[0][i] for i in range(rank))
    return {"Out": [VarState(shape, xs[0].dtype)]}


@rule("lookup_table")
def _r_lookup(ins, attrs, emit):
    w, ids = _one(ins, "W"), _one(ins, "Ids")
    if ids.dtype and not _is_int(ids.dtype):
        emit(Severity.ERROR, "dtype-mismatch",
             f"lookup_table Ids must be integers, got {ids.dtype}")
    dim = w.shape[1] if w.shape is not None and len(w.shape) == 2 else None
    return {"Out": [VarState((None, dim), w.dtype)]}


@rule("fill_constant")
def _r_fill(ins, attrs, emit):
    shape = tuple(int(s) for s in attrs.get("shape", ()))
    return {"Out": [VarState(shape, str(attrs.get("dtype", "float32")))]}


@rule("uniform_random", "gaussian_random")
def _r_random(ins, attrs, emit):
    shape = tuple(int(s) for s in attrs.get("shape", ()))
    return {"Out": [VarState(shape, str(attrs.get("dtype", "float32")))]}


@rule("top_k")
def _r_top_k(ins, attrs, emit):
    x = _one(ins, "X")
    k = int(attrs.get("k", 1))
    shape = None
    if x.shape is not None:
        shape = tuple(x.shape[:-1]) + (k,)
        if x.shape[-1] is not None and x.shape[-1] < k:
            emit(Severity.ERROR, "shape-mismatch",
                 f"top_k k={k} exceeds last dim {x.shape[-1]}")
    return {"Out": [VarState(shape, x.dtype)],
            "Indices": [VarState(shape, "int32")]}


@rule("argmax")
def _r_argmax(ins, attrs, emit):
    x = _one(ins, "X")
    return {"Out": [VarState(None, "int32")]}


@rule("accuracy")
def _r_accuracy(ins, attrs, emit):
    label = _one(ins, "Label")
    if label.dtype and not _is_int(label.dtype):
        emit(Severity.ERROR, "dtype-mismatch",
             f"accuracy Label must be integers, got {label.dtype}")
    return {"Accuracy": [VarState((), "float32")],
            "Correct": [VarState((), "int32")],
            "Total": [VarState((), "int32")]}


@rule("sgd", "momentum", "adagrad", "adadelta", "rmsprop",
      "decayed_adagrad", "adam", "adamax", "proximal_gd")
def _r_optimizer(ins, attrs, emit):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    if _known(p.shape) and _known(g.shape) and p.shape != g.shape:
        emit(Severity.ERROR, "shape-mismatch",
             f"optimizer grad shape {list(g.shape)} does not match "
             f"param {list(p.shape)}")
    st = VarState(p.shape, p.dtype)
    return {slot: [st] for slot in
            ("ParamOut", "VelocityOut", "MomentOut", "Moment1Out",
             "Moment2Out", "MeanSquareOut", "AvgSquaredGradOut",
             "AvgSquaredUpdateOut", "InfNormOut")}


# ---------------------------------------------------------------------------
# the verifier walk
# ---------------------------------------------------------------------------


def _op_rule_outputs(op, in_states, emit) -> Dict[str, List[VarState]]:
    fn = _RULES.get(op.type)
    if fn is None:
        return {}
    return fn(in_states, dict(op.attrs), emit)


class _BlockChecker:
    """Def/use + inference walk over one block (sub-blocks get their
    step-local names pre-seeded by the caller)."""

    def __init__(self, program, block, diags: List[Diagnostic],
                 outer_defined: Optional[Dict[str, VarState]] = None):
        self.program = program
        self.block = block
        self.diags = diags
        # name -> abstract state, for everything defined "so far"
        self.defined: Dict[str, VarState] = dict(outer_defined or {})
        self.written_by: Dict[str, List[int]] = {}
        self.first_writer: Dict[str, int] = {}

    # -- scope helpers -----------------------------------------------------

    def _declared(self, name: str):
        b = self.block
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = (self.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        return None

    def _initially_defined(self, var) -> bool:
        """Matches Executor._materialize_params: parameters, persistables
        with an initializer or static shape, pre-start."""
        from paddle_tpu.fluid.framework import Parameter

        if isinstance(var, Parameter):
            return True
        if var.persistable and (var.initializer is not None or
                                (var.shape and all(s > 0 for s in var.shape))):
            return True
        return False

    # -- the walk ----------------------------------------------------------

    def run(self, feed_names: Sequence[str] = ()) -> None:
        ops = self.block.ops
        for idx, op in enumerate(ops):
            for n in op.output_names():
                self.written_by.setdefault(n, []).append(idx)
                self.first_writer.setdefault(n, idx)
        # leaves: anything declared that is pre-defined or fed, plus
        # names that are read but never written (presumed feeds — the
        # executor cannot tell either until run time)
        for name, var in self._all_scope_vars().items():
            if self._initially_defined(var) or name in feed_names or \
                    name not in self.first_writer:
                self.defined.setdefault(name, _declared_state(var))

        for idx, op in enumerate(ops):
            self._check_op(idx, op)

    def _all_scope_vars(self):
        out = {}
        b = self.block
        chain = []
        while b is not None:
            chain.append(b)
            b = (self.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        for b in reversed(chain):   # inner shadows outer
            out.update(b.vars)
        return out

    def _emit_for(self, idx, op):
        def emit(severity, code, message, *vars):
            self.diags.append(Diagnostic(
                severity, code, f"op {op.type!r}: {message}",
                block_idx=self.block.idx, op_idx=idx, vars=tuple(vars)))
        return emit

    def _check_op(self, idx, op) -> None:
        emit = self._emit_for(idx, op)
        is_grad = op.type.endswith("_grad")
        in_states: Dict[str, List[VarState]] = {}
        for slot, names in op.inputs.items():
            states = []
            for n in names:
                states.append(self._check_read(idx, op, slot, n, emit,
                                               optional=is_grad))
            in_states[slot] = states
        out_states = {} if is_grad else _op_rule_outputs(op, in_states, emit)
        for slot, names in op.outputs.items():
            inferred = out_states.get(slot, [])
            for j, n in enumerate(names):
                self._check_write(idx, op, slot, n, emit)
                st = inferred[j] if j < len(inferred) else VarState()
                if st.shape is None and st.dtype == "":
                    # no rule: keep at least the declared dtype
                    var = self._declared(n)
                    if var is not None:
                        st = VarState(None, var.dtype)
                self.defined[n] = st
        # sub-block ops: walk the sub-block with its step-locals seeded
        if "sub_block" in op.attrs:
            self._check_sub_block(op)

    def _check_read(self, idx, op, slot, name, emit,
                    optional: bool) -> VarState:
        if name in self.defined:
            return self.defined[name]
        var = self._declared(name)
        if var is None:
            if not optional:
                emit(Severity.ERROR, "undefined-var",
                     f"reads {name!r} (slot {slot}), which no block in "
                     "scope declares", name)
            return VarState()
        if optional:
            # grad-op OutGrad inputs default to zeros when absent — a
            # declared-but-unwritten grad var is the normal case
            return self.defined.get(name, _declared_state(var))
        writer = self.first_writer.get(name)
        if writer is not None and writer > idx:
            emit(Severity.ERROR, "def-before-use",
                 f"reads {name!r} (slot {slot}) but its first writer is "
                 f"op {writer} ({self.block.ops[writer].type!r}) — the "
                 "graph is misordered", name)
        elif writer is None:
            # declared, never written, not pre-defined: unreachable in
            # practice because run() pre-seeds never-written names
            emit(Severity.ERROR, "def-before-use",
                 f"reads {name!r} which nothing defines", name)
        return _declared_state(var)

    def _check_write(self, idx, op, slot, name, emit) -> None:
        from paddle_tpu.fluid import ops as op_lib
        from paddle_tpu.fluid.framework import GRAD_SUFFIX

        writers = self.written_by.get(name, [])
        if len(writers) <= 1 or writers[0] == idx:
            return
        # sanctioned multi-writer aliases:
        if name.endswith(GRAD_SUFFIX):
            return                      # gradient fan-in accumulation
        base = op.type[:-5] if op.type.endswith("_grad") else op.type
        try:
            info = op_lib.get(base)
        except Exception:
            info = None
        if info is not None and slot in info.stateful_outputs:
            return                      # declared stateful slot (bn stats)
        if name in op.input_names():
            return                      # in-place update through own input
        emit(Severity.ERROR, "duplicate-writer",
             f"writes {name!r} (slot {slot}) already written by op(s) "
             f"{[w for w in writers if w != idx]}", name)

    def _check_sub_block(self, op) -> None:
        sub = self.program.blocks[int(op.attrs["sub_block"])]
        seeded: Dict[str, VarState] = {}
        for key in ("step_inputs", "step_states_in", "param_names",
                    "x_names"):
            for n in op.attrs.get(key, []):
                var = sub.vars.get(n) or self._declared(n)
                seeded[n] = (_declared_state(var) if var is not None
                             else VarState())
        inner = _BlockChecker(self.program, sub, self.diags,
                              outer_defined={**self.defined, **seeded})
        inner.run()


def feed_fetch_problems(program, feed_names: Sequence[str],
                        fetch_names: Sequence[str]) -> List[Tuple[str, str]]:
    """THE definition of a valid feed/fetch set, shared by
    ``verify_program`` and ``Executor.run``'s up-front validation (one
    helper so the two can never drift): a feed must name a declared
    variable in some block; a fetch must be produced by an op, stored in
    a persistable variable, or fed.  Returns [(code, message)]."""
    declared: Set[str] = set()
    for b in program.blocks:
        declared.update(b.vars)
    gb = program.global_block()
    written = {n for op in gb.ops for n in op.output_names()}
    persistable = {n for n, v in gb.vars.items() if v.persistable}
    problems: List[Tuple[str, str]] = []
    for n in feed_names:
        if n not in declared:
            problems.append((
                "unknown-feed",
                f"feed {n!r} matches no program variable (it would be "
                "silently ignored)"))
    for n in fetch_names:
        if n not in written and n not in persistable and \
                n not in feed_names:
            problems.append((
                "dangling-fetch",
                f"fetch {n!r} is produced by no op and stored in no "
                "persistable variable"))
    return problems


def verify_program(program, fetch_names: Optional[Sequence[str]] = None,
                   feed_names: Optional[Sequence[str]] = None
                   ) -> List[Diagnostic]:
    """Verify a ``fluid.Program``; returns all diagnostics (possibly
    empty).  ``fetch_names``/``feed_names`` enable the fetch/feed and
    dead-variable checks; without a fetch list dead-var analysis is
    skipped (the verifier cannot know the program's sinks)."""
    diags: List[Diagnostic] = []
    gb = program.global_block()
    checker = _BlockChecker(program, gb, diags)
    checker.run(feed_names=tuple(feed_names or ()))

    for code, msg in feed_fetch_problems(program, tuple(feed_names or ()),
                                         tuple(fetch_names or ())):
        diags.append(Diagnostic(Severity.ERROR, code, msg, block_idx=0))

    if fetch_names is not None:
        _dead_var_scan(program, set(fetch_names), diags)
    return diags


def _dead_var_scan(program, fetches: Set[str],
                   diags: List[Diagnostic]) -> None:
    """Ops none of whose outputs reach a fetch / persistable store /
    stateful slot: prune() candidates, reported as WARNINGs (mirrors
    framework.prune's reverse reachability walk)."""
    from paddle_tpu.fluid import ops as op_lib
    from paddle_tpu.fluid.framework import GRAD_SUFFIX

    gb = program.global_block()
    needed = set(fetches)
    for n, v in gb.vars.items():
        if v.persistable:
            needed.add(n)
    kept: Set[int] = set()
    for idx in range(len(gb.ops) - 1, -1, -1):
        op = gb.ops[idx]
        sink = any(n in needed for n in op.output_names())
        if not sink:
            base = op.type[:-5] if op.type.endswith("_grad") else op.type
            try:
                info = op_lib.get(base)
            except Exception:
                info = None
            if info is not None and info.stateful_outputs and \
                    any(slot in info.stateful_outputs
                        for slot in op.outputs):
                sink = True
        if sink:
            kept.add(idx)
            needed.update(op.input_names())
            # a kept grad op's outputs feed earlier grad ops' OutGrad
            # reads (accumulation is executor-side, not an explicit op),
            # and it replays its FORWARD op's recorded inputs via
            # jax.vjp — the forward op is live even if nothing else
            # reads its outputs
            if op.type.endswith("_grad"):
                needed.update(op.output_names())
                if "fwd_idx" in op.attrs:
                    fwd = gb.ops[int(op.attrs["fwd_idx"])]
                    needed.update(fwd.output_names())
    for idx, op in enumerate(gb.ops):
        if idx in kept:
            continue
        outs = op.output_names()
        diags.append(Diagnostic(
            Severity.WARNING, "dead-var",
            f"op {op.type!r} is dead: none of its outputs "
            f"{outs} reach a fetch target or persistable store",
            block_idx=0, op_idx=idx, vars=tuple(outs)))


# ---------------------------------------------------------------------------
# layer-DSL (Topology) verification — the paddle_tpu.models surface
# ---------------------------------------------------------------------------


def verify_topology(outputs) -> List[Diagnostic]:
    """Verify a layer-DSL graph (a ``Topology`` or the LayerOutput(s) to
    freeze into one): well-formed DAG (no cycles, no duplicate names),
    every non-data placeholder reachable, parameter/state specs with
    static positive shapes, shared-parameter shape agreement.  These are
    the same diagnostic classes as the fluid pass, mapped onto the graph
    the ``paddle_tpu.models`` zoo actually builds."""
    from paddle_tpu.platform.enforce import EnforceError
    from paddle_tpu.topology import LayerOutput, Topology

    diags: List[Diagnostic] = []
    try:
        topo = outputs if isinstance(outputs, Topology) else \
            Topology(outputs if isinstance(outputs, (list, tuple))
                     else [outputs])
    except EnforceError as e:
        # cycles and duplicate names raise at freeze; map them onto the
        # matching diagnostic classes
        msg = str(e)
        code = "duplicate-writer" if "named" in msg else "def-before-use"
        diags.append(Diagnostic(Severity.ERROR, code, msg))
        return diags

    for node in topo.nodes:
        if node.fn is None and node.layer_type != "data":
            diags.append(Diagnostic(
                Severity.WARNING, "def-before-use",
                f"node {node.name!r} ({node.layer_type}) is a "
                "placeholder with no compute fn outside a step graph — "
                "forward will demand a feed for it", vars=(node.name,)))
        for pname, spec in node.params.items():
            if not all(int(s) > 0 for s in spec.shape):
                diags.append(Diagnostic(
                    Severity.ERROR, "shape-mismatch",
                    f"parameter {node.name}.{pname} needs a static "
                    f"positive shape, got {tuple(spec.shape)}",
                    vars=(f"{node.name}.{pname}",)))
        for sname, spec in node.state.items():
            if not all(int(s) >= 0 for s in spec.shape):
                diags.append(Diagnostic(
                    Severity.ERROR, "shape-mismatch",
                    f"state slot {node.name}/{sname} has negative dims "
                    f"{tuple(spec.shape)}", vars=(f"{node.name}/{sname}",)))
    try:
        topo.param_specs()       # shared-parameter shape agreement
        topo.state_specs()       # shared-state shape agreement
    except EnforceError as e:
        diags.append(Diagnostic(Severity.ERROR, "shape-mismatch", str(e)))
    for out in topo.outputs:
        if out.name not in topo.by_name:
            diags.append(Diagnostic(
                Severity.ERROR, "dangling-fetch",
                f"requested output {out.name!r} is not in the frozen "
                "graph", vars=(out.name,)))
    return diags
