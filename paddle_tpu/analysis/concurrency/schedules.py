"""SCHED-AUDIT: the schedule-permutation model checker.

The fleet's ``step()`` declares a permutable mid-tick section (lease
sweep / autoscale / WFQ drain / migration pump, then per-replica step
order) and CLAIMS those orderings are commutable with respect to every
terminal outcome: request statuses, exactly-once token streams, and the
conservation ledgers.  This module holds the runtime to that claim by
replaying small seeded chaos drives — replica kill + heartbeat
partition, migration drop + kill, tenant storm + autoscale, host-tier
spill + kill + warm restart — under systematically permuted intra-tick
schedules and comparing a canonical terminal fingerprint byte-for-byte.

Exploration is bounded DFS with a partial-order reduction: a canonical
run first records which ordering points are HOT (two or more phases
with actual work, or two or more replicas with work — permuting
anything else is the identity), then single-tick permutations of hot
points run first, then depth-2 combinations, up to
``FLAGS.conc_audit_max_schedules`` per drive.  Every divergence is
reproducible from its finding: the message names the minimal schedule
delta (tick, ordering-point kind, permutation), and the divergent
schedule is replayed once more under a real tracer so the flight
recorder lands an ``OBS-POSTMORTEM`` dump.

The fingerprint is deliberately the OUTCOME, not the trajectory:
per-frid (terminal status, emitted count, result tokens) plus the
duplicate-completion count.  Tick counts, migration apply-vs-fallback
tallies, and autoscale action counts legitimately vary with intra-tick
order; terminal results must not.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.platform.flags import FLAGS

__all__ = [
    "FleetDrive", "ToyOrderDrive", "default_drives", "explore_drive",
    "run_schedule_audit", "MIN_SCHEDULES_PER_DRIVE",
]

# the documented coverage bar: a clean audit must have explored at
# least this many distinct schedules per chaos drive (budget allowing)
MIN_SCHEDULES_PER_DRIVE = 50

# (kind, tick) -> permuted name order
_Delta = Dict[Tuple[str, int], Tuple]


# ---------------------------------------------------------------------------
# tiny shared model (one jit cache across every drive and replay)
# ---------------------------------------------------------------------------

_MODEL = None
_CACHE_ON = False


def _enable_compile_cache() -> None:
    """Point jax's persistent compilation cache at a scratch dir:
    every replay builds FRESH engines (fresh jit closures), so without
    it each of the explorer's ~50+ schedules per drive pays full XLA
    compiles (~3s); with it, replays pay tracing plus a disk hit
    (~0.5s).  Best-effort — an unwritable dir just means slow."""
    global _CACHE_ON
    if _CACHE_ON:
        return
    _CACHE_ON = True
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/paddle_tpu_conc_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def _model():
    global _MODEL
    if _MODEL is None:
        import jax

        from paddle_tpu.serving import DecoderLM

        model = DecoderLM(vocab_size=32, num_layers=1, num_heads=2,
                          head_dim=4, max_positions=64)
        _MODEL = (model, model.init_params(jax.random.PRNGKey(0)))
    return _MODEL


def _make_engine(time_fn, **kw):
    from paddle_tpu.serving import ServingEngine

    model, params = _model()
    base = dict(eos_id=1, page_size=4, num_pages=32, max_pages_per_seq=8,
                max_slots=2, buckets=(4, 8))
    base.update(kw)
    return ServingEngine(model, params, time_fn=time_fn, **base)


def _prompts(seed: int, n: int, shared: int = 0, lo: int = 4, hi: int = 7):
    import numpy as np

    rng = np.random.RandomState(seed)
    sysp = rng.randint(2, 32, size=shared).tolist() if shared else []
    return [sysp + rng.randint(2, 32, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


# ---------------------------------------------------------------------------
# drives
# ---------------------------------------------------------------------------


class FleetDrive:
    """One seeded chaos scenario with explorer hooks.

    ``build(tracer)`` returns a fresh :class:`FleetRouter`;
    ``arrivals(tick, fl)`` injects that tick's submissions/actions
    (called BEFORE the tick steps, outside the permutable section, so
    arrivals are schedule-invariant by construction).  Replays are full
    re-executions from a fresh router — the jit cache is the only state
    shared between schedules.
    """

    def __init__(self, name: str,
                 build: Callable[[Optional[object]], object],
                 arrivals: Callable[[int, object], None],
                 max_ticks: int = 300,
                 extra_checks: Optional[Callable[[object], None]] = None):
        self.name = name
        self._build = build
        self._arrivals = arrivals
        self.max_ticks = max_ticks
        self._extra_checks = extra_checks

    # -- execution ---------------------------------------------------------

    def _execute(self, hook=None, tracer=None):
        from paddle_tpu.platform.enforce import enforce_that

        fl = self._build(tracer)
        if hook is not None:
            fl.schedule_hook = lambda t, k, names: hook(fl, t, k, names)
        tick = 0
        while True:
            self._arrivals(tick, fl)
            if not fl.has_work and tick > 0:
                break
            fl.step()
            tick += 1
            enforce_that(tick < self.max_ticks,
                         f"SCHED-AUDIT drive {self.name} failed to drain "
                         f"within {self.max_ticks} ticks",
                         context="analysis")
        fl.check_fleet_conservation()
        if self._extra_checks is not None:
            self._extra_checks(fl)
        return fl

    def _fingerprint(self, fl) -> bytes:
        rows = []
        # enumerate in frid order but fingerprint the POSITION: fleet
        # rids come from a process-global counter, so the raw numbers
        # differ between replays while submission order is identical
        for pos, frid in enumerate(sorted(fl._requests)):
            freq = fl._requests[frid]
            rows.append((pos, str(freq.status), freq.emitted,
                         tuple(freq.result) if freq.result is not None
                         else None))
        return repr((rows, fl.metrics.duplicate_completions)).encode()

    # -- hotness (the partial-order reduction) -----------------------------

    @staticmethod
    def _hot(fl, kind: str, names: Sequence) -> bool:
        from paddle_tpu.serving import ReplicaState

        if kind == "phases":
            active = 0
            if any(r.state in (ReplicaState.JOINING, ReplicaState.DRAINING)
                   for r in fl.replicas):
                active += 1                         # lease sweep acts
            if fl.autoscaler is not None:
                active += 1                         # policy loop runs
            if fl.wfq is not None and len(fl.wfq):
                active += 1                         # WFQ has buffered work
            if any(fl._mig_queues.values()):
                active += 1                         # transfers pending
            return active >= 2
        # two or more live replicas and at least one with work: step
        # order then interleaves harvest/resubmit/retire against other
        # replicas' state (a lone live replica, or an all-idle tick,
        # makes every order the identity)
        live = [r for r in fl.replicas
                if r.state is not ReplicaState.DEAD]
        return len(live) >= 2 and any(r.engine.has_work for r in live)

    # -- explorer interface ------------------------------------------------

    def record(self):
        """Canonical run; returns (fingerprint, ordered hot sites)."""
        sites: List[Tuple[str, int, Tuple]] = []

        def hook(fl, tick, kind, names):
            if self._hot(fl, kind, names):
                sites.append((kind, tick, tuple(names)))
            return names

        fl = self._execute(hook)
        return self._fingerprint(fl), sites

    def replay(self, deltas: _Delta, tracer=None) -> bytes:
        def hook(fl, tick, kind, names):
            want = deltas.get((kind, tick))
            if want is not None and list(want) != list(names) and \
                    sorted(map(repr, want)) == sorted(map(repr, names)):
                return list(want)
            return names

        return self._fingerprint(self._execute(hook, tracer=tracer))

    def postmortem(self, deltas: _Delta, reason: str) -> None:
        """Replay the divergent schedule under a real tracer and dump
        the flight recorder (prints the OBS-POSTMORTEM line)."""
        from paddle_tpu.obs.trace import Tracer

        tracer = Tracer()
        try:
            self.replay(deltas, tracer=tracer)
        except Exception:
            pass                       # the dump is the point
        tracer.dump_postmortem(reason)


class ToyOrderDrive:
    """Deliberately order-SENSITIVE drive for the auditor's own tests:
    two phases, increment and double, whose composition does not
    commute.  The explorer must catch it on the first permuted
    schedule and name the minimal delta."""

    name = "toy_order_sensitive"

    def __init__(self, ticks: int = 3, commuting: bool = False):
        self.ticks = ticks
        # commuting=True turns both phases into increments — the clean
        # twin, for pinning the no-findings path without a fleet
        self.commuting = commuting

    def _execute(self, hook=None, tracer=None) -> int:
        x = 1
        for tick in range(self.ticks):
            names = ["inc", "dbl"]
            order = names if hook is None else hook(None, tick, "phases",
                                                    names)
            for phase in order:
                if phase == "inc" or self.commuting:
                    x += 1
                else:
                    x *= 2
        return x

    def record(self):
        sites = [("phases", t, ("inc", "dbl")) for t in range(self.ticks)]
        return repr(self._execute()).encode(), sites

    def replay(self, deltas: _Delta, tracer=None) -> bytes:
        def hook(_fl, tick, kind, names):
            want = deltas.get((kind, tick))
            return list(want) if want is not None else names

        return repr(self._execute(hook)).encode()

    def postmortem(self, deltas: _Delta, reason: str) -> None:
        return None                    # nothing to dump for the toy


# ---------------------------------------------------------------------------
# the four scaled-down chaos drives
# ---------------------------------------------------------------------------


def _drive_fleet_kill_partition() -> FleetDrive:
    """Replica kill + heartbeat partition on a 3-replica unified fleet:
    one replica is killed outright, a second is partitioned past its
    lease TTL (zombie-fenced), and every request must still reach one
    terminal with its exactly-once stream intact."""

    def build(tracer=None):
        from paddle_tpu.serving import (FleetFaultPlan, FleetRouter,
                                        ManualClock)

        plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                              kill_at={5: 0}, partitions={1: (3, 60)})
        return FleetRouter(lambda i, tf: _make_engine(tf), 3,
                           heartbeat_s=0.05, resubmit_budget=3,
                           faults=plan, tracer=tracer)

    prompts = _prompts(seed=1, n=6)

    def arrivals(tick, fl):
        if tick == 0:
            for p in prompts[:4]:
                fl.submit(p, max_tokens=3)
        elif tick == 2:
            for p in prompts[4:]:
                fl.submit(p, max_tokens=3)

    return FleetDrive("fleet_kill_partition", build, arrivals)


def _drive_migration_drop_kill() -> FleetDrive:
    """Disaggregated prefill/decode fleet: chain handoffs with one blob
    dropped in flight (re-prefill fallback) and one decode replica
    killed mid-stream (death resubmit) — the migration ledger must
    balance under every schedule."""

    def build(tracer=None):
        from paddle_tpu.serving import (FleetFaultPlan, FleetRouter,
                                        ManualClock)

        plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                              drop_migration_at={1}, kill_at={8: 2})
        return FleetRouter(lambda i, tf: _make_engine(tf), 3,
                           roles=["prefill", "decode", "decode"],
                           heartbeat_s=0.05, resubmit_budget=3,
                           migrate_budget=8, faults=plan, tracer=tracer)

    prompts = _prompts(seed=2, n=5, shared=8)

    def arrivals(tick, fl):
        if tick == 0:
            for p in prompts:
                fl.submit(p, max_tokens=3)

    return FleetDrive("migration_drop_kill", build, arrivals)


def _drive_control_storm_autoscale() -> FleetDrive:
    """Tenant storm through the WFQ with the autoscaler live: a batch
    tenant floods a 1-replica fleet, the policy loop scales up and back
    down, and weighted-fair release order must not leak into terminal
    results."""

    def build(tracer=None):
        from paddle_tpu.serving import (FleetFaultPlan, FleetRouter,
                                        ManualClock)
        from paddle_tpu.serving.control import (AutoscalePolicy,
                                                TenantRegistry)

        reg = TenantRegistry()
        reg.register("storm", "batch")
        reg.register("fg", "batch")
        plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01))
        return FleetRouter(
            lambda i, tf: _make_engine(tf), 1, heartbeat_s=0.05,
            resubmit_budget=2, faults=plan, tenants=reg, wfq=True,
            autoscale=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                      buffered_hi=2, cooldown_ticks=2),
            tracer=tracer)

    storm = _prompts(seed=3, n=9, lo=5, hi=8)
    fg = _prompts(seed=4, n=2)

    def arrivals(tick, fl):
        if tick in (0, 2, 4):
            for p in storm[tick // 2 * 3:tick // 2 * 3 + 3]:
                fl.submit(p, max_tokens=2, tenant="storm")
        if tick == 1:
            for p in fg:
                fl.submit(p, max_tokens=2, tenant="fg")

    def extra(fl):
        from paddle_tpu.serving.control import check_control_conservation

        check_control_conservation(fl)

    return FleetDrive("control_storm_autoscale", build, arrivals,
                      extra_checks=extra)


def _drive_hosttier_kill_restart() -> FleetDrive:
    """Host-RAM spill tier under pressure: a small device pool forces
    spills, one replica is killed and later warm-restarted (its host
    tier re-adopted, checksum-verified), and late arrivals ride the
    restored cache — page conservation must hold across the restart
    under every schedule."""

    state = {"restarted": False}

    def build(tracer=None):
        from paddle_tpu.serving import (FleetFaultPlan, FleetRouter,
                                        ManualClock)

        state["restarted"] = False
        plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                              kill_at={4: 0})
        return FleetRouter(
            lambda i, tf: _make_engine(tf, num_pages=16,
                                       host_tier_bytes=1 << 20,
                                       swap_in_budget=4),
            2, heartbeat_s=0.05, resubmit_budget=3, faults=plan,
            routing="round_robin", tracer=tracer)

    prompts = _prompts(seed=5, n=12, shared=8, lo=4, hi=6)

    # Arrival waves are dense enough that the fleet never drains before
    # the warm restart: _execute() stops as soon as has_work goes False,
    # so a gap in arrivals would end the drive early and the restart
    # window (and its JOINING+READY overlap, the interesting hot ticks)
    # would never be explored.
    waves = {0: prompts[:4], 3: prompts[4:6], 5: prompts[6:8],
             7: prompts[8:10], 9: prompts[10:]}

    def arrivals(tick, fl):
        from paddle_tpu.serving import ReplicaState

        for p in waves.get(tick, ()):
            fl.submit(p, max_tokens=5)
        if tick == 5 and not state["restarted"] and \
                fl.replicas[0].state is ReplicaState.DEAD:
            fl.restart_replica(0)
            state["restarted"] = True

    return FleetDrive("hosttier_kill_restart", build, arrivals)


def default_drives() -> List[FleetDrive]:
    return [_drive_fleet_kill_partition(), _drive_migration_drop_kill(),
            _drive_control_storm_autoscale(),
            _drive_hosttier_kill_restart()]


# ---------------------------------------------------------------------------
# the explorer
# ---------------------------------------------------------------------------


def _site_perms(names: Tuple, cap: int = 5) -> List[Tuple]:
    """Non-canonical permutations of one ordering point, deterministic
    (lexicographic) order, capped so replica-rich fleets don't explode
    one site into hundreds of schedules."""
    out = [p for p in itertools.permutations(names) if p != tuple(names)]
    return out[:cap]


def enumerate_schedules(sites: List[Tuple[str, int, Tuple]],
                        budget: int) -> List[_Delta]:
    """Single-tick deltas over every hot site first (breadth), then
    depth-2 combinations (site-pair, first permutation each) — bounded
    DFS order, deterministic, truncated at ``budget``."""
    singles: List[Tuple[Tuple[str, int], Tuple]] = []
    for kind, tick, names in sites:
        for p in _site_perms(names):
            singles.append(((kind, tick), p))
    schedules: List[_Delta] = [{key: p} for key, p in singles]
    if len(schedules) < budget:
        for (k1, p1), (k2, p2) in itertools.combinations(singles, 2):
            if k1 == k2:
                continue              # one order per ordering point
            schedules.append({k1: p1, k2: p2})
            if len(schedules) >= budget:
                break
    return schedules[:budget]


def _fmt_delta(deltas: _Delta) -> str:
    parts = [f"tick {tick} {kind} order {list(order)!r}"
             for (kind, tick), order in sorted(deltas.items())]
    return "; ".join(parts)


def explore_drive(drive, budget: Optional[int] = None,
                  max_findings: int = 3) -> Tuple[int, List[Diagnostic]]:
    """Explore one drive's schedule space; returns (schedules explored,
    diagnostics).  A fingerprint mismatch or a replay crash (a
    conservation ledger raising under a permuted schedule) is an ERROR
    finding naming the minimal schedule delta; exploration continues —
    capped at ``max_findings`` — so one divergence doesn't mask an
    independent one at another site."""
    if budget is None:
        budget = int(FLAGS.conc_audit_max_schedules)
    _enable_compile_cache()
    diags: List[Diagnostic] = []
    base_fp, sites = drive.record()
    explored = 0
    for deltas in enumerate_schedules(sites, budget):
        delta_s = _fmt_delta(deltas)
        try:
            fp = drive.replay(deltas)
        except Exception as e:
            explored += 1
            diags.append(Diagnostic(
                Severity.ERROR, "SCHED-AUDIT",
                f"drive {drive.name}: replay crashed under permuted "
                f"schedule [{delta_s}]: {type(e).__name__}: {e} — the "
                "permuted order broke an invariant the canonical order "
                "upholds"))
            if len(diags) >= max_findings:
                break
            continue
        explored += 1
        if fp != base_fp:
            diags.append(Diagnostic(
                Severity.ERROR, "SCHED-AUDIT",
                f"drive {drive.name}: terminal fingerprint diverged "
                f"under permuted schedule [{delta_s}] — minimal "
                "schedule prefix; statuses, streams, or ledgers are "
                "order-sensitive where step() declares them commutable"))
            drive.postmortem(deltas,
                             f"SCHED-AUDIT divergence: {drive.name} "
                             f"[{delta_s}]")
            if len(diags) >= max_findings:
                break
    if not diags and explored < min(MIN_SCHEDULES_PER_DRIVE, budget):
        diags.append(Diagnostic(
            Severity.WARNING, "SCHED-AUDIT",
            f"drive {drive.name}: only {explored} schedules explored "
            f"(coverage bar is {MIN_SCHEDULES_PER_DRIVE}, budget "
            f"{budget}) — the drive has too few hot ordering points to "
            "meaningfully audit; widen it"))
    return explored, diags


def run_schedule_audit(runtime_only: bool = False) -> List[Diagnostic]:
    """Drive the chaos scenarios and return SCHED-AUDIT diagnostics
    (plus PROTO-AUDIT runtime-transition findings — the recorder is
    reset first and every drive feeds it through the fleet's
    instrumented transition sites).  ``runtime_only`` skips the
    permutation exploration and runs each drive once canonically — the
    cheap path when only rule ``transition-runtime`` is selected."""
    from paddle_tpu.analysis.concurrency.lifecycle import (
        reset_recorder, runtime_diagnostics)

    reset_recorder()
    _enable_compile_cache()
    diags: List[Diagnostic] = []
    for drive in default_drives():
        if runtime_only:
            drive.record()
        else:
            _, found = explore_drive(drive)
            diags.extend(found)
    diags.extend(runtime_diagnostics())
    return diags
