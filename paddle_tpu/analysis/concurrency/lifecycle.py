"""Declared lifecycle state machines — the ``PROTO-AUDIT`` rule family.

The distributed runtime's correctness arguments are all phrased over
small state machines (a request reaches exactly one terminal status; a
replica dies exactly once; a migration resolves to exactly one of
applied/fallback/aborted; a checkpoint commits through a fixed phase
chain) — but until now the machines lived implicitly in scattered
assignment sites.  This module *declares* them as
:class:`StateMachineSpec` tables and checks the code against the
tables two ways:

- **statically** (:func:`run_static_check`): an AST pass extracts every
  literal status/phase assignment site (``x.status = RequestStatus.X``,
  ``rep.state = ReplicaState.Y``, the ``metrics.on_migration_*`` ledger
  markers, the ``ckpt.snapshot/write/prune`` phase chain) and flags any
  site whose state is not in the table — plus drift between the
  scheduler's ``_TERMINAL`` frozenset / the ``ReplicaState`` enum and
  the declared tables, so the table cannot silently rot.
- **dynamically**: the runtime calls :func:`record_transition` at its
  transition choke points (``FleetRouter._finish`` / ``_fence`` /
  ``_promote_joining`` / the migration ledger / the checkpoint writer).
  The process-global :class:`TransitionRecorder` counts every edge and
  flags undeclared ones; any tier-1 drive that takes an edge outside
  the table surfaces it through :func:`undeclared_transitions` (and the
  ``lifecycle_transitions_total`` / ``lifecycle_undeclared_total``
  counters on whichever obs registry the caller passes in).

All findings carry the grep-able ``PROTO-AUDIT`` code.
"""

from __future__ import annotations

import ast
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["StateMachineSpec", "MACHINES", "TransitionRecorder",
           "recorder", "record_transition", "undeclared_transitions",
           "reset_recorder", "run_static_check"]


@dataclass(frozen=True)
class StateMachineSpec:
    """One declared lifecycle machine: the full state set, the legal
    edge set, and where its literal assignment sites live."""

    name: str
    states: Tuple[str, ...]
    initial: str
    terminal: FrozenSet[str]
    edges: FrozenSet[Tuple[str, str]]
    doc: str = ""

    def legal(self, src: str, dst: str) -> bool:
        return (src, dst) in self.edges


_REQ_TERMINALS = ("completed", "timed_out", "cancelled", "rejected",
                  "failed")

REQUEST_STATUS = StateMachineSpec(
    name="request_status",
    states=("queued", "running", "preempted") + _REQ_TERMINALS,
    initial="queued",
    terminal=frozenset(_REQ_TERMINALS),
    edges=frozenset(
        # dispatch / engine-mirror progress
        [("queued", "running"), ("queued", "preempted"),
         ("running", "preempted"), ("preempted", "running"),
         # death-resubmit and migration-fallback re-dispatch loops
         ("running", "queued"), ("preempted", "queued")]
        # every live state may reach every terminal (shed, deadline,
        # cancel, kill-with-burned-budget, engine reject)
        + [(src, t) for src in ("queued", "running", "preempted")
           for t in _REQ_TERMINALS]),
    doc="fleet-level request lifecycle (mirrors the engine statuses; "
        "exactly one terminal transition per rid — _finish refuses a "
        "second one and counts it as duplicate_completions instead)")

REPLICA_LIFECYCLE = StateMachineSpec(
    name="replica_lifecycle",
    states=("joining", "ready", "draining", "dead"),
    initial="joining",
    terminal=frozenset({"dead"}),
    edges=frozenset([
        ("joining", "ready"),      # lease alive + healthz -> promoted
        ("joining", "draining"),   # drained before first promotion
        ("joining", "dead"),       # fenced before first promotion
        ("ready", "draining"),     # drain_replica / autoscaler
        ("ready", "dead"),         # kill / lease lapse -> _fence
        ("draining", "dead"),      # graceful retire, or fenced mid-drain
        ("dead", "joining"),       # restart_replica (warm restart)
    ]),
    doc="replica membership lifecycle (fence-then-reap on death; "
        "restart re-enters through JOINING, never straight to READY)")

MIGRATION_TRANSFER = StateMachineSpec(
    name="migration_transfer",
    states=("started", "applied", "fallback", "aborted"),
    initial="started",
    terminal=frozenset({"applied", "fallback", "aborted"}),
    edges=frozenset([
        ("started", "applied"),    # chain imported at the destination
        ("started", "fallback"),   # blob dropped in flight -> re-prefill
        ("started", "aborted"),    # stale / terminal rid / dest died
    ]),
    doc="chain-handoff ledger states; conservation requires "
        "started == applied + fallback + aborted at any full drain")

CHECKPOINT_COMMIT = StateMachineSpec(
    name="checkpoint_commit",
    states=("idle", "snapshot", "write", "commit", "prune", "failed"),
    initial="idle",
    terminal=frozenset(),          # the machine cycles back to idle
    edges=frozenset([
        ("idle", "snapshot"),      # save(): blocking device->host copy
        ("snapshot", "write"),     # writer thread takes the payload
        ("write", "commit"),       # tmp+rename+md5 landed, meta last
        ("commit", "prune"),       # keep-budget pruning (keep > 0)
        ("commit", "idle"),        # keep == 0: no prune pass
        ("prune", "idle"),
        ("write", "failed"),       # writer exception (injected death)
        ("failed", "idle"),        # error recorded; surfaces at wait()
    ]),
    doc="depth-one pipelined checkpoint phases (commit order == submit "
        "order; a failed write leaves the previous checkpoint latest)")

MACHINES: Dict[str, StateMachineSpec] = {
    m.name: m for m in (REQUEST_STATUS, REPLICA_LIFECYCLE,
                        MIGRATION_TRANSFER, CHECKPOINT_COMMIT)}


# ---------------------------------------------------------------------------
# dynamic: the transition recorder
# ---------------------------------------------------------------------------


class TransitionRecorder:
    """Process-global transition counter.

    Stateless with respect to the *instances* being tracked: call sites
    pass explicit ``(src, dst)`` pairs, so any number of routers,
    engines and checkpointers share one recorder without confusing each
    other's machines.  Thread-safe because the checkpoint writer thread
    records from off the training thread.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str, str], int] = {}  # guarded_by(_lock)
        self._undeclared: List[Tuple[str, str, str]] = []   # guarded_by(_lock)

    def record(self, machine: str, src, dst, registry=None) -> bool:
        """Count one ``src -> dst`` edge; returns True when the edge is
        declared.  Self-loops (mirror refreshes) are ignored.  Unknown
        machine names are themselves undeclared edges."""
        src_s, dst_s = str(src), str(dst)
        if src_s == dst_s:
            return True
        spec = MACHINES.get(machine)
        ok = spec is not None and spec.legal(src_s, dst_s)
        with self._lock:
            key = (machine, src_s, dst_s)
            self._counts[key] = self._counts.get(key, 0) + 1
            if not ok:
                self._undeclared.append(key)
        if registry is not None:
            registry.counter(
                "lifecycle_transitions_total",
                "declared-state-machine edges taken at runtime").labels(
                    machine=machine, src=src_s, dst=dst_s).inc()
            if not ok:
                registry.counter(
                    "lifecycle_undeclared_total",
                    "transitions outside the declared tables "
                    "(PROTO-AUDIT)").labels(machine=machine).inc()
        return ok

    def counts(self) -> Dict[Tuple[str, str, str], int]:
        with self._lock:
            return dict(self._counts)

    def undeclared(self) -> List[Tuple[str, str, str]]:
        with self._lock:
            return list(self._undeclared)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._undeclared.clear()


_RECORDER = TransitionRecorder()


def recorder() -> TransitionRecorder:
    return _RECORDER


def record_transition(machine: str, src, dst, registry=None) -> bool:
    """The runtime hook: one line at each transition choke point."""
    return _RECORDER.record(machine, src, dst, registry=registry)


def undeclared_transitions() -> List[Tuple[str, str, str]]:
    return _RECORDER.undeclared()


def reset_recorder() -> None:
    _RECORDER.reset()


# ---------------------------------------------------------------------------
# static: assignment-site extraction probes
# ---------------------------------------------------------------------------


def _pkg_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


def _read_sources(rel_paths: Sequence[str],
                  sources: Optional[Dict[str, str]]) -> Dict[str, str]:
    """{package-relative path: source}; ``sources`` overrides disk (the
    seeded-bad tests feed doctored modules through here)."""
    if sources is not None:
        return dict(sources)
    root = _pkg_root().parent
    return {p: (root / p).read_text() for p in rel_paths}


def _enum_assign_sites(tree: ast.Module, attr: str,
                       enum_name: str) -> List[Tuple[int, str]]:
    """(line, MEMBER) for every ``<x>.<attr> = <enum_name>.<MEMBER>``
    assignment — plus dataclass defaults ``<attr>: T = <enum>.<M>``."""
    out: List[Tuple[int, str]] = []

    def _value_member(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Attribute) and \
                isinstance(value.value, ast.Name) and \
                value.value.id == enum_name:
            return value.attr
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            member = _value_member(node.value)
            if member is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == attr:
                    out.append((node.lineno, member))
                elif isinstance(t, ast.Tuple):
                    for el in t.elts:
                        if isinstance(el, ast.Attribute) and \
                                el.attr == attr:
                            out.append((node.lineno, member))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            member = _value_member(node.value)
            if member is not None and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id == attr:
                out.append((node.lineno, member))
    return out


def _frozenset_members(tree: ast.Module, name: str,
                       enum_name: str) -> Optional[List[str]]:
    """Members of ``NAME = frozenset({Enum.A, Enum.B, ...})``."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets)):
            continue
        call = node.value
        if not (isinstance(call, ast.Call) and
                isinstance(call.func, ast.Name) and
                call.func.id == "frozenset" and call.args):
            continue
        members: List[str] = []
        for el in ast.walk(call.args[0]):
            if isinstance(el, ast.Attribute) and \
                    isinstance(el.value, ast.Name) and \
                    el.value.id == enum_name:
                members.append(el.attr)
        return members
    return None


def _enum_class_members(tree: ast.Module,
                        cls_name: str) -> Optional[List[str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            members = []
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Constant) and \
                        isinstance(stmt.value.value, str):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            members.append(stmt.value.value)
            return members
    return None


def _diag(msg: str, where: str) -> Diagnostic:
    return Diagnostic(Severity.ERROR, "PROTO-AUDIT", msg, vars=(where,))


def _check_request_status(sources: Optional[Dict[str, str]]) -> List[Diagnostic]:
    spec = REQUEST_STATUS
    paths = ("paddle_tpu/serving/scheduler.py",
             "paddle_tpu/serving/engine.py",
             "paddle_tpu/serving/fleet.py")
    srcs = _read_sources(paths, sources)
    out: List[Diagnostic] = []
    declared = {s.upper() for s in spec.states}
    for path, src in srcs.items():
        tree = ast.parse(src, filename=path)
        for lineno, member in _enum_assign_sites(tree, "status",
                                                 "RequestStatus"):
            if member not in declared:
                out.append(_diag(
                    f"{path}:{lineno}: assignment site uses undeclared "
                    f"request status RequestStatus.{member} — declare "
                    "it in the request_status StateMachineSpec or drop "
                    "the state", f"{path}:{lineno}"))
        terms = _frozenset_members(tree, "_TERMINAL", "RequestStatus")
        if terms is not None:
            got = {t.lower() for t in terms}
            if got != set(spec.terminal):
                out.append(_diag(
                    f"{path}: scheduler _TERMINAL {sorted(got)} drifted "
                    f"from the declared terminal set "
                    f"{sorted(spec.terminal)}", path))
    return out


def _check_replica_lifecycle(sources: Optional[Dict[str, str]]) -> List[Diagnostic]:
    spec = REPLICA_LIFECYCLE
    path = "paddle_tpu/serving/fleet.py"
    src = _read_sources((path,), sources)[path]
    tree = ast.parse(src, filename=path)
    out: List[Diagnostic] = []
    declared = {s.upper() for s in spec.states}
    for lineno, member in _enum_assign_sites(tree, "state",
                                             "ReplicaState"):
        if member not in declared:
            out.append(_diag(
                f"{path}:{lineno}: assignment site uses undeclared "
                f"replica state ReplicaState.{member} — declare it in "
                "the replica_lifecycle StateMachineSpec",
                f"{path}:{lineno}"))
    members = _enum_class_members(tree, "ReplicaState")
    if members is not None and set(members) != set(spec.states):
        out.append(_diag(
            f"{path}: ReplicaState enum {sorted(members)} drifted from "
            f"the declared state set {sorted(spec.states)}", path))
    return out


_MIGRATION_MARKERS = {
    "applied": "on_migration_applied",
    "fallback": "on_migration_fallback",
    "aborted": "on_migration_aborted",
}


def _check_migration_transfer(sources: Optional[Dict[str, str]]) -> List[Diagnostic]:
    spec = MIGRATION_TRANSFER
    path = "paddle_tpu/serving/fleet.py"
    src = _read_sources((path,), sources)[path]
    tree = ast.parse(src, filename=path)
    calls: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr.startswith("on_migration_"):
            calls[node.func.attr] = calls.get(node.func.attr, 0) + 1
    out: List[Diagnostic] = []
    if "on_migration_start" not in calls:
        out.append(_diag(
            f"{path}: no on_migration_start ledger marker — the "
            "migration_transfer machine has no entry site", path))
    for state in sorted(spec.terminal):
        marker = _MIGRATION_MARKERS[state]
        if marker not in calls:
            out.append(_diag(
                f"{path}: declared migration terminal '{state}' has no "
                f"{marker}() ledger site — the conservation identity "
                "cannot balance", path))
    # on_migration_resubmit counts cross-replica prefix RE-SEEDING for a
    # resubmitted request — a cache-warmth event, not a transfer-state
    # transition — so it is exempt rather than declared
    known = {"on_migration_start", "on_migration_resubmit"} \
        | set(_MIGRATION_MARKERS.values())
    for marker in sorted(set(calls) - known):
        out.append(_diag(
            f"{path}: ledger marker {marker}() has no state in the "
            "migration_transfer StateMachineSpec — declare it", path))
    return out


_CKPT_PHASE_MARKERS = (("snapshot", "snapshot_checkpoint"),
                       ("write", "write_checkpoint"),
                       ("prune", "prune_checkpoints"))


def _check_checkpoint_commit(sources: Optional[Dict[str, str]]) -> List[Diagnostic]:
    path = "paddle_tpu/resilience/checkpointer.py"
    src = _read_sources((path,), sources)[path]
    tree = ast.parse(src, filename=path)
    first_line: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            name = node.func.attr
            if any(name == marker for _, marker in _CKPT_PHASE_MARKERS):
                first_line.setdefault(name, node.lineno)
    out: List[Diagnostic] = []
    prev = 0
    for phase, marker in _CKPT_PHASE_MARKERS:
        if marker not in first_line:
            out.append(_diag(
                f"{path}: checkpoint phase '{phase}' has no "
                f"ckpt.{marker}() site — the commit chain is broken",
                path))
            continue
        if first_line[marker] < prev:
            out.append(_diag(
                f"{path}:{first_line[marker]}: ckpt.{marker}() appears "
                f"before the preceding phase's marker — the declared "
                "phase order snapshot->write->prune is violated",
                f"{path}:{first_line[marker]}"))
        prev = first_line[marker]
    return out


def run_static_check(sources: Optional[Dict[str, str]] = None) -> List[Diagnostic]:
    """All four machines' static probes.  ``sources`` (path -> source)
    overrides disk for the probed files — the seeded-bad tests use it."""
    out: List[Diagnostic] = []
    out.extend(_check_request_status(sources))
    out.extend(_check_replica_lifecycle(sources))
    out.extend(_check_migration_transfer(sources))
    out.extend(_check_checkpoint_commit(sources))
    out.sort(key=lambda d: d.message)
    return out


def runtime_diagnostics() -> List[Diagnostic]:
    """PROTO-AUDIT findings for every undeclared edge the recorder has
    seen since the last reset (the dynamic half of the rule)."""
    out: List[Diagnostic] = []
    seen = set()
    for machine, src, dst in _RECORDER.undeclared():
        key = (machine, src, dst)
        if key in seen:
            continue
        seen.add(key)
        out.append(Diagnostic(
            Severity.ERROR, "PROTO-AUDIT",
            f"runtime transition {machine}: {src} -> {dst} is not in "
            "the declared StateMachineSpec — declare the edge or fix "
            "the transition site", vars=(machine,)))
    return out
