"""paddle_tpu.analysis.concurrency — the concurrency auditor.

Three rule families over the distributed runtime's host-side state
(tier-1 ladder exit 14, CLI ``python -m paddle_tpu.analysis
concurrency``):

- :mod:`~paddle_tpu.analysis.concurrency.guards` — ``CONC-AUDIT``:
  the ``# guarded_by(...)`` lock-discipline checker (rule
  ``guarded-by``).
- :mod:`~paddle_tpu.analysis.concurrency.lifecycle` — ``PROTO-AUDIT``:
  declared :class:`StateMachineSpec` tables checked statically against
  every literal assignment site (rule ``state-table``) and dynamically
  through the transition recorder during the chaos drives (rule
  ``transition-runtime``).
- :mod:`~paddle_tpu.analysis.concurrency.schedules` — ``SCHED-AUDIT``:
  the schedule-permutation model checker replaying the seeded chaos
  drives under permuted intra-tick phase orders (rule
  ``schedule-permute``).

This ``__init__`` stays lazy on purpose: ``serving/fleet.py`` and
``resilience/checkpointer.py`` import the transition-recorder hook from
:mod:`.lifecycle` on their own import paths, so pulling the schedule
explorer (which itself drives the fleet) in here would be a cycle.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

RULE_NAMES = ("guarded-by", "state-table", "transition-runtime",
              "schedule-permute")

__all__ = ["RULE_NAMES", "run_concurrency_audit"]


def run_concurrency_audit(rules: Optional[Sequence[str]] = None) -> List:
    """Run the selected rule families (default: all four) and return
    their merged :class:`Diagnostic` list.  ``transition-runtime`` and
    ``schedule-permute`` drive the real chaos fleets, so they dominate
    the runtime; the two static families are milliseconds."""
    selected = tuple(rules) if rules is not None else RULE_NAMES
    diags: List = []
    if "guarded-by" in selected:
        from paddle_tpu.analysis.concurrency.guards import run_guard_check
        diags.extend(run_guard_check())
    if "state-table" in selected:
        from paddle_tpu.analysis.concurrency.lifecycle import \
            run_static_check
        diags.extend(run_static_check())
    need_drives = {"transition-runtime", "schedule-permute"} & set(selected)
    if need_drives:
        from paddle_tpu.analysis.concurrency import schedules
        diags.extend(schedules.run_schedule_audit(
            runtime_only="schedule-permute" not in selected))
    return diags
