"""Lock-discipline checker — the ``CONC-AUDIT`` rule family.

The distributed runtime's host-side state lives in two concurrency
regimes, and both are now *declared* next to the field they protect:

- ``# guarded_by(<lock_attr>)`` — the field is shared across threads
  and every access outside ``__init__``/``__post_init__`` must be
  lexically inside a ``with <...>.<lock_attr>:`` block.  The proof is
  lexical on purpose: a ``with m._lock:`` over another object's lock of
  the same *name* satisfies the checker, which matches how this repo
  shares one lock between a parent metric and its series views.
- ``# guarded_by(serialized: <justification>)`` — the field is mutable
  but *confined*: a documented happens-before edge (``Thread.join`` in
  ``AsyncCheckpointer.wait``, the single-threaded fleet tick driving
  ``HostPageTier``, the queue sentinel in ``reader/prefetch.py``)
  serializes all accesses, so no lock exists.  The checker proves the
  field is touched only through ``self`` inside its declaring class —
  any cross-object access needs an explicit
  ``# lint: allow(guarded-by)`` naming the edge that makes it safe.
- ``# guarded_by(caller: <lock_attr>)`` on a ``def`` line — the
  Clang-``REQUIRES`` idiom: the method touches guarded fields but the
  *caller* holds the lock.  The body is checked as if the lock were
  held, and every ``self.<method>()`` call site outside a ``with`` over
  that lock (and outside ``__init__``) is a finding.

The annotation rides the assignment that *creates* the field (same
line or the line above), in ``__init__``/``__post_init__`` or the class
body.  Suppression uses the linter's own escape hatch —
``# lint: allow(guarded-by)`` on the access line or the line directly
above — so one grep (``lint: allow``) still finds every sanctioned
exception in the repo.

A second, coverage-shaped rule keeps the convention honest: every
module in :data:`REQUIRED_MODULES` (the ones that actually spawn
threads or hand state across them) must declare at least one guard —
a new threaded module cannot silently opt out of the discipline.

All findings carry the grep-able ``CONC-AUDIT`` code.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.lint import _allowed_rules, _attr_chain

__all__ = ["run_guard_check", "check_guards_source", "collect_guards",
           "REQUIRED_MODULES", "GuardSpec"]

_GUARD_RE = re.compile(r"#\s*guarded_by\(([^)]*)\)")
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_ALLOW_RULE = "guarded-by"

#: Modules (package-relative POSIX paths) that are genuinely threaded —
#: they spawn threads, run under ThreadingTCPServer handlers, or hand
#: mutable state across a thread boundary — and therefore MUST declare
#: their discipline.  An entry with zero annotations is itself a
#: finding.
REQUIRED_MODULES: Tuple[str, ...] = (
    "paddle_tpu/resilience/checkpointer.py",
    "paddle_tpu/serving/kv_cache.py",
    "paddle_tpu/obs/registry.py",
    "paddle_tpu/obs/trace.py",
    "paddle_tpu/platform/stats.py",
    "paddle_tpu/master/service.py",
    "paddle_tpu/master/server.py",
    "paddle_tpu/reader/prefetch.py",
    "paddle_tpu/analysis/retrace.py",
)

_INIT_METHODS = ("__init__", "__post_init__")


@dataclass(frozen=True)
class GuardSpec:
    """One declared guard: ``field`` in ``cls`` is protected by
    ``lock`` (kind ``"lock"``) or by a documented serialization edge
    (kind ``"serialized"``, justification in ``note``)."""

    cls: str
    field: str
    kind: str                  # "lock" | "serialized"
    lock: Optional[str]        # lock attribute name for kind "lock"
    note: str
    lineno: int


def _parse_guard_comment(lines: List[str], lineno: int) -> Optional[Tuple[str, Optional[str], str]]:
    """(kind, lock, note) for a guarded_by comment on ``lineno`` or the
    line directly above; None when absent or malformed (malformed is
    reported by the caller via the raw-text sweep)."""
    for ln in (lineno, lineno - 1):
        if not 1 <= ln <= len(lines):
            continue
        m = _GUARD_RE.search(lines[ln - 1])
        if not m:
            continue
        body = m.group(1).strip()
        if body.startswith("serialized"):
            _, _, note = body.partition(":")
            return ("serialized", None, note.strip())
        if body.startswith("caller"):
            _, _, lock = body.partition(":")
            lock = lock.strip()
            if _IDENT_RE.match(lock):
                return ("caller", lock, "")
            return ("malformed", None, body)
        if _IDENT_RE.match(body):
            return ("lock", body, "")
        return ("malformed", None, body)
    return None


def collect_guards(tree: ast.Module, lines: List[str]) -> Dict[str, List[GuardSpec]]:
    """{class name: [GuardSpec, ...]} for every annotated field-creating
    assignment (class body, or ``self.x = ...`` in __init__/__post_init__)."""
    out: Dict[str, List[GuardSpec]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        specs: List[GuardSpec] = []

        def _try(field: str, lineno: int) -> None:
            parsed = _parse_guard_comment(lines, lineno)
            if parsed is None:
                return
            kind, lock, note = parsed
            specs.append(GuardSpec(cls.name, field, kind, lock, note,
                                   lineno))

        for node in cls.body:
            if isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                _try(node.target.id, node.lineno)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        _try(t.id, node.lineno)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a guarded_by(caller: L) on the def line declares the
                # REQUIRES contract for the whole method
                _try(node.name, node.lineno)
                if node.name in _INIT_METHODS:
                    for sub in ast.walk(node):
                        targets: List[ast.expr] = []
                        if isinstance(sub, ast.Assign):
                            targets = list(sub.targets)
                        elif isinstance(sub, ast.AnnAssign):
                            targets = [sub.target]
                        for t in targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                _try(t.attr, sub.lineno)
        if specs:
            out[cls.name] = specs
    return out


class _AccessVisitor(ast.NodeVisitor):
    """Walk one class's methods tracking which lock *names* are
    lexically held (``with <chain ending in name>:``), and record every
    access to a guarded field."""

    def __init__(self, cls: ast.ClassDef,
                 own: Dict[str, GuardSpec],
                 module_guards: Dict[str, List[GuardSpec]],
                 caller_locks: Dict[str, str]):
        self.cls = cls
        self.own = own                      # this class's field -> spec
        self.module_guards = module_guards  # field -> specs, whole module
        self.caller_locks = caller_locks    # method -> lock it REQUIRES
        self.held: List[str] = []           # stack of held lock names
        self.in_init = 0
        self.findings: List[Tuple[int, str]] = []

    # -- scope tracking ----------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        is_init = node.name in _INIT_METHODS
        if is_init:
            self.in_init += 1
        req = self.caller_locks.get(node.name)
        if req is not None:
            self.held.append(req)   # the declared REQUIRES contract
        self.generic_visit(node)
        if req is not None:
            self.held.pop()
        if is_init:
            self.in_init -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            req = self.caller_locks.get(f.attr)
            if req is not None and req not in self.held and \
                    not self.in_init:
                self.findings.append((
                    node.lineno,
                    f"call to {self.cls.name}.{f.attr}() — declared "
                    f"guarded_by(caller: {req}) — outside "
                    f"`with ...{req}:`"))
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            chain = _attr_chain(item.context_expr)
            if len(chain) >= 2:
                acquired.append(chain[-1])
        self.held.extend(acquired)
        self.generic_visit(node)
        del self.held[len(self.held) - len(acquired):]

    # -- the check ---------------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        recv = node.value
        field = node.attr
        if isinstance(recv, ast.Name) and recv.id == "self":
            spec = self.own.get(field)
            if spec is not None and not self.in_init:
                if spec.kind == "lock" and spec.lock not in self.held:
                    self.findings.append((
                        node.lineno,
                        f"{self.cls.name}.{field} is "
                        f"guarded_by({spec.lock}) but accessed outside "
                        f"`with ...{spec.lock}:`"))
                # serialized: any self access inside the class is the
                # declared discipline — nothing to prove here
        else:
            # cross-object access to a field name guarded anywhere in
            # this module: x._pending, series.value, ...
            specs = self.module_guards.get(field, ())
            for spec in specs:
                if spec.kind == "lock":
                    if spec.lock not in self.held:
                        self.findings.append((
                            node.lineno,
                            f"access to '{field}' (guarded_by"
                            f"({spec.lock}) in {spec.cls}) outside "
                            f"`with ...{spec.lock}:`"))
                    break
                self.findings.append((
                    node.lineno,
                    f"cross-object access to '{field}' — declared "
                    f"guarded_by(serialized) in {spec.cls}; name the "
                    "happens-before edge with `# lint: "
                    "allow(guarded-by)` if this is safe"))
                break
        self.generic_visit(node)


def check_guards_source(src: str, path: str = "<string>") -> Tuple[List[Diagnostic], int]:
    """(findings, number of guard annotations) for one source file."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return ([Diagnostic(Severity.ERROR, "CONC-AUDIT",
                            f"{path}:{e.lineno}: parse error: {e.msg}")],
                0)
    lines = src.splitlines()
    per_class = collect_guards(tree, lines)
    n_guards = 0
    diags: List[Diagnostic] = []
    field_index: Dict[str, List[GuardSpec]] = {}
    for specs in per_class.values():
        for s in specs:
            if s.kind == "malformed":
                diags.append(Diagnostic(
                    Severity.ERROR, "CONC-AUDIT",
                    f"{path}:{s.lineno}: malformed guarded_by({s.note}) "
                    "— use guarded_by(<lock_attr>) or "
                    "guarded_by(serialized: <justification>)",
                    vars=(f"{path}:{s.lineno}",)))
                continue
            n_guards += 1
            if s.kind == "serialized" and not s.note:
                diags.append(Diagnostic(
                    Severity.ERROR, "CONC-AUDIT",
                    f"{path}:{s.lineno}: guarded_by(serialized:) on "
                    f"{s.cls}.{s.field} needs a justification naming "
                    "the happens-before edge",
                    vars=(f"{path}:{s.lineno}",)))
            if s.kind != "caller":
                field_index.setdefault(s.field, []).append(s)
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        own = {s.field: s for s in per_class.get(cls.name, [])
               if s.kind in ("lock", "serialized")}
        caller_locks = {s.field: s.lock
                        for s in per_class.get(cls.name, [])
                        if s.kind == "caller"}
        v = _AccessVisitor(cls, own, field_index, caller_locks)
        for node in cls.body:
            v.visit(node)
        for lineno, msg in v.findings:
            if _ALLOW_RULE in _allowed_rules(lines, lineno):
                continue
            diags.append(Diagnostic(
                Severity.ERROR, "CONC-AUDIT", f"{path}:{lineno}: {msg}",
                vars=(f"{path}:{lineno}",)))
    return diags, n_guards


def _package_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


def run_guard_check(paths: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Check every annotated module in the package (default) or just
    ``paths``; enforce annotation coverage over :data:`REQUIRED_MODULES`
    when running package-wide."""
    pkg = _package_root()
    check_coverage = paths is None
    if paths is None:
        files = sorted(pkg.rglob("*.py"))
    else:
        files = [Path(p) for p in paths]
    out: List[Diagnostic] = []
    annotated: Set[str] = set()
    for f in files:
        src = f.read_text()
        if "guarded_by(" not in src:
            continue
        try:
            rel = f.resolve().relative_to(pkg.parent).as_posix()
        except ValueError:
            rel = f.as_posix()
        diags, n = check_guards_source(src, path=rel)
        out.extend(diags)
        if n:
            annotated.add(rel)
    if check_coverage:
        for mod in REQUIRED_MODULES:
            if mod not in annotated:
                out.append(Diagnostic(
                    Severity.ERROR, "CONC-AUDIT",
                    f"{mod}: threaded module declares no guarded_by "
                    "annotations — declare the lock (or the serializing "
                    "happens-before edge) for its shared state",
                    vars=(mod,)))
    out.sort(key=lambda d: d.message)
    return out
