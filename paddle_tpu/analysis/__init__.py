"""paddle_tpu.analysis — static program verifier, jit retrace auditor,
and repo-invariant linter.

Three cooperating passes (the compile-first contract a TPU stack needs:
"does this program compile once, and is it well-formed before it runs"):

- :mod:`paddle_tpu.analysis.program_check` — abstract interpretation over
  ``fluid.Program`` graphs (and the layer-DSL ``Topology``): def-before-use,
  dangling fetches, dead variables, duplicate writers, shape/dtype
  conflicts.  Runs standalone (``python -m paddle_tpu.analysis program
  <script>``) and inline before ``Executor.run`` behind
  ``FLAGS.fluid_verify``.
- :mod:`paddle_tpu.analysis.retrace` — opt-in (``FLAGS.jit_audit``)
  instrumentation around the repo's jit call sites that records
  abstract-signature → compile events and flags any compile after a site
  is sealed (or for an already-seen signature) as a ``RETRACE``
  diagnostic.
- :mod:`paddle_tpu.analysis.lint` — AST-based repo-invariant rules
  (wall-clock in serving/master code, unseeded global RNG, host syncs in
  per-tick serving loops, mutable default args, import-time FLAGS reads),
  allowlistable via inline ``# lint: allow(<rule>)`` and runnable as
  ``python -m paddle_tpu.analysis lint``.
- :mod:`paddle_tpu.analysis.xla` — jaxpr-level compiled-path auditor
  over the captured ``audit_jit`` sites: donation contracts, dtype
  promotion drift, host transfers/callbacks, const-captured weights,
  collective placement, and per-site memory/FLOP budgets declared via
  :class:`~paddle_tpu.analysis.retrace.SiteContract` next to the jit
  call.  Runs as ``python -m paddle_tpu.analysis xla`` (tier-1 ladder
  exit 8 on ``XLA-AUDIT`` findings).
- :mod:`paddle_tpu.analysis.sharding` — static GSPMD
  sharding-propagation auditor: infers placements through each
  captured site's jaxpr from the ``PartitionSpec`` contract declared
  next to the jit (``SiteContract(in_specs=/out_specs=/mesh_axes=``)
  and reports contract mismatches, implicit all-gathers, accidental
  replication, axis collisions and collective-byte budget violations
  as ``SHARD-AUDIT`` findings.  Runs as ``python -m paddle_tpu.analysis
  sharding`` (tier-1 ladder exit 9).

This ``__init__`` stays import-light on purpose: the serving engine and
trainer import :func:`audit_jit` from here on their hot construction
paths, so pulling in the whole fluid verifier here would tax every
import of the package.
"""

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.retrace import SiteContract, audit_jit, auditor

__all__ = ["Diagnostic", "Severity", "SiteContract", "audit_jit",
           "auditor"]
