"""Jit retrace auditor: catch silent XLA recompilation on hot paths.

A TPU serving/training step that quietly retraces erases the wins the
fused step bought — the failure is invisible (everything still returns
the right numbers) and shows up only as mystery latency.  This module
wraps the repo's jit call sites (:func:`audit_jit` replaces a bare
``jax.jit``) and records, per named *site*:

- every **call** with its abstract signature (shape/dtype/weak-type of
  each array leaf; python scalars by type+value, since jax specializes
  on them via weak types or static closure);
- every **compile** — detected exactly, by counting executions of the
  wrapped python body, which jax only runs when tracing.

Two things are flagged as ``RETRACE`` diagnostics:

- a compile for a signature this site has ALREADY compiled (the classic
  silent retrace: weak-type flips, a dropped compilation cache, a new
  wrapper identity for the same computation);
- any compile after the site was **sealed** (``auditor().seal()`` after
  warmup): steady state must not compile at all.

The whole thing is gated on ``FLAGS.jit_audit`` *at wrap time*: with the
flag off (the default) ``audit_jit`` returns a bare ``jax.jit`` and
costs nothing.  Turn the flag on BEFORE constructing the engine/trainer
whose sites you want audited.

Budget assertions for tests::

    FLAGS.jit_audit = True
    eng = ServingEngine(...)
    ... run warmup traffic ...
    auditor().seal()                      # steady state begins
    ... run steady-state traffic ...
    auditor().assert_budget("serving.step", 3)   # one compile per
    #                                 (decode_bucket, prefill_bucket) pair
    auditor().assert_no_retraces()

Assertion failures carry the literal token ``RETRACE`` so CI wrappers
can grep for it, same as the PAGE-LEAK / REF-LEAK contracts.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["audit_jit", "auditor", "RetraceAuditor", "RetraceError",
           "abstract_signature"]


class RetraceError(AssertionError):
    """A compile-budget or no-retrace assertion failed.  The message
    always contains the literal token ``RETRACE``."""


def abstract_signature(args: Tuple, kwargs: Dict) -> Tuple:
    """Hashable abstract signature of a call: array leaves collapse to
    (shape, dtype, weak_type); non-array leaves keep type+repr (they are
    trace-time constants, so a changed value IS a changed program)."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            weak = bool(getattr(x, "weak_type", False))
            return ("arr", tuple(x.shape), str(x.dtype), weak)
        return ("const", type(x).__name__, repr(x))

    leaves, treedef = jax.tree.flatten((args, kwargs))
    return (str(treedef),) + tuple(leaf(x) for x in leaves)


@dataclass
class SiteRecord:
    """Per-site call/compile history."""

    name: str
    calls: int = 0
    compiles: int = 0
    sealed: bool = False
    # signature -> number of compiles it triggered (>=2 means a retrace
    # happened even without sealing)
    compiled_sigs: Dict[Tuple, int] = field(default_factory=dict)
    _pending_sig: Optional[Tuple] = None


class RetraceAuditor:
    """Registry of audited sites + the RETRACE diagnostics they raised.

    Thread-safe enough for the repo's usage (sites are created at
    construction time; counters mutate under one lock)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sites: Dict[str, SiteRecord] = {}
        self.diagnostics: List[Diagnostic] = []
        self._sealed_all = False
        # obs hook: when attached (ServingEngine.set_tracer does it for
        # an enabled tracer under FLAGS.jit_audit), every compile lands
        # on the trace timeline as a `jit_compile` instant — so a chaos
        # replay shows WHERE the compile spikes sit between the request
        # spans.  None = no tracing, zero overhead.
        self.tracer = None

    def attach_tracer(self, tracer) -> None:
        """Report each compile to an obs tracer (last attach wins; the
        auditor is process-global, so a fleet attaches its shared base
        tracer once).  Cleared by :meth:`reset`."""
        self.tracer = tracer

    # ---- bookkeeping (called by audit_jit wrappers) ----------------------

    def site(self, name: str) -> SiteRecord:
        with self._lock:
            rec = self.sites.get(name)
            if rec is None:
                # a site first seen AFTER a global seal() is born sealed:
                # "steady state must not compile" has to cover lazily
                # created jits (per-bucket prefill/chunk wrappers) too
                rec = self.sites[name] = SiteRecord(
                    name, sealed=self._sealed_all)
            return rec

    def _on_call(self, rec: SiteRecord, sig: Tuple) -> None:
        with self._lock:
            rec.calls += 1
            rec._pending_sig = sig

    def _on_compile(self, rec: SiteRecord) -> None:
        if self.tracer is not None:
            self.tracer.instant("jit_compile", cat="compile",
                                site=rec.name)
        with self._lock:
            rec.compiles += 1
            sig = rec._pending_sig
            seen = sig is not None and sig in rec.compiled_sigs
            if sig is not None:
                rec.compiled_sigs[sig] = rec.compiled_sigs.get(sig, 0) + 1
            if rec.sealed:
                self.diagnostics.append(Diagnostic(
                    Severity.ERROR, "RETRACE",
                    f"site {rec.name!r} compiled after seal "
                    f"(compile #{rec.compiles}, call #{rec.calls})",
                    vars=(rec.name,)))
            elif seen:
                self.diagnostics.append(Diagnostic(
                    Severity.ERROR, "RETRACE",
                    f"site {rec.name!r} recompiled an already-compiled "
                    f"signature (compile #{rec.compiles}) — weak-type "
                    "flip, dropped cache, or a fresh jit wrapper for the "
                    "same computation", vars=(rec.name,)))

    # ---- test / operator surface ----------------------------------------

    def seal(self, name: Optional[str] = None) -> None:
        """Declare warmup over: any later compile at ``name`` — or, when
        None, at every site including ones first created AFTER the seal
        (lazily built per-bucket jits) — is a RETRACE."""
        with self._lock:
            if name is not None:
                rec = self.sites.get(name)
                if rec is None:
                    rec = self.sites[name] = SiteRecord(name)
                rec.sealed = True
                return
            self._sealed_all = True
            for rec in self.sites.values():
                rec.sealed = True

    def compile_count(self, name: str) -> int:
        rec = self.sites.get(name)
        return rec.compiles if rec is not None else 0

    def call_count(self, name: str) -> int:
        rec = self.sites.get(name)
        return rec.calls if rec is not None else 0

    def assert_budget(self, name: str, max_compiles: int) -> None:
        """Raise :class:`RetraceError` if ``name`` compiled more than
        ``max_compiles`` times (a site that never ran counts 0)."""
        got = self.compile_count(name)
        if got > max_compiles:
            raise RetraceError(
                f"RETRACE: site {name!r} compiled {got} times, budget "
                f"{max_compiles} ({self.call_count(name)} calls)")

    def assert_no_retraces(self) -> None:
        retraces = [d for d in self.diagnostics if d.code == "RETRACE"]
        if retraces:
            raise RetraceError(
                "RETRACE: " + "; ".join(d.message for d in retraces))

    def reset(self) -> None:
        """Zero every counter and unseal.  Records are reset IN PLACE —
        live ``audit_jit`` wrappers hold references to their SiteRecord,
        so replacing the dict would orphan them and every later count
        would silently read 0 while the wrappers kept incrementing the
        discarded records."""
        self.tracer = None
        with self._lock:
            self._sealed_all = False
            for rec in self.sites.values():
                rec.calls = 0
                rec.compiles = 0
                rec.sealed = False
                rec.compiled_sigs.clear()
                rec._pending_sig = None
            self.diagnostics.clear()

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """{site: {calls, compiles, distinct_signatures}} — one dict an
        operator can dump next to serving metrics."""
        with self._lock:
            return {
                name: {"calls": rec.calls, "compiles": rec.compiles,
                       "distinct_signatures": len(rec.compiled_sigs),
                       "sealed": int(rec.sealed)}
                for name, rec in self.sites.items()}


_AUDITOR = RetraceAuditor()


def auditor() -> RetraceAuditor:
    """The process-global auditor all ``audit_jit`` sites report to."""
    return _AUDITOR


def audit_jit(fn, *, site: str, **jit_kwargs):
    """``jax.jit`` with retrace accounting under ``FLAGS.jit_audit``.

    With the flag off this IS ``jax.jit(fn, **jit_kwargs)`` — zero
    overhead, zero behavior change.  With it on, every call records its
    abstract signature and every actual trace of ``fn`` counts as a
    compile at ``site`` (jax only executes the python body when
    tracing, so the count is exact, not inferred from signatures).
    """
    import jax

    from paddle_tpu.platform.flags import FLAGS

    if not getattr(FLAGS, "jit_audit", False):
        return jax.jit(fn, **jit_kwargs)

    rec = _AUDITOR.site(site)

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        _AUDITOR._on_compile(rec)
        return fn(*args, **kwargs)

    jitted = jax.jit(traced, **jit_kwargs)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        _AUDITOR._on_call(rec, abstract_signature(args, kwargs))
        return jitted(*args, **kwargs)

    wrapper._audit_site = site
    return wrapper
