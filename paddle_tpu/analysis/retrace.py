"""Jit retrace auditor: catch silent XLA recompilation on hot paths.

A TPU serving/training step that quietly retraces erases the wins the
fused step bought — the failure is invisible (everything still returns
the right numbers) and shows up only as mystery latency.  This module
wraps the repo's jit call sites (:func:`audit_jit` replaces a bare
``jax.jit``) and records, per named *site*:

- every **call** with its abstract signature (shape/dtype/weak-type of
  each array leaf; python scalars by type+value, since jax specializes
  on them via weak types or static closure);
- every **compile** — detected exactly, by counting executions of the
  wrapped python body, which jax only runs when tracing.

Two things are flagged as ``RETRACE`` diagnostics:

- a compile for a signature this site has ALREADY compiled (the classic
  silent retrace: weak-type flips, a dropped compilation cache, a new
  wrapper identity for the same computation);
- any compile after the site was **sealed** (``auditor().seal()`` after
  warmup): steady state must not compile at all.

The whole thing is gated on ``FLAGS.jit_audit`` *at wrap time*: with the
flag off (the default) ``audit_jit`` returns a bare ``jax.jit`` and
costs nothing.  Turn the flag on BEFORE constructing the engine/trainer
whose sites you want audited.

Budget assertions for tests::

    FLAGS.jit_audit = True
    eng = ServingEngine(...)
    ... run warmup traffic ...
    auditor().seal()                      # steady state begins
    ... run steady-state traffic ...
    auditor().assert_budget("serving.step", 3)   # one compile per
    #                                 (decode_bucket, prefill_bucket) pair
    auditor().assert_no_retraces()

Assertion failures carry the literal token ``RETRACE`` so CI wrappers
can grep for it, same as the PAGE-LEAK / REF-LEAK contracts.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["audit_jit", "auditor", "CapturedCall", "RetraceAuditor",
           "RetraceError", "SiteContract", "abstract_signature",
           "declare_site"]


@dataclass(frozen=True)
class SiteContract:
    """Declared compiled-path contract for an ``audit_jit`` site,
    checked by the jaxpr auditor (:mod:`paddle_tpu.analysis.xla`) —
    the budget/donation declarations live NEXT TO the jit call so the
    contract and the code it binds cannot drift apart.

    - ``donate``: positional argnums that MUST appear in the jit's
      *requested* ``donate_argnums``.  Checked against the requested
      kwargs, not the backend behavior, so a CPU tier-1 run still
      verifies the TPU donation contract (CPU cannot donate; see
      :func:`audit_jit`'s backend strip).
    - ``per_tick``: this site runs on the serving hot path — host
      callbacks and collectives inside it are ERRORs, not INFO.
    - ``allow_collectives``: collectives are the POINT of this site
      (ZeRO placement, sharded train steps) — report INFO, never ERROR.
    - ``allow_upcast``: source dtype names ("bfloat16", "int8") whose
      promotion into f32 matmuls/reductions is intentional (the
      int8-dequant path, f32 loss/norm reductions under use_bf16,
      attn_pv_f32) — anything else narrow feeding an f32 sink is drift.
    - ``peak_bytes`` / ``flops``: per-signature budgets for the
      abstract live-set / FLOP estimator; None = unbudgeted.
    - ``big_arg_bytes`` / ``const_bytes``: per-site overrides for the
      donation-candidate and const-capture thresholds (None = the
      ``FLAGS.xla_audit_*`` process defaults).

    Sharding contract (checked by :mod:`paddle_tpu.analysis.sharding`):

    - ``in_specs`` / ``out_specs``: declared ``PartitionSpec``-style
      placements, one tuple entry per positional argument / flattened
      output — each entry None (undeclared), ``()`` (replicated) or a
      tuple of per-dim mesh-axis names aligned to the LEADING dims
      (``("data",)`` = dim 0 sharded over ``data``).  A length-1 tuple
      broadcasts to every argument/output.  A spec applies to an array
      leaf only when the leaf has enough dims and every sharded dim
      divides by the axis size; other leaves are treated replicated.
    - ``mesh_axes``: ``((axis_name, size), ...)`` — the mesh the specs
      refer to, so the static walk can cost collectives without a live
      mesh object.
    - ``comm_bytes``: per-signature budget for the estimated collective
      bytes moved over the interconnect (the 2112.09017 cost model);
      None = unbudgeted (the estimate is reported INFO).
    - ``expect_sharded``: argnums that MUST carry at least one mesh
      axis in their effective input spec — a weight the plan shards
      arriving replicated is the accidental-replication failure.
    """

    donate: Tuple[int, ...] = ()
    per_tick: bool = False
    allow_collectives: bool = False
    allow_upcast: Tuple[str, ...] = ()
    peak_bytes: Optional[int] = None
    flops: Optional[float] = None
    big_arg_bytes: Optional[int] = None
    const_bytes: Optional[int] = None
    in_specs: Optional[Tuple] = None
    out_specs: Optional[Tuple] = None
    mesh_axes: Tuple[Tuple[str, int], ...] = ()
    comm_bytes: Optional[float] = None
    expect_sharded: Tuple[int, ...] = ()


class RetraceError(AssertionError):
    """A compile-budget or no-retrace assertion failed.  The message
    always contains the literal token ``RETRACE``."""


def abstract_signature(args: Tuple, kwargs: Dict) -> Tuple:
    """Hashable abstract signature of a call: array leaves collapse to
    (shape, dtype, weak_type) — plus the mesh/PartitionSpec for arrays
    committed to a NamedSharding, since jax.jit keys its cache on input
    shardings too: a TP engine and a replicated engine sharing one site
    legitimately compile the same shapes twice, which must not read as
    a same-signature retrace.  Uncommitted/single-device arrays (no
    ``.spec``) are unaffected.  Non-array leaves keep type+repr (they
    are trace-time constants, so a changed value IS a changed
    program)."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            weak = bool(getattr(x, "weak_type", False))
            sig = ("arr", tuple(x.shape), str(x.dtype), weak)
            sh = getattr(x, "sharding", None)
            spec = getattr(sh, "spec", None)
            mesh = getattr(sh, "mesh", None)
            if spec is not None and mesh is not None:
                try:
                    sig += (str(tuple(spec)),
                            tuple((str(a), int(n))
                                  for a, n in dict(mesh.shape).items()))
                except Exception:
                    pass
            return sig
        return ("const", type(x).__name__, repr(x))

    leaves, treedef = jax.tree.flatten((args, kwargs))
    return (str(treedef),) + tuple(leaf(x) for x in leaves)


@dataclass
class CapturedCall:
    """One audited signature, self-contained for the jaxpr auditor:
    the RAW python callable that traced it, the *requested* jit kwargs
    (donation contract intact even where the backend strips it), the
    :class:`SiteContract` declared at that wrap, and the abstract
    ``(args, kwargs)`` (array leaves collapsed to
    ``jax.ShapeDtypeStruct`` — the ARGS hold no device buffers, so
    donation is unaffected).  Note the raw callable itself may close
    over its owner (the engine's step closes over the engine, KV pool
    included), so audit mode keeps wrapped owners alive while their
    captures exist — ``auditor().reset()`` clears captures AND the
    per-site fn references, which is the reclamation path for a
    long-running audited fleet that replaces replicas.  Carried PER
    CAPTURE, not per site: two engines sharing a site name (a
    heterogeneous fleet, two engines in one test) wrap different
    closures, and each signature must replay through the closure that
    actually traced it."""

    fn: Callable
    jit_kwargs: Dict[str, object]
    contract: Optional[SiteContract]
    args: Tuple
    kwargs: Dict


@dataclass
class SiteRecord:
    """Per-site call/compile history, plus — under ``FLAGS.jit_audit``
    — one :class:`CapturedCall` per distinct signature for the jaxpr
    auditor.  ``jit_kwargs``/``contract`` mirror the LATEST wrap at
    this site (the inspection/scrape convenience); the auditor reads
    the per-capture copies."""

    name: str
    calls: int = 0
    compiles: int = 0
    sealed: bool = False
    # signature -> number of compiles it triggered (>=2 means a retrace
    # happened even without sealing)
    compiled_sigs: Dict[Tuple, int] = field(default_factory=dict)
    _pending_sig: Optional[Tuple] = None
    fn: Optional[Callable] = None
    jit_kwargs: Dict[str, object] = field(default_factory=dict)
    contract: Optional[SiteContract] = None
    captured: Dict[Tuple, CapturedCall] = field(default_factory=dict)
    # stamped by the sharding auditor (max estimated collective bytes
    # per call across audited signatures); published as
    # ``comm_bytes_total{site=...}`` next to the compile counters
    comm_bytes: Optional[float] = None


class RetraceAuditor:
    """Registry of audited sites + the RETRACE diagnostics they raised.

    Thread-safe enough for the repo's usage (sites are created at
    construction time; counters mutate under one lock)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sites: Dict[str, SiteRecord] = {}          # guarded_by(_lock)
        self.diagnostics: List[Diagnostic] = []         # guarded_by(_lock)
        self._sealed_all = False                        # guarded_by(_lock)
        # obs hook: when attached (ServingEngine.set_tracer does it for
        # an enabled tracer under FLAGS.jit_audit), every compile lands
        # on the trace timeline as a `jit_compile` instant — so a chaos
        # replay shows WHERE the compile spikes sit between the request
        # spans.  None = no tracing, zero overhead.  Rebinding a single
        # reference is atomic and readers tolerate either value, so the
        # tracer hook stays lock-free by design (unlike the counters).
        self.tracer = None

    def attach_tracer(self, tracer) -> None:
        """Report each compile to an obs tracer (last attach wins; the
        auditor is process-global, so a fleet attaches its shared base
        tracer once).  Cleared by :meth:`reset`."""
        self.tracer = tracer

    # ---- bookkeeping (called by audit_jit wrappers) ----------------------

    def site(self, name: str) -> SiteRecord:
        with self._lock:
            rec = self.sites.get(name)
            if rec is None:
                # a site first seen AFTER a global seal() is born sealed:
                # "steady state must not compile" has to cover lazily
                # created jits (per-bucket prefill/chunk wrappers) too
                rec = self.sites[name] = SiteRecord(
                    name, sealed=self._sealed_all)
            return rec

    def _on_call(self, rec: SiteRecord, sig: Tuple,
                 capture: Optional[Callable[[], "CapturedCall"]] = None
                 ) -> None:
        with self._lock:
            rec.calls += 1
            rec._pending_sig = sig
            if capture is not None and sig not in rec.captured:
                rec.captured[sig] = capture()

    def _on_compile(self, rec: SiteRecord) -> None:
        if self.tracer is not None:
            self.tracer.instant("jit_compile", cat="compile",
                                site=rec.name)
        with self._lock:
            rec.compiles += 1
            sig = rec._pending_sig
            seen = sig is not None and sig in rec.compiled_sigs
            if sig is not None:
                rec.compiled_sigs[sig] = rec.compiled_sigs.get(sig, 0) + 1
            if rec.sealed:
                self.diagnostics.append(Diagnostic(
                    Severity.ERROR, "RETRACE",
                    f"site {rec.name!r} compiled after seal "
                    f"(compile #{rec.compiles}, call #{rec.calls})",
                    vars=(rec.name,)))
            elif seen:
                self.diagnostics.append(Diagnostic(
                    Severity.ERROR, "RETRACE",
                    f"site {rec.name!r} recompiled an already-compiled "
                    f"signature (compile #{rec.compiles}) — weak-type "
                    "flip, dropped cache, or a fresh jit wrapper for the "
                    "same computation", vars=(rec.name,)))

    # ---- test / operator surface ----------------------------------------

    def seal(self, name: Optional[str] = None) -> None:
        """Declare warmup over: any later compile at ``name`` — or, when
        None, at every site including ones first created AFTER the seal
        (lazily built per-bucket jits) — is a RETRACE."""
        with self._lock:
            if name is not None:
                rec = self.sites.get(name)
                if rec is None:
                    rec = self.sites[name] = SiteRecord(name)
                rec.sealed = True
                return
            self._sealed_all = True
            for rec in self.sites.values():
                rec.sealed = True

    def compile_count(self, name: str) -> int:
        # under the lock like every other sites reader: a budget assert
        # racing a lazily-created site (per-bucket jit on another
        # thread) must never read the dict mid-insert
        with self._lock:
            rec = self.sites.get(name)
            return rec.compiles if rec is not None else 0

    def call_count(self, name: str) -> int:
        with self._lock:
            rec = self.sites.get(name)
            return rec.calls if rec is not None else 0

    def assert_budget(self, name: str, max_compiles: int) -> None:
        """Raise :class:`RetraceError` if ``name`` compiled more than
        ``max_compiles`` times (a site that never ran counts 0)."""
        got = self.compile_count(name)
        if got > max_compiles:
            raise RetraceError(
                f"RETRACE: site {name!r} compiled {got} times, budget "
                f"{max_compiles} ({self.call_count(name)} calls)")

    def assert_no_retraces(self) -> None:
        with self._lock:
            retraces = [d for d in self.diagnostics
                        if d.code == "RETRACE"]
        if retraces:
            raise RetraceError(
                "RETRACE: " + "; ".join(d.message for d in retraces))

    def reset(self) -> None:
        """Zero every counter and unseal.  Records are reset IN PLACE —
        live ``audit_jit`` wrappers hold references to their SiteRecord,
        so replacing the dict would orphan them and every later count
        would silently read 0 while the wrappers kept incrementing the
        discarded records.  Captures AND the per-site fn/kwargs
        references are dropped too: the captured closures can pin their
        owning engine (KV pool included), so reset() is also the memory
        reclamation path — live wrappers re-capture on their next call.
        """
        self.tracer = None
        with self._lock:
            self._sealed_all = False
            for rec in self.sites.values():
                rec.calls = 0
                rec.compiles = 0
                rec.sealed = False
                rec.compiled_sigs.clear()
                rec._pending_sig = None
                rec.captured.clear()
                rec.fn = None
                rec.jit_kwargs = {}
                rec.contract = None
                rec.comm_bytes = None
            self.diagnostics.clear()

    def publish(self, registry, **labels) -> None:
        """Land per-site compile/call counts on a unified
        :class:`~paddle_tpu.obs.registry.MetricsRegistry` as
        ``jit_compiles_total{site=...}`` / ``jit_calls_total{site=...}``
        — before this, compiles existed only as ``jit_compile`` trace
        instants (:meth:`attach_tracer`), invisible to a Prometheus
        scraper.  ``ServingEngine.healthz`` calls it whenever the
        auditor has sites, so the engine's scrape surface carries the
        compile ladder next to the serving counters."""
        with self._lock:
            counts = [(name, rec.calls, rec.compiles, rec.comm_bytes)
                      for name, rec in self.sites.items()]
        compiles = registry.gauge(
            "jit_compiles_total",
            "cumulative XLA compiles per audited jit site")
        calls = registry.gauge(
            "jit_calls_total", "cumulative calls per audited jit site")
        comm = None
        for name, n_calls, n_compiles, n_comm in counts:
            compiles.labels(site=name, **labels).set(n_compiles)
            calls.labels(site=name, **labels).set(n_calls)
            if n_comm is not None:
                # sharding-audit estimate: collective bytes per call at
                # this site (lazy gauge: only exists once an audit ran)
                if comm is None:
                    comm = registry.gauge(
                        "comm_bytes_total",
                        "estimated collective bytes per call at each "
                        "audited jit site (paddle_tpu.analysis sharding)")
                comm.labels(site=name, **labels).set(n_comm)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """{site: {calls, compiles, distinct_signatures}} — one dict an
        operator can dump next to serving metrics."""
        with self._lock:
            return {
                name: {"calls": rec.calls, "compiles": rec.compiles,
                       "distinct_signatures": len(rec.compiled_sigs),
                       "sealed": int(rec.sealed)}
                for name, rec in self.sites.items()}


_AUDITOR = RetraceAuditor()


def auditor() -> RetraceAuditor:
    """The process-global auditor all ``audit_jit`` sites report to."""
    return _AUDITOR


def declare_site(name: str, contract: SiteContract) -> SiteRecord:
    """Register a contract-bearing site WITHOUT wrapping a jit — for
    sites whose compiled path does not exist yet (the pipeline/MoE
    stubs).  A declared site that captures nothing makes the sharding
    auditor print its loud 'contract NOT audited' notice instead of
    silently skipping the site, so the build-out starts checkable.
    Re-declaring an existing site only updates its contract."""
    rec = _AUDITOR.site(name)
    rec.contract = contract
    return rec


def _backend_jit_kwargs(jit_kwargs: Dict) -> Dict:
    """Donation is a CONTRACT declaration even on backends that cannot
    honor it: strip ``donate_argnums``/``donate_argnames`` before the
    underlying ``jax.jit`` on CPU (which would only warn and ignore
    them), so call sites declare the TPU donation contract
    unconditionally and tier-1 CPU runs stay warning-free while the
    jaxpr auditor checks the *requested* kwargs — the old per-backend
    gate in the engine left donation contracts untested under tier-1."""
    if not (jit_kwargs.get("donate_argnums")
            or jit_kwargs.get("donate_argnames")):
        return jit_kwargs
    import jax

    if jax.default_backend() != "cpu":
        return jit_kwargs
    kw = dict(jit_kwargs)
    kw.pop("donate_argnums", None)
    kw.pop("donate_argnames", None)
    return kw


def _abstract_call(args: Tuple, kwargs: Dict) -> Tuple:
    """(args, kwargs) with array leaves collapsed to ShapeDtypeStruct —
    re-traceable through jax.make_jaxpr without holding device buffers
    (a donated arg must not be kept alive by the audit capture)."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x

    return jax.tree.map(leaf, (args, kwargs))


def audit_jit(fn, *, site: str, xla_contract: Optional[SiteContract] = None,
              **jit_kwargs):
    """``jax.jit`` with retrace accounting under ``FLAGS.jit_audit``.

    With the flag off this IS ``jax.jit(fn, **jit_kwargs)`` — zero
    overhead, zero behavior change (modulo the CPU donation strip,
    which only removes a warning).  With it on, every call records its
    abstract signature and every actual trace of ``fn`` counts as a
    compile at ``site`` (jax only executes the python body when
    tracing, so the count is exact, not inferred from signatures); the
    site also captures one abstract ``(args, kwargs)`` per signature
    plus the requested jit kwargs, which is everything the jaxpr
    auditor (``python -m paddle_tpu.analysis xla``) needs to
    re-materialize and rule-check the compiled program.

    ``xla_contract`` declares the site's compiled-path contract
    (:class:`SiteContract`: donation, budgets, allowlists) right next
    to the jit call; it is inert unless the auditor runs.
    """
    import jax

    from paddle_tpu.platform.flags import FLAGS

    if not getattr(FLAGS, "jit_audit", False):
        return jax.jit(fn, **_backend_jit_kwargs(jit_kwargs))

    rec = _AUDITOR.site(site)
    rec.fn = fn
    rec.jit_kwargs = dict(jit_kwargs)        # REQUESTED, pre-strip
    if xla_contract is not None:
        rec.contract = xla_contract

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        _AUDITOR._on_compile(rec)
        return fn(*args, **kwargs)

    jitted = jax.jit(traced, **_backend_jit_kwargs(jit_kwargs))

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        def capture() -> CapturedCall:
            a, k = _abstract_call(args, kwargs)
            return CapturedCall(fn=fn, jit_kwargs=dict(jit_kwargs),
                                contract=xla_contract, args=a, kwargs=k)

        _AUDITOR._on_call(rec, abstract_signature(args, kwargs),
                          capture=capture)
        return jitted(*args, **kwargs)

    wrapper._audit_site = site
    return wrapper
