"""Structured diagnostics shared by all three analysis passes.

One flat record type instead of per-pass ad-hoc tuples, so the CLIs, the
inline ``Executor.run`` hook, and the tests all consume the same shape.
Severity ordering matters: ``ERROR`` is "this program cannot run (or the
invariant is violated)", ``WARNING`` is "suspicious but executable",
``INFO`` is context.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class Severity(enum.IntEnum):
    """Ordered so ``max(diags, key=severity)`` and threshold filters work."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "ERROR", not "Severity.ERROR"
        return self.name


@dataclass(frozen=True)
class Diagnostic:
    """One finding.

    ``code`` is the stable machine-readable class ("def-before-use",
    "shape-mismatch", "RETRACE", a lint rule name, ...); ``where`` is a
    human location — ``block 0 op 3`` for program checks, ``path:line``
    for lint, the site name for retrace findings.  ``vars`` names the
    variables (or symbols) involved so tooling can link back into the
    program without re-parsing the message.
    """

    severity: Severity
    code: str
    message: str
    block_idx: Optional[int] = None
    op_idx: Optional[int] = None
    vars: Tuple[str, ...] = field(default=())

    @property
    def where(self) -> str:
        if self.block_idx is None:
            return ""
        if self.op_idx is None:
            return f"block {self.block_idx}"
        return f"block {self.block_idx} op {self.op_idx}"

    def __str__(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.severity} {self.code}{loc}: {self.message}"


def errors(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity is Severity.ERROR]


def format_report(diags: Sequence[Diagnostic], title: str = "") -> str:
    """Multi-line report, most severe first (stable within a severity)."""
    lines: List[str] = []
    if title:
        lines.append(title)
    for d in sorted(diags, key=lambda d: -int(d.severity)):
        lines.append(f"  {d}")
    if not diags:
        lines.append("  (no diagnostics)")
    return "\n".join(lines)
