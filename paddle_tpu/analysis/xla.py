"""Jaxpr-level compiled-path auditor: does what gets COMPILED match
what the site declared?

PR 5's verifier checks the *fluid program* layer and the retrace
auditor counts *how often* we compile — this module inspects *what*
gets compiled: the ``ClosedJaxpr`` behind every named ``audit_jit``
site (``serving.step``, the trainer steps, the ZeRO placement jits).
That is where a silently dropped ``donate_argnums``, a bf16→f32
promotion, or an accidentally const-captured weight array costs real
HBM and MFU while every number still comes out right.

Each site's :class:`~paddle_tpu.analysis.retrace.SiteRecord` (under
``FLAGS.jit_audit``) captures one abstract ``(args, kwargs)`` per
compiled signature plus the *requested* jit kwargs and the
:class:`~paddle_tpu.analysis.retrace.SiteContract` declared next to
the jit call.  The auditor re-materializes each signature's jaxpr via
``jax.make_jaxpr`` and runs a rule registry over it:

- **donation-contract** — every argnum the contract declares donatable
  must appear in the requested ``donate_argnums`` (requested, not
  backend-effective: CPU tier-1 runs still verify the TPU contract)
  and be alias-eligible (some output aval matches each donated leaf);
  any large non-donated argument whose avals all match outputs is a
  donation candidate (the caller overwrites it, so XLA pays a copy).
- **dtype-promotion-drift** — the walk seeds every input with its
  declared dtype and flags narrow operands (bf16/f16/int8) silently
  promoted into f32 matmuls/reductions; ``contract.allow_upcast``
  sanctions the intentional paths (int8 dequant, f32 loss/norm
  reductions under use_bf16, ``attn_pv_f32``).
- **host-transfer** — ``pure_callback``/``io_callback``/
  ``debug_callback``/infeed/outfeed eqns: ERROR inside ``per_tick``
  serving sites (one host sync per tick is the documented budget and
  it happens OUTSIDE the compiled step), INFO elsewhere.
- **const-capture** — arrays above a byte threshold baked into the
  executable as jaxpr consts instead of arguments: re-baked on every
  compile, duplicated per specialization, and invisible to donation.
- **collective-placement** — ``psum``/``all_gather``/... eqns: ERROR
  in single-replica ``per_tick`` sites, INFO where the contract says
  collectives are the point (ZeRO, sharded train steps).
- **budget** — an abstract live-set/FLOP estimate per signature
  (:func:`estimate_jaxpr`), checked against the ``peak_bytes`` /
  ``flops`` budgets declared next to the ``audit_jit`` call.

Findings are structured :class:`Diagnostic`\\ s whose code is the
grep-able ``XLA-AUDIT`` tag and whose message names the rule, site and
eqn.  ``python -m paddle_tpu.analysis xla`` drives a sealed mixed
serving steady-state run (int8 KV, prefix cache on) plus one trainer
step, audits every captured site, and exits 1 on findings / 2 on a
crash — ``tools_tier1.sh`` turns that into ladder exit 8.

Estimator semantics (documented approximations, all upper-bound
flavored): peak bytes is a linear live-variable scan that ignores
donation aliasing and rematerialization; nested jaxprs (pjit / scan /
cond / shard_map) contribute ``max(inner peak, outer live)``; scan
FLOPs multiply by the trip count, while_loops count one trip; conv
FLOPs use the dense upper bound.  Budgets are guardrails against
asymptotic surprises (an O(B·S²) broadcast, a duplicated pool), not
cycle-accurate predictions — declare them with slack.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity
from paddle_tpu.analysis.retrace import (CapturedCall, SiteContract,
                                         SiteRecord, auditor)

__all__ = ["audit_sites", "audit_record", "estimate_jaxpr", "SiteReport",
           "RULES", "drive_serving_steady_state",
           "drive_serving_spec_steady_state", "drive_trainer_step",
           "run_compiled_path_audit"]

TAG = "XLA-AUDIT"

_DEFAULT_CONTRACT = SiteContract()

_NARROW = {"bfloat16", "float16", "int8", "uint8"}
_DRIFT_SINKS = {"dot_general", "conv_general_dilated", "reduce_sum",
                "reduce_prod"}
_CALLBACKS = {"pure_callback", "io_callback", "debug_callback", "callback",
              "infeed", "outfeed"}
_COLLECTIVES = {"psum", "psum2", "all_gather", "all_gather_invariant",
                "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                "reduce_scatter", "all_reduce"}


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import numpy as np

    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:           # symbolic dim: count as 1
            pass
    return n * np.dtype(dtype).itemsize


def _aval_key(aval) -> Tuple:
    return (tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "?")))


def _sub_jaxprs(eqn) -> List:
    """Closed sub-jaxprs of an eqn (pjit, scan, while, cond branches,
    custom_* calls, shard_map) as (ClosedJaxpr-or-Jaxpr) values."""
    import jax

    out = []

    def add(v):
        if isinstance(v, jax.core.ClosedJaxpr):
            out.append(v)
        elif isinstance(v, jax.core.Jaxpr):
            out.append(jax.core.ClosedJaxpr(v, ()))

    for v in eqn.params.values():
        add(v)
        if isinstance(v, (list, tuple)):
            for x in v:
                add(x)
    return out


def _iter_eqns(closed, path: str = ""):
    """Yield (eqn, path) depth-first across nested jaxprs; ``path`` is
    the dotted eqn index ("3.1" = eqn 1 inside eqn 3's sub-jaxpr)."""
    for i, eqn in enumerate(closed.jaxpr.eqns):
        here = f"{path}{i}"
        yield eqn, here
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub, path=f"{here}.")


def materialize_jaxpr(cap: CapturedCall):
    """Re-trace one captured signature through the raw callable that
    ACTUALLY traced it (each capture carries its own closure — two
    engines sharing a site name wrap different closures).
    ``make_jaxpr`` traces the raw fn (NOT the counting wrapper), so
    materialization never pollutes the compile counts; static jit
    kwargs (out_shardings, donation) do not change the traced
    program."""
    import jax

    return jax.make_jaxpr(cap.fn)(*cap.args, **cap.kwargs)


# ---------------------------------------------------------------------------
# live-set / FLOP estimator
# ---------------------------------------------------------------------------


def _dot_general_flops(eqn) -> float:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = 1
    for d in lb:
        batch *= int(lhs[d])
    contract = 1
    for d in lc:
        contract *= int(lhs[d])
    m = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= int(d)
    n = 1
    for i, d in enumerate(rhs):
        if i not in rc and i not in _rb:
            n *= int(d)
    return 2.0 * batch * m * n * contract


def _elems(aval) -> float:
    n = 1
    for d in getattr(aval, "shape", ()):
        try:
            n *= int(d)
        except TypeError:
            pass
    return float(n)


def estimate_jaxpr(closed) -> Tuple[int, float]:
    """(peak_live_bytes, total_flops) of one ClosedJaxpr — a linear
    abstract walk: every var costs ``prod(shape) * itemsize`` from its
    definition to its last use (donation aliasing ignored, so the
    estimate upper-bounds a donating executable); FLOPs are exact for
    ``dot_general``, input-sized for reductions, output-sized for
    everything elementwise, dense-upper-bound for conv, and nested
    jaxprs fold in as described in the module doc."""
    import jax

    jaxpr = closed.jaxpr
    last_use: Dict[int, int] = {}
    n_eqns = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        if not isinstance(v, jax.core.Literal):
            last_use[id(v)] = n_eqns

    live: Dict[int, int] = {}
    for v in list(jaxpr.constvars) + list(jaxpr.invars):
        live[id(v)] = _aval_bytes(v.aval)
    cur = sum(live.values())
    peak = cur
    flops = 0.0
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        subs = _sub_jaxprs(eqn)
        if subs:
            inner = [estimate_jaxpr(s) for s in subs]
            inner_peak = max(p for p, _ in inner)
            inner_flops = sum(f for _, f in inner)
            if name == "scan":
                inner_flops *= max(1, int(eqn.params.get("length", 1)))
            elif name == "cond":
                inner_flops = max(f for _, f in inner)
            flops += inner_flops
            peak = max(peak, cur + inner_peak)
        elif name == "dot_general":
            flops += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            # dense upper bound: every output element pays the whole
            # kernel (2 * out * rhs_elems / out_channels would need the
            # dimension_numbers dance; the bound is what budgets want)
            flops += 2.0 * _elems(eqn.outvars[0].aval) \
                * _elems(eqn.invars[1].aval)
        elif name.startswith("reduce_") or name in ("argmax", "argmin"):
            flops += _elems(eqn.invars[0].aval)
        else:
            flops += sum(_elems(o.aval) for o in eqn.outvars)
        for o in eqn.outvars:
            b = _aval_bytes(o.aval)
            live[id(o)] = b
            cur += b
        peak = max(peak, cur)
        dying = {id(v) for v in eqn.invars
                 if not isinstance(v, jax.core.Literal)}
        for vid in dying:
            if last_use.get(vid) == i and vid in live:
                cur -= live.pop(vid)
    return int(peak), flops


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _diag(sev: Severity, rule: str, site: str, msg: str,
          where: str = "") -> Diagnostic:
    loc = f" eqn {where}" if where else ""
    return Diagnostic(sev, TAG, f"[{rule}] site {site!r}{loc}: {msg}",
                      vars=(site, rule))


def _flat_avals(x) -> List[Tuple]:
    """Aval keys of every array leaf of one argument pytree."""
    import jax

    out = []
    for leaf in jax.tree.leaves(x):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append(_aval_key(leaf))
    return out


def _leaf_bytes(x) -> int:
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(x):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            n = 1
            for d in leaf.shape:
                n *= int(d)
            total += n * np.dtype(leaf.dtype).itemsize
    return total


def _big_arg_threshold(contract: SiteContract) -> int:
    if contract.big_arg_bytes is not None:
        return int(contract.big_arg_bytes)
    from paddle_tpu.platform.flags import FLAGS

    return int(FLAGS.xla_audit_big_arg_bytes)


def _const_threshold(contract: SiteContract) -> int:
    if contract.const_bytes is not None:
        return int(contract.const_bytes)
    from paddle_tpu.platform.flags import FLAGS

    return int(FLAGS.xla_audit_const_bytes)


def _rule_donation(site, closed, call, jit_kwargs, contract,
                   est) -> List[Diagnostic]:
    args, _kwargs = call
    donate = jit_kwargs.get("donate_argnums", ()) or ()
    if isinstance(donate, int):
        donate = (donate,)
    donate = set(int(d) for d in donate)
    out: List[Diagnostic] = []
    # multiset of output avals, consumed as donated/candidate args match
    remaining: Dict[Tuple, int] = {}
    for aval in closed.out_avals:
        k = _aval_key(aval)
        remaining[k] = remaining.get(k, 0) + 1

    def consume(keys) -> bool:
        taken = []
        for k in keys:
            if remaining.get(k, 0) <= 0:
                for t in taken:
                    remaining[t] += 1
                return False
            remaining[k] -= 1
            taken.append(k)
        return True

    for argnum in contract.donate:
        if argnum >= len(args):
            continue
        if argnum not in donate:
            out.append(_diag(
                Severity.ERROR, "donation-contract", site,
                f"arg {argnum} is declared donatable by the site "
                f"contract but absent from the requested donate_argnums="
                f"{tuple(sorted(donate))} — the compiled step copies it "
                "instead of updating in place (peak HBM doubles the "
                "documented cost)"))
            continue
        if not consume(_flat_avals(args[argnum])):
            out.append(_diag(
                Severity.WARNING, "donation-contract", site,
                f"arg {argnum} is donated but not alias-eligible: no "
                "unclaimed output aval matches every donated leaf, so "
                "XLA silently drops the donation"))
    big = _big_arg_threshold(contract)
    for i, a in enumerate(args):
        if i in donate or i in contract.donate:
            continue
        keys = _flat_avals(a)
        if not keys or _leaf_bytes(a) < big:
            continue
        if consume(keys):
            out.append(_diag(
                Severity.WARNING, "donation-contract", site,
                f"arg {i} ({_leaf_bytes(a)} bytes) aval-matches the "
                "outputs but is not donated — if the caller overwrites "
                "it with the result (the repo's step idiom), donating "
                "saves a full copy"))
    return out


def _rule_dtype_drift(site, closed, call, jit_kwargs, contract,
                      est) -> List[Diagnostic]:
    import jax

    allow = set(contract.allow_upcast)
    out: List[Diagnostic] = []
    seen: set = set()                      # (origin, prim): dedupe spam

    def walk(sub, origin: Dict[int, str], path: str):
        for i, eqn in enumerate(sub.jaxpr.eqns):
            here = f"{path}{i}"
            name = eqn.primitive.name
            # origin per POSITION over the FULL invar list (Literals
            # slot in as None) — sub-jaxpr invars align positionally
            # with eqn.invars, so filtering literals first would shift
            # every origin onto the wrong inner operand
            in_orig = [None if isinstance(v, jax.core.Literal)
                       else origin.get(id(v)) for v in eqn.invars]
            if name == "convert_element_type":
                v0 = eqn.invars[0]
                src_dt = str(v0.aval.dtype) if hasattr(v0, "aval") else "?"
                src = in_orig[0] or src_dt
                dst_dt = str(eqn.outvars[0].aval.dtype)
                if dst_dt == "float32" and src in _NARROW \
                        and src not in allow:
                    origin[id(eqn.outvars[0])] = src
                continue
            if name in _DRIFT_SINKS:
                for o in in_orig:
                    if o and (o, name) not in seen:
                        seen.add((o, name))
                        out.append(_diag(
                            Severity.ERROR, "dtype-promotion-drift", site,
                            f"{o} operand silently upcast to f32 feeds "
                            f"{name} — the narrow dtype's memory/MXU "
                            "saving is spent without being declared; "
                            "allowlist an intentional path via "
                            f"SiteContract(allow_upcast=({o!r},))",
                            where=f"{here} ({name})"))
                continue
            subs = _sub_jaxprs(eqn)
            if subs:
                for s in subs:
                    inner: Dict[int, str] = {}
                    ivars = s.jaxpr.invars
                    for v, o in zip(ivars, in_orig[-len(ivars):]):
                        if o:
                            inner[id(v)] = o
                    walk(s, inner, path=f"{here}.")
                continue
            # elementwise/structural f32 ops carry the origin forward
            # (the dequant mul, gathers, reshapes) so the sink check
            # sees through them
            carried = next((o for o in in_orig if o), None)
            if carried:
                for o in eqn.outvars:
                    if str(getattr(o.aval, "dtype", "")) == "float32":
                        origin[id(o)] = carried

    seed: Dict[int, str] = {}
    for v in closed.jaxpr.invars:
        dt = str(getattr(v.aval, "dtype", ""))
        if dt in _NARROW and dt not in allow:
            seed[id(v)] = dt
    walk(closed, seed, "")
    return out


def _rule_host_transfer(site, closed, call, jit_kwargs, contract,
                        est) -> List[Diagnostic]:
    sev = Severity.ERROR if contract.per_tick else Severity.INFO
    out = []
    for eqn, path in _iter_eqns(closed):
        name = eqn.primitive.name
        if name in _CALLBACKS or "callback" in name:
            out.append(_diag(
                sev, "host-transfer", site,
                f"{name} crosses the host boundary inside the compiled "
                "step" + (" — a per-tick serving site budgets exactly "
                          "one host sync per tick, OUTSIDE the jit"
                          if contract.per_tick else ""),
                where=f"{path} ({name})"))
    return out


def _rule_const_capture(site, closed, call, jit_kwargs, contract,
                        est) -> List[Diagnostic]:
    import numpy as np

    limit = _const_threshold(contract)
    out = []

    def check(sub, path):
        for cv, c in zip(sub.jaxpr.constvars, sub.consts):
            nbytes = getattr(c, "nbytes", None)
            if nbytes is None:
                try:
                    nbytes = np.asarray(c).nbytes
                except Exception:
                    continue
            if nbytes > limit:
                shape = tuple(getattr(c, "shape", ()))
                dtype = getattr(c, "dtype", "?")
                out.append(_diag(
                    Severity.ERROR, "const-capture", site,
                    f"{shape} {dtype} ({nbytes} bytes) captured as a "
                    "jaxpr const instead of an argument — baked into "
                    "the executable, re-baked on every compile, and "
                    "invisible to donation; pass it through the call",
                    where=path or "consts"))
        for i, eqn in enumerate(sub.jaxpr.eqns):
            for s in _sub_jaxprs(eqn):
                check(s, f"{path}{i}." if path else f"{i}.")

    check(closed, "")
    return out


def _rule_collectives(site, closed, call, jit_kwargs, contract,
                      est) -> List[Diagnostic]:
    out = []
    for eqn, path in _iter_eqns(closed):
        name = eqn.primitive.name
        if name in _COLLECTIVES:
            if contract.per_tick and not contract.allow_collectives:
                out.append(_diag(
                    Severity.ERROR, "collective-placement", site,
                    f"{name} inside a single-replica per-tick site — a "
                    "decode step must not pay interconnect latency per "
                    "token", where=f"{path} ({name})"))
            else:
                out.append(_diag(
                    Severity.INFO, "collective-placement", site,
                    f"{name} (declared intentional for this site)",
                    where=f"{path} ({name})"))
        elif name == "sharding_constraint" and contract.per_tick:
            out.append(_diag(
                Severity.INFO, "collective-placement", site,
                "GSPMD sharding constraint — a resharding point the "
                "partitioner may lower into a collective",
                where=f"{path} ({name})"))
    return out


def _rule_budget(site, closed, call, jit_kwargs, contract,
                 est) -> List[Diagnostic]:
    peak, flops = est
    out = []
    if contract.peak_bytes is not None and peak > contract.peak_bytes:
        out.append(_diag(
            Severity.ERROR, "budget", site,
            f"estimated peak live set {peak} bytes exceeds the declared "
            f"budget {int(contract.peak_bytes)} — an unplanned "
            "allocation (duplicated pool, O(B*S^2) broadcast) grew the "
            "compiled footprint"))
    if contract.flops is not None and flops > contract.flops:
        out.append(_diag(
            Severity.ERROR, "budget", site,
            f"estimated {flops:.3g} FLOPs exceed the declared budget "
            f"{contract.flops:.3g} — the compiled step does "
            "asymptotically more math than the site declared"))
    return out


RULES: Dict[str, Callable] = {
    "donation-contract": _rule_donation,
    "dtype-promotion-drift": _rule_dtype_drift,
    "host-transfer": _rule_host_transfer,
    "const-capture": _rule_const_capture,
    "collective-placement": _rule_collectives,
    "budget": _rule_budget,
}


# ---------------------------------------------------------------------------
# per-site driver
# ---------------------------------------------------------------------------


@dataclass
class SiteReport:
    """Audit result for one site across every captured signature."""

    site: str
    signatures: int = 0
    peak_bytes: int = 0                 # max over signatures
    flops: float = 0.0                  # max over signatures
    eqns: int = 0                       # max over signatures
    diagnostics: List[Diagnostic] = field(default_factory=list)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity is Severity.ERROR]


def audit_record(name: str, rec: SiteRecord,
                 rules: Optional[Sequence[str]] = None) -> SiteReport:
    """Audit every captured signature of one site — each through its
    OWN captured closure/kwargs/contract (falling back to the record's
    latest, then the defaults).  Diagnostics are deduplicated across
    signatures by message (two prefill buckets of the same program
    produce the same finding once)."""
    rep = SiteReport(site=name)
    seen_msgs: set = set()
    for sig, cap in list(rec.captured.items()):
        contract = cap.contract or rec.contract or _DEFAULT_CONTRACT
        closed = materialize_jaxpr(cap)
        est = estimate_jaxpr(closed)
        rep.signatures += 1
        rep.peak_bytes = max(rep.peak_bytes, est[0])
        rep.flops = max(rep.flops, est[1])
        rep.eqns = max(rep.eqns, len(closed.jaxpr.eqns))
        call = (cap.args, cap.kwargs)
        for rname, rule in RULES.items():
            if rules is not None and rname not in rules:
                continue
            for d in rule(name, closed, call, cap.jit_kwargs, contract,
                          est):
                if d.message not in seen_msgs:
                    seen_msgs.add(d.message)
                    rep.diagnostics.append(d)
    return rep


def audit_sites(aud=None, sites: Optional[Sequence[str]] = None,
                rules: Optional[Sequence[str]] = None
                ) -> Dict[str, SiteReport]:
    """Audit every site the (global) retrace auditor captured; returns
    {site: SiteReport}.  Sites with no captures (never called under
    ``FLAGS.jit_audit``) are skipped — there is nothing to audit."""
    aud = aud if aud is not None else auditor()
    out: Dict[str, SiteReport] = {}
    for name, rec in sorted(aud.sites.items()):
        if sites is not None and name not in sites:
            continue
        if not rec.captured:
            continue
        out[name] = audit_record(name, rec, rules=rules)
    return out


# ---------------------------------------------------------------------------
# the driven acceptance run (CLI + clean-run test pins share it)
# ---------------------------------------------------------------------------


def drive_serving_steady_state(kv_dtype: str = "int8", seal: bool = True):
    """Build a small engine and run the canonical mixed steady state
    (int8 KV + prefix cache by default): short decode, a chunked long
    prefill riding the same ticks, a full-cover cache hit exercising
    the COW fork site, and one fault-plan-poisoned request whose FAILED
    scrub exercises the zero_pages site — then seal and replay the same
    pattern so the retrace contract is checked too.  Requires
    ``FLAGS.jit_audit`` on BEFORE the call (audit_jit's wrap-time
    gate).  Returns the engine.
    """
    import jax
    import numpy as np

    from paddle_tpu.serving import DecoderLM, ServingEngine
    from paddle_tpu.serving.faults import FaultPlan

    model = DecoderLM(vocab_size=50, num_layers=2, num_heads=2,
                      head_dim=8, max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    faults = FaultPlan()
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=64, max_pages_per_seq=12, max_slots=4,
                        buckets=(4, 8, 16), prefill_chunk=8,
                        kv_dtype=kv_dtype, faults=faults)
    rng = np.random.RandomState(0)
    shared = rng.randint(2, 50, size=8).tolist()   # two FULL pages

    def mixed_burst(long_len: int):
        eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=12)
        eng.step()
        eng.submit(rng.randint(2, 50, size=long_len).tolist(),
                   max_tokens=8)
        eng.run(max_ticks=300)

    # warmup: every pair bucket + the COW fork compile
    eng.submit(shared, max_tokens=6)
    eng.run(max_ticks=200)
    eng.submit(shared, max_tokens=6)               # full-cover hit: fork
    eng.run(max_ticks=200)
    mixed_burst(20)
    # one poisoned decode: the NaN row fails ONLY that request, whose
    # uncached pages get the device scrub — serving.zero_pages must
    # compile (and so be audited) too, or its donation contract would
    # sit forever untested behind a fault path tier-1 never walks
    bad = eng.submit(rng.randint(2, 50, size=5).tolist(), max_tokens=6)
    eng.step()
    faults.poison_nan(bad)
    eng.run(max_ticks=200)
    if seal:
        auditor().seal()
        # steady state: the same arrival pattern must not compile again
        eng.submit(shared, max_tokens=6)
        eng.run(max_ticks=200)
        mixed_burst(17)
    return eng


def drive_serving_spec_steady_state(seal: bool = True):
    """The speculative-decoding steady state (round 18): an n-gram
    speculating engine (``spec_mode='ngram'``) runs a repetitive trace
    so the widened ``serving.step`` — each slot contributing ``k+1``
    verify rows — compiles, accepts, rejects and rolls back for real,
    then (sealed) replays the same shape: the audit proves speculation
    adds the ``k`` dimension to the (bucket, k1) jit ladder and nothing
    else, under the SAME step contract.  Requires ``FLAGS.jit_audit``
    on before the call.  Returns the engine."""
    import jax
    import numpy as np

    from paddle_tpu.serving import DecoderLM, ServingEngine

    model = DecoderLM(vocab_size=50, num_layers=2, num_heads=2,
                      head_dim=8, max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=64, max_pages_per_seq=12, max_slots=4,
                        buckets=(4, 8, 16), prefill_chunk=8,
                        spec_mode="ngram", spec_k=3)
    rng = np.random.RandomState(0)
    phrase = rng.randint(2, 50, size=4).tolist()

    def burst():
        # repetitive prompts: the n-gram proposer finds real matches,
        # so accept AND reject/rollback paths both execute
        eng.submit(phrase * 3, max_tokens=10)
        eng.step()
        eng.submit(rng.randint(2, 50, size=6).tolist(), max_tokens=8)
        eng.run(max_ticks=300)

    burst()
    if seal:
        auditor().seal()
        burst()                       # steady state: no new compiles
    return eng


def drive_trainer_step(batches: int = 2, batch_size: int = 16):
    """One tiny fc-classifier training pass (the ``trainer.train_step``
    site, donation contract (0, 1, 2)) plus one test pass (the
    ``trainer.test_step`` site).  The trainer runs GUARDED
    (resilience.BadStepGuard, skip policy) so the audited jaxpr is the
    production fault-tolerant step: the fused bad-step reduction and the
    skip selects must stay inside the ONE compiled program — no host
    callback, no extra compile, no donation regression.  Requires
    ``FLAGS.jit_audit`` on before the call.  Returns the SGD trainer."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layer, optimizer, trainer as trainer_mod
    from paddle_tpu.resilience.guard import BadStepGuard

    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(3))
    h = layer.fc(x, size=16, act="relu")
    logits = layer.fc(h, size=3)
    cost = layer.classification_cost(input=logits, label=y)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer_mod.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Momentum(
                              momentum=0.9, learning_rate=0.05),
                          guard=BadStepGuard(policy="skip"))
    rng = np.random.RandomState(0)
    data = [(rng.randn(8).astype(np.float32) * 0.1, int(rng.randint(0, 3)))
            for _ in range(batches * batch_size)]
    reader = paddle.batch(lambda: iter(data), batch_size)
    sgd.train(reader, num_passes=1)
    sgd.test(reader)                       # trainer.test_step compiles
    return sgd


def run_compiled_path_audit(printer: Callable[[str], None] = print,
                            rules: Optional[Sequence[str]] = None
                            ) -> Tuple[Dict[str, SiteReport],
                                       List[Diagnostic]]:
    """The acceptance run: flip ``FLAGS.jit_audit`` on, drive the
    sealed serving steady state plus one trainer pass, audit every
    captured site (``rules`` restricts the registry; RETRACE
    diagnostics from the sealed replay are folded in regardless).
    Returns (reports, all_diagnostics)."""
    from paddle_tpu.platform.flags import FLAGS

    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    aud = auditor()
    aud.reset()
    try:
        eng = drive_serving_steady_state(seal=False)
        # the widened speculative step (k+1 verify rows per slot) rides
        # the same serving.step contract — audit it in the gate too
        drive_serving_spec_steady_state(seal=False)
        drive_trainer_step()
        aud.seal()
        # sealed steady-state replay (fresh traffic, same buckets)
        import numpy as np

        rng = np.random.RandomState(7)
        eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=12)
        eng.step()
        eng.submit(rng.randint(2, 50, size=17).tolist(), max_tokens=8)
        eng.run(max_ticks=300)
        reports = audit_sites(aud, rules=rules)
    finally:
        FLAGS.jit_audit = old
    diags: List[Diagnostic] = []
    for name, rep in reports.items():
        printer(f"== {name}: {rep.signatures} signature(s), "
                f"{rep.eqns} eqns, est peak {rep.peak_bytes} B, "
                f"est {rep.flops:.3g} FLOPs")
        for d in rep.diagnostics:
            printer(f"  {d}")
        diags.extend(rep.diagnostics)
    # a contract-bearing site the drive never compiled is a coverage
    # hole, not a pass — say so, loudly enough to notice in the log
    for name, rec in sorted(aud.sites.items()):
        if rec.contract is not None and not rec.captured:
            printer(f"== {name}: declared a SiteContract but captured "
                    "no signatures this run — its contract was NOT "
                    "audited")
    retraces = list(aud.diagnostics)
    for d in retraces:
        printer(f"  {d}")
    diags.extend(retraces)
    return reports, diags
