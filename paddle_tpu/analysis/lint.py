"""Repo-invariant linter: AST rules for the mistakes this codebase has
actually had to engineer away.

Every rule encodes a repo contract that tests cannot easily enforce:

- ``wall-clock``       — ``time.time()`` / ``time.monotonic()`` /
  ``time.perf_counter()`` called in serving/, master/, obs/ or
  resilience/ code.
  Those layers run on an injectable clock (``time_fn=`` / ``FaultPlan``
  ``ManualClock``) so SLO, fault AND tracing paths are testable without
  sleeps — the obs tracer stamping events off the injected clock is
  what makes chaos-trace exports byte-deterministic.  Passing
  ``time.monotonic`` as an injectable *default* is fine — only calls
  are flagged.
- ``unseeded-random``  — module-function ``np.random.*`` calls (the
  process-global RNG) in library code; use ``np.random.RandomState(seed)``
  so parity tests and multi-host runs stay deterministic.
- ``host-sync``        — ``.item()``, ``np.asarray``/``np.array``/
  ``jnp.asarray``/``jax.device_get`` calls — and ``float()``/``int()``
  over a jax expression — lexically inside a ``for``/``while`` loop in
  serving, obs, platform or resilience code: a per-tick loop that
  syncs per
  element serializes the device pipeline (one sync per *tick* is the
  engine's documented budget, and instrumentation must add ZERO to it
  — obs is covered so a tracer hook can never smuggle a readback into
  the tick).  ``block_until_ready`` (method or ``jax.`` function form)
  is flagged at ANY depth, loop or not: it stalls on the WHOLE
  pipeline, so the only sanctioned uses are deliberate end-of-window
  timing syncs (``platform/stats.py``'s ``timer(block=...)``), each
  carrying a justified ``# lint: allow(host-sync)``.
- ``mutable-default``  — mutable default argument values (list/dict/set
  literals or constructors), the classic shared-state trap.
- ``import-time-flags``— reading ``FLAGS.<name>`` at module import time
  (module body, class body, or a function's *default argument*): the
  value freezes before ``paddle.init(**kwargs)`` / tests can override
  it.  ``FLAGS.define(...)`` and friends are the registry, not reads.

Findings are :class:`Diagnostic`\\ s with ``block_idx=None`` and the
location carried in the message (``path:line``).  Any rule is
suppressible per line with an inline ``# lint: allow(<rule>[, <rule>])``
comment on the offending line or the line directly above it.

Run: ``python -m paddle_tpu.analysis lint [paths...]`` (defaults to the
``paddle_tpu`` package).  A nonzero finding count prints a final line
tagged ``LINT-FAIL`` and exits 1; ``tools_tier1.sh`` greps the tag and
exits 5, the same loud-failure contract as PAGE-LEAK/REF-LEAK.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["run_lint", "lint_file", "lint_source", "RULES"]

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")

_CLOCK_CALLS = {"time", "monotonic", "perf_counter", "monotonic_ns",
                "time_ns", "clock"}
_SEEDED_RANDOM_OK = {"RandomState", "default_rng", "Generator",
                     "SeedSequence", "PCG64", "Philox", "bit_generator"}
_SYNC_FUNCS = {("np", "asarray"), ("np", "array"), ("numpy", "asarray"),
               ("numpy", "array"), ("jnp", "asarray"),
               ("jax", "device_get")}
_FLAGS_REGISTRY_ATTRS = {"define", "set", "update", "to_dict"}


@dataclass(frozen=True)
class Rule:
    name: str
    description: str
    # path predicate over POSIX-ish relative parts ("serving" in parts)
    applies: Callable[[Sequence[str]], bool]
    check: Callable[[ast.AST, List[str]], List]   # -> [(line, message)]


def _attr_chain(node: ast.AST) -> List[str]:
    """x.y.z -> ["x", "y", "z"]; empty when not a plain name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _contains_device_expr(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        chain = _attr_chain(sub) if isinstance(sub, ast.Attribute) else []
        if chain and chain[0] in ("jnp", "jax"):
            return True
    return False


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _check_wall_clock(tree, lines):
    # resolve aliases first so `import time as t` / `from time import
    # monotonic` cannot smuggle a wall-clock call past the rule
    module_aliases = {"time"}            # names bound to the time module
    func_aliases: dict = {}              # local name -> clock fn name
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    module_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _CLOCK_CALLS:
                    func_aliases[a.asname or a.name] = a.name
    out = []

    def flag(node, spelled):
        out.append((node.lineno,
                    f"{spelled} in serving/master code — route through "
                    "the injectable clock (time_fn= / FaultPlan "
                    "ManualClock)"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if len(chain) == 2 and chain[0] in module_aliases \
                and chain[1] in _CLOCK_CALLS:
            flag(node, f"{chain[0]}.{chain[1]}()")
        elif isinstance(node.func, ast.Name) \
                and node.func.id in func_aliases:
            flag(node, f"{node.func.id}() (= time."
                       f"{func_aliases[node.func.id]})")
    return out


def _check_unseeded_random(tree, lines):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if len(chain) == 3 and chain[0] in ("np", "numpy") \
                and chain[1] == "random" \
                and chain[2] not in _SEEDED_RANDOM_OK:
            out.append((node.lineno,
                        f"np.random.{chain[2]}() uses the process-global "
                        "RNG — use np.random.RandomState(seed) so runs "
                        "replay deterministically"))
    return out


class _LoopSyncVisitor(ast.NodeVisitor):
    """Collect host-sync calls lexically inside for/while bodies."""

    def __init__(self):
        self.loop_depth = 0
        self.findings: List = []

    def _visit_loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call):
        # block_until_ready is flagged at ANY depth (not just loops):
        # it drains the whole dispatch pipeline, which serving/obs/
        # platform layers may only do as a deliberate, annotated
        # end-of-timing-window sync.  Covers both the method form
        # (x.block_until_ready()) and jax.block_until_ready(x).
        chain = _attr_chain(node.func)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready") \
                or (len(chain) == 2 and chain[0] == "jax"
                    and chain[1] == "block_until_ready"):
            self.findings.append(
                (node.lineno, "block_until_ready() stalls on the whole "
                 "device pipeline — sync at most once per window, and "
                 "annotate a deliberate timing sync with "
                 "`# lint: allow(host-sync)`"))
        if self.loop_depth > 0:
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item":
                self.findings.append(
                    (node.lineno, ".item() inside a per-tick serving "
                     "loop forces one device sync per element — batch "
                     "the readback outside the loop"))
            chain = tuple(_attr_chain(node.func))
            if chain in _SYNC_FUNCS:
                self.findings.append(
                    (node.lineno, f"{'.'.join(chain)}() inside a "
                     "per-tick serving loop syncs the device per "
                     "element — hoist one readback out of the loop"))
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") and node.args \
                    and _contains_device_expr(node.args[0]):
                self.findings.append(
                    (node.lineno, f"{node.func.id}() over a jax "
                     "expression inside a loop blocks on the device "
                     "each iteration — stack and read back once"))
        self.generic_visit(node)


def _check_host_sync(tree, lines):
    v = _LoopSyncVisitor()
    v.visit(tree)
    return v.findings


def _check_mutable_default(tree, lines):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        a = node.args
        for default in list(a.defaults) + [d for d in a.kw_defaults if d]:
            bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set", "bytearray"))
            if bad:
                name = getattr(node, "name", "<lambda>")
                out.append((default.lineno,
                            f"mutable default argument in {name}() is "
                            "shared across calls — default to None and "
                            "construct inside"))
    return out


def _check_import_time_flags(tree, lines):
    out = []

    def flags_reads(node) -> Iterable[ast.Attribute]:
        """FLAGS reads in code that executes AT IMPORT TIME.  The walk
        stops at function/lambda boundaries (their bodies run later —
        even when the def sits inside a module-level if/try/with) but
        still visits their defaults and decorators, which do evaluate
        at import; class bodies execute at import and are descended."""
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                stack.extend(d for d in sub.args.defaults if d)
                stack.extend(d for d in sub.args.kw_defaults if d)
                stack.extend(getattr(sub, "decorator_list", []))
                continue
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "FLAGS" \
                    and sub.attr not in _FLAGS_REGISTRY_ATTRS:
                yield sub
            stack.extend(ast.iter_child_nodes(sub))

    for a in flags_reads(tree):
        out.append((a.lineno,
                    f"FLAGS.{a.attr} read at module import time freezes "
                    "the value before paddle.init()/env overrides apply "
                    "— read it inside the function that needs it"))
    return out


def _in_dirs(*names):
    return lambda parts: any(n in parts for n in names)


RULES: Dict[str, Rule] = {
    "wall-clock": Rule(
        "wall-clock",
        "direct clock calls in serving/master/obs/resilience code "
        "(injectable-clock layers)",
        _in_dirs("serving", "master", "obs", "resilience"),
        _check_wall_clock),
    "unseeded-random": Rule(
        "unseeded-random",
        "process-global np.random use in library code",
        lambda parts: True, _check_unseeded_random),
    "host-sync": Rule(
        "host-sync",
        "per-element device syncs inside serving/obs/platform/"
        "resilience loops (+ block_until_ready anywhere in those "
        "layers)",
        _in_dirs("serving", "obs", "platform", "resilience"),
        _check_host_sync),
    "mutable-default": Rule(
        "mutable-default", "mutable default argument values",
        lambda parts: True, _check_mutable_default),
    "import-time-flags": Rule(
        "import-time-flags", "FLAGS reads at module import time",
        lambda parts: "flags.py" not in parts[-1:],
        _check_import_time_flags),
}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _allowed_rules(lines: List[str], lineno: int) -> set:
    """Rules allowlisted for ``lineno`` (1-based): an inline
    ``# lint: allow(...)`` on the line or the line directly above."""
    allowed = set()
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m:
                allowed.update(t.strip() for t in m.group(1).split(","))
    return allowed


def lint_source(src: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint one source string as if it lived at ``path`` (the path's
    directory parts select which rules apply)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Diagnostic(Severity.ERROR, "parse-error",
                           f"{path}:{e.lineno}: {e.msg}")]
    lines = src.splitlines()
    # scope from the RESOLVED path: a bare filename linted from inside
    # its directory (`cd serving && lint engine.py`) must still select
    # the dir-scoped rules, not silently skip them
    parts = tuple(Path(path).resolve().parts) if path != "<string>" \
        else ("<string>",)
    out: List[Diagnostic] = []
    for name, r in RULES.items():
        if rules is not None and name not in rules:
            continue
        if not r.applies(parts):
            continue
        for lineno, message in r.check(tree, lines):
            if name in _allowed_rules(lines, lineno):
                continue
            out.append(Diagnostic(
                Severity.ERROR, name, f"{path}:{lineno}: {message}",
                vars=(f"{path}:{lineno}",)))
    out.sort(key=lambda d: d.message)
    return out


def lint_file(path, root: Optional[Path] = None,
              rules: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    p = Path(path)
    rel = p.relative_to(root) if root is not None and p.is_absolute() \
        else p
    return lint_source(p.read_text(), path=str(rel), rules=rules)


def iter_python_files(paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            files.extend(sorted(pp.rglob("*.py")))
        else:
            files.append(pp)
    return files


def run_lint(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None) -> List[Diagnostic]:
    """Lint ``paths`` (default: the installed ``paddle_tpu`` package
    tree).  Returns all findings; empty means clean."""
    if paths is None:
        pkg_root = Path(__file__).resolve().parent.parent
        paths = [str(pkg_root)]
        root: Optional[Path] = pkg_root.parent
    else:
        root = None
    out: List[Diagnostic] = []
    for f in iter_python_files(paths):
        out.extend(lint_file(f, root=root, rules=rules))
    return out
