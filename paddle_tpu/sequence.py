"""Ragged / variable-length sequence representation.

Reference: paddle/parameter/Argument.h:84-90 (sequenceStartPositions /
subSequenceStartPositions — concatenated tokens + offsets, no padding) and its
Gen-2 formalization LoD (paddle/framework/lod_tensor.h:57-80).

TPU-native design: XLA needs static shapes, so a ``SequenceBatch`` holds a
*flat* token buffer padded to a static capacity plus ``segment_ids`` mapping
each slot to its sequence (or -1/num_seqs for padding) — the segment-ids
formulation keeps the reference's "no per-timestep padding waste" property for
pooling/softmax/last-token ops, while ``to_padded()`` provides the [B, T, ...]
view that ``lax.scan`` RNNs want. Nested (sub-)sequences carry a second level
of segment ids, mirroring subSequenceStartPositions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class SequenceBatch:
    """A batch of variable-length sequences in flat (LoD-like) form.

    data:        [capacity, ...feature] — concatenated tokens, padded at the end
    segment_ids: [capacity] int32 — sequence index per slot; >= num_seqs ⇒ pad
    lengths:     [num_seqs] int32 — true length of each sequence
    sub_segment_ids: optional [capacity] int32 — inner-sequence index for
        nested sequences (subSequenceStartPositions analog)
    """

    data: jax.Array
    segment_ids: jax.Array
    lengths: jax.Array
    sub_segment_ids: Optional[jax.Array] = None
    # STATIC metadata (pytree aux, not traced): an upper bound on the longest
    # sequence, set host-side by the DataFeeder (bucketed). Keeps lax.scan
    # time loops at ~max_len steps instead of `capacity` steps.
    max_len: Optional[int] = None

    @property
    def num_seqs(self) -> int:
        return self.lengths.shape[0]

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    @property
    def valid_mask(self) -> jax.Array:
        return self.segment_ids < self.num_seqs

    def with_data(self, data: jax.Array) -> "SequenceBatch":
        return SequenceBatch(data, self.segment_ids, self.lengths,
                             self.sub_segment_ids, self.max_len)

    # ---- conversions -----------------------------------------------------

    def to_padded(self, max_len: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
        """Return ([B, T, ...feature], mask [B, T]) dense view.

        T is static: max_len arg, else self.max_len, else capacity.
        Scatter via position-in-sequence ids.
        """
        B = self.num_seqs
        T = int(max_len if max_len is not None else (self.max_len or self.capacity))
        pos = position_in_sequence(self.segment_ids)
        valid = self.valid_mask & (pos < T)
        seg = jnp.where(valid, self.segment_ids, B)
        p = jnp.where(valid, pos, 0)
        feat = self.data.shape[1:]
        out = jnp.zeros((B + 1, T) + feat, dtype=self.data.dtype)
        out = out.at[seg, p].set(jnp.where(
            valid.reshape((-1,) + (1,) * len(feat)), self.data, 0))
        mask = jnp.arange(T)[None, :] < self.lengths[:, None]
        return out[:B], mask

    @staticmethod
    def from_padded(padded: jax.Array, lengths: jax.Array,
                    capacity: Optional[int] = None) -> "SequenceBatch":
        """Build flat form from [B, T, ...] + lengths. capacity defaults B*T."""
        B, T = padded.shape[0], padded.shape[1]
        cap = int(capacity) if capacity is not None else B * T
        # Flatten row-major; slots beyond each row's length are pads. We pack
        # compactly so downstream segment ops see contiguous tokens.
        seg_full = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)
        pos_full = jnp.tile(jnp.arange(T, dtype=jnp.int32), B)
        valid_full = pos_full < lengths[seg_full]
        order = jnp.argsort(~valid_full, stable=True)  # valid tokens first
        take = order[:cap]
        flat = padded.reshape((B * T,) + padded.shape[2:])[take]
        seg = jnp.where(valid_full[take], seg_full[take], B).astype(jnp.int32)
        if cap > B * T:  # pad out to the requested static capacity
            extra = cap - B * T
            flat = jnp.concatenate(
                [flat, jnp.zeros((extra,) + flat.shape[1:], flat.dtype)])
            seg = jnp.concatenate(
                [seg, jnp.full((extra,), B, jnp.int32)])
        data = jnp.where(
            (seg < B).reshape((-1,) + (1,) * (flat.ndim - 1)), flat, 0)
        return SequenceBatch(data=data, segment_ids=seg, lengths=lengths,
                             max_len=T)

    @staticmethod
    def from_list(seqs, dtype=jnp.float32, capacity: Optional[int] = None) -> "SequenceBatch":
        """Host-side constructor from a python list of [len_i, ...] arrays."""
        arrs = [np.asarray(s) for s in seqs]
        lengths = np.asarray([a.shape[0] for a in arrs], dtype=np.int32)
        total = int(lengths.sum())
        cap = capacity if capacity is not None else total
        from paddle_tpu.platform.enforce import enforce_that
        enforce_that(cap >= total,
                     f"from_list capacity {cap} < total tokens {total}",
                     context="sequence")
        feat = arrs[0].shape[1:] if arrs else ()
        data = np.zeros((cap,) + feat, dtype=np.dtype(jnp.dtype(dtype)))
        seg = np.full((cap,), len(arrs), dtype=np.int32)
        off = 0
        for i, a in enumerate(arrs):
            n = a.shape[0]
            data[off:off + n] = a
            seg[off:off + n] = i
            off += n
        return SequenceBatch(data=jnp.asarray(data), segment_ids=jnp.asarray(seg),
                             lengths=jnp.asarray(lengths),
                             max_len=int(lengths.max()) if len(arrs) else 0)


def _sb_flatten(sb: SequenceBatch):
    # max_len is STATIC aux data: it parameterizes compiled shapes (scan
    # lengths), so two batches with different max_len hash to different jit
    # cache entries — exactly the bucketed-recompile behavior we want.
    return (sb.data, sb.segment_ids, sb.lengths, sb.sub_segment_ids), sb.max_len


def _sb_unflatten(max_len, children) -> SequenceBatch:
    return SequenceBatch(*children, max_len=max_len)


# Registered as a pytree so SequenceBatch feeds flow through jit/grad/scan
# boundaries like any array (the LoDTensor-crosses-the-C++-boundary analog).
jax.tree_util.register_pytree_node(SequenceBatch, _sb_flatten, _sb_unflatten)


def position_in_sequence(segment_ids: jax.Array) -> jax.Array:
    """Per-slot position within its segment, assuming contiguous segments."""
    n = segment_ids.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # start index of each slot's segment = first occurrence; with sorted
    # contiguous segments, slot i's position = i - start_of_segment.
    is_start = jnp.concatenate([
        jnp.ones((1,), dtype=bool), segment_ids[1:] != segment_ids[:-1]])
    start_idx = jnp.where(is_start, idx, 0)
    seg_start = jax.lax.associative_scan(jnp.maximum, start_idx)
    return idx - seg_start


def lengths_to_segment_ids(lengths: jax.Array, capacity: int) -> jax.Array:
    """[num_seqs] lengths -> [capacity] contiguous segment ids (pads = num_seqs)."""
    B = lengths.shape[0]
    ends = jnp.cumsum(lengths)
    slots = jnp.arange(capacity, dtype=jnp.int32)
    seg = jnp.searchsorted(ends, slots, side="right").astype(jnp.int32)
    return jnp.where(slots < ends[-1], seg, B)


def nested_to_padded(sb: "SequenceBatch", max_inner: int,
                     max_inner_len: int):
    """Dense view of a NESTED sequence batch (subSequenceStartPositions
    analog): [B, S, W, ...feature] data plus inner lengths [B, S] and
    inner-sequence counts [B].

    ``max_inner`` (S: most inner sequences per outer sequence) and
    ``max_inner_len`` (W: longest inner sequence) are STATIC bounds —
    hierarchical recurrent groups scan over S with W-wide frames, so
    compiled shapes need them up front (pass tight bounds from the
    feeder's bucketing; tokens beyond the bounds are dropped like
    to_padded's max_len).
    """
    from paddle_tpu.platform.enforce import enforce_that
    enforce_that(sb.sub_segment_ids is not None,
                 "nested_to_padded needs a nested SequenceBatch "
                 "(sub_segment_ids)", context="sequence")
    B, S, W = sb.num_seqs, int(max_inner), int(max_inner_len)
    seg = sb.segment_ids
    sub = sb.sub_segment_ids
    valid = sb.valid_mask & (sub < S)
    # contiguous (outer, inner) runs -> position within the inner sequence
    combined = jnp.where(valid, seg * S + sub, B * S)
    pos = position_in_sequence(combined)
    valid = valid & (pos < W)
    s_seg = jnp.where(valid, seg, B)
    s_sub = jnp.where(valid, sub, 0)
    s_pos = jnp.where(valid, pos, 0)
    feat = sb.data.shape[1:]
    out = jnp.zeros((B + 1, S, W) + feat, dtype=sb.data.dtype)
    out = out.at[s_seg, s_sub, s_pos].set(jnp.where(
        valid.reshape((-1,) + (1,) * len(feat)), sb.data, 0))
    ones = valid.astype(jnp.int32)
    inner_lens = jnp.zeros((B + 1, S), jnp.int32).at[s_seg, s_sub].add(ones)
    counts = jnp.zeros((B + 1,), jnp.int32).at[
        jnp.where(valid, seg, B)].max(jnp.where(valid, sub + 1, 0))
    return out[:B], inner_lens[:B], counts[:B]


def nested_from_padded(data: jax.Array, inner_lens: jax.Array,
                       counts: jax.Array, capacity: int) -> "SequenceBatch":
    """Inverse of nested_to_padded: [B, S, W, ...feature] + inner lengths
    [B, S] + inner-sequence counts [B] -> a nested SequenceBatch with
    tokens packed compactly in (outer, inner, position) order."""
    B, S, W = data.shape[0], data.shape[1], data.shape[2]
    cap = int(capacity)
    feat = data.shape[3:]
    b_ix = jnp.repeat(jnp.arange(B, dtype=jnp.int32), S * W)
    s_ix = jnp.tile(jnp.repeat(jnp.arange(S, dtype=jnp.int32), W), B)
    w_ix = jnp.tile(jnp.arange(W, dtype=jnp.int32), B * S)
    valid = (s_ix < counts[b_ix]) & (w_ix < inner_lens[b_ix, s_ix])
    order = jnp.argsort(~valid, stable=True)[:cap]
    flat = data.reshape((B * S * W,) + feat)[order]
    seg = jnp.where(valid[order], b_ix[order], B).astype(jnp.int32)
    sub = jnp.where(valid[order], s_ix[order], 0).astype(jnp.int32)
    lengths = jnp.sum(jnp.where(jnp.arange(S)[None, :] < counts[:, None],
                                inner_lens, 0), axis=1).astype(jnp.int32)
    mask = (seg < B).reshape((-1,) + (1,) * len(feat))
    return SequenceBatch(data=jnp.where(mask, flat, 0), segment_ids=seg,
                         lengths=lengths, sub_segment_ids=sub,
                         max_len=min(cap, S * W))
