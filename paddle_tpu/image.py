"""Image preprocessing utilities.

Reference: python/paddle/v2/image.py:1-60 (load/resize/crop/flip/chw
pipelines used by the image demos — flowers, VOC, model-zoo resnet).

TPU twist: the native layout here is **HWC** (and NHWC for batches) because
that is the layout XLA tiles best onto the MXU (ops/conv.py); ``to_chw``
exists for reference-format compatibility (the v2 API fed CHW-major flat
vectors). Decoding prefers cv2 (BGR, like the reference) and falls back to
PIL (RGB) so the module works wherever either is installed.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Callable, Dict, Optional

import numpy as np

try:
    import cv2
except Exception:  # pragma: no cover - env without opencv
    cv2 = None

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw", "to_hwc",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def channel_order() -> str:
    """Channel order produced by load_image_bytes: cv2 decodes BGR, the PIL
    fallback RGB. Callers applying per-channel constants (means) must match."""
    return "BGR" if cv2 is not None else "RGB"


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an image from raw bytes -> HWC uint8 (HW if gray)."""
    if cv2 is not None:
        flag = 1 if is_color else 0
        arr = np.frombuffer(data, np.uint8)
        img = cv2.imdecode(arr, flag)
        if img is None:
            raise IOError("cv2 could not decode image bytes")
        return img
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize(im: np.ndarray, w: int, h: int) -> np.ndarray:
    if cv2 is not None:
        return cv2.resize(im, (w, h), interpolation=cv2.INTER_LANCZOS4)
    from PIL import Image

    mode = "L" if im.ndim == 2 else "RGB"
    return np.asarray(Image.fromarray(im, mode).resize((w, h), Image.LANCZOS))


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the SHORT edge equals ``size``, keeping aspect ratio."""
    h, w = im.shape[:2]
    if h > w:
        new_w, new_h = size, int(round(h * size / w))
    else:
        new_w, new_h = int(round(w * size / h)), size
    return _resize(im, new_w, new_h)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (the reference's storage layout)."""
    assert im.ndim == len(order)
    return im.transpose(order)


def to_hwc(im: np.ndarray) -> np.ndarray:
    """CHW -> HWC (the TPU-native layout)."""
    assert im.ndim == 3
    return im.transpose(1, 2, 0)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    h0, w0 = (h - size) // 2, (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    h0 = rng.randint(0, h - size + 1)
    w0 = rng.randint(0, w - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean: Optional[np.ndarray] = None,
                     layout: str = "HWC",
                     rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    """resize_short -> (random|center) crop -> [flip] -> float32 [-mean].

    ``layout``: "HWC" (TPU-native, default) or "CHW" (reference-compatible).
    """
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if rng.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        # per-pixel mean comes in the requested layout; per-channel applies
        # to the last (HWC) axis before any transpose
        if mean.ndim == 1 and im.ndim == 3:
            im -= mean.reshape(1, 1, -1)
        else:
            im -= mean
    if layout == "CHW" and im.ndim == 3:
        im = to_chw(im)
    return im


def load_and_transform(path: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean=None, layout: str = "HWC") -> np.ndarray:
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean, layout)


def batch_images_from_tar(data_file: str, dataset_name: str,
                          img2label: Dict[str, int],
                          num_per_batch: int = 1024) -> str:
    """Pack raw images from a tar into pickled batch files; returns the meta
    list file (reference: image.py batch_images_from_tar)."""
    batch_dir = data_file + "_batch"
    out_path = os.path.join(batch_dir, dataset_name)
    meta_file = os.path.join(batch_dir, dataset_name + ".txt")
    if os.path.exists(out_path):
        return meta_file
    os.makedirs(out_path)

    data, labels, file_id = [], [], 0

    def dump():
        nonlocal data, labels, file_id
        with open(os.path.join(out_path, f"batch_{file_id}"), "wb") as f:
            pickle.dump({"label": labels, "data": data}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        file_id += 1
        data, labels = [], []

    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name in img2label:
                data.append(tf.extractfile(mem).read())
                labels.append(img2label[mem.name])
                if len(data) == num_per_batch:
                    dump()
    if data:
        dump()
    with open(meta_file, "a") as meta:
        for fname in sorted(os.listdir(out_path)):
            meta.write(os.path.abspath(os.path.join(out_path, fname)) + "\n")
    return meta_file
