"""Merged-model export: one self-contained inference artifact.

Reference analog: ``paddle merge_model`` (trainer/MergeModel.cpp) packs
ModelConfig proto + weights into a single file consumed by the C
inference API (paddle/capi gradient_machine loading).

TPU-native design: instead of a config proto + a C++ engine to interpret
it, the whole forward graph is compiled and serialized as **StableHLO**
via ``jax.export`` with the trained weights baked in as constants. The
artifact is a zip with the serialized executable plus a json manifest of
input/output specs. Loading needs no layer library at all — any PJRT
runtime (incl. the C API used by capi_runtime.cpp) can execute it, which
is the capability the reference's merged model + capi pair provided.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.parameters import Parameters
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.topology import LayerOutput, Topology

_FORMAT_VERSION = 1


def merge_model(output_layers, parameters: Parameters, path: str,
                batch_size: Optional[int] = None) -> None:
    """Compile forward(feeds) with weights baked in and write ``path``.

    ``batch_size=None`` exports with a symbolic batch dimension (any
    batch size at load time); an int pins it."""
    import jax
    from jax import export as jexport

    outs = output_layers if isinstance(output_layers, (list, tuple)) \
        else [output_layers]
    topo = Topology(list(outs))
    state = topo.init_state()
    params = {k: np.asarray(v) for k, v in parameters.as_dict().items()}

    data_nodes = [n for n in topo.nodes if n.layer_type == "data"]
    data_nodes.sort(key=lambda n: getattr(n, "declare_idx", 0))
    feed_specs = []
    for n in data_nodes:
        enforce_that(not n.is_sequence,
                     "merge_model currently exports dense-input graphs "
                     "(sequence feeds carry host-side ragged metadata)",
                     context="export")
        if _is_int_feed(n):
            dtype = "int32"
            shape: Tuple = ()
        else:
            dtype = "float32"
            shape = (n.size,)
        feed_specs.append({"name": n.name, "dtype": dtype,
                           "feature_shape": list(shape)})

    if batch_size is None:
        (b,) = jexport.symbolic_shape("b")
    else:
        b = int(batch_size)

    args = tuple(
        jax.ShapeDtypeStruct((b,) + tuple(s["feature_shape"]),
                             np.dtype(s["dtype"]))
        for s in feed_specs)

    def forward(*feed_vals):
        feeds = {s["name"]: v for s, v in zip(feed_specs, feed_vals)}
        outs_v, _ = topo.forward(params, state, feeds, train=False)
        return tuple(o.data if hasattr(o, "segment_ids") else o
                     for o in outs_v)

    exported = jexport.export(jax.jit(forward))(*args)
    blob = exported.serialize()

    manifest = {
        "format_version": _FORMAT_VERSION,
        "inputs": feed_specs,
        "outputs": [n.name for n in outs],
        "symbolic_batch": batch_size is None,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("manifest.json", json.dumps(manifest))
        z.writestr("model.stablehlo", blob)


class MergedModel:
    """Loaded merged model: ``infer(feeds)`` with no layer library needed
    (the capi paddle_gradient_machine_create_for_inference analog)."""

    def __init__(self, path: str):
        from jax import export as jexport

        with zipfile.ZipFile(path) as z:
            self.manifest = json.loads(z.read("manifest.json"))
            enforce_that(
                self.manifest.get("format_version") == _FORMAT_VERSION,
                "unsupported merged-model version", context="export")
            self._exported = jexport.deserialize(z.read("model.stablehlo"))
        self.input_names = [s["name"] for s in self.manifest["inputs"]]
        self.output_names = self.manifest["outputs"]

    def infer(self, feeds: Dict[str, np.ndarray]) -> List[np.ndarray]:
        args = []
        for spec in self.manifest["inputs"]:
            enforce_that(spec["name"] in feeds,
                         f"missing feed {spec['name']!r}", context="export")
            args.append(np.asarray(feeds[spec["name"]],
                                   dtype=np.dtype(spec["dtype"])))
        outs = self._exported.call(*args)
        return [np.asarray(o) for o in outs]

    def create_shared(self) -> "MergedModel":
        """New inference instance sharing this model's compiled executable
        (weights baked in — ONE copy serves all instances), the
        paddle_gradient_machine_create_shared_param analog
        (capi/gradient_machine.h:88): hand each serving thread its own
        instance. ``infer`` is reentrant either way (the executable is
        stateless); the clone exists so embedders can mirror the
        reference's one-handle-per-thread pattern."""
        clone = object.__new__(MergedModel)
        clone.manifest = self.manifest
        clone._exported = self._exported
        clone.input_names = list(self.input_names)
        clone.output_names = list(self.output_names)
        return clone


def load_merged_model(path: str) -> MergedModel:
    return MergedModel(path)


def _is_int_feed(n) -> bool:
    """Integer-id data node (embedding tables): fed as [B] int32.
    data_type.integer_value marks the slot kind INDEX (SlotKind.INDEX)."""
    return "INDEX" in str(getattr(n.input_type, "slot", "")).upper()


def _dense_forward_spec(output_layers, parameters, batch_size, *, context):
    """Shared export preamble: topology, sorted dense data nodes, the
    weights-closed forward fn, and fixed-batch arg specs (merge_model /
    export_pjrt_model / export_aot_program all trace the same way)."""
    import jax

    outs = output_layers if isinstance(output_layers, (list, tuple)) \
        else [output_layers]
    topo = Topology(list(outs))
    state = topo.init_state()
    params = {k: np.asarray(v) for k, v in parameters.as_dict().items()}

    data_nodes = [n for n in topo.nodes if n.layer_type == "data"]
    data_nodes.sort(key=lambda n: getattr(n, "declare_idx", 0))
    args = []
    for n in data_nodes:
        enforce_that(not n.is_sequence,
                     f"{context} supports dense-input graphs",
                     context=context)
        if _is_int_feed(n):
            args.append(jax.ShapeDtypeStruct((int(batch_size),), np.int32))
        else:
            args.append(jax.ShapeDtypeStruct((int(batch_size), n.size),
                                             np.float32))
    args = tuple(args)

    def forward(*feed_vals):
        feeds = {n.name: v for n, v in zip(data_nodes, feed_vals)}
        outs_v, _ = topo.forward(params, state, feeds, train=False)
        return tuple(o.data if hasattr(o, "segment_ids") else o
                     for o in outs_v)

    return outs, topo, data_nodes, forward, args


# ---------------------------------------------------------------------------
# PJRT model export: the TPU-production C inference artifact
# ---------------------------------------------------------------------------


def export_pjrt_model(output_layers, parameters: Parameters, path: str,
                      batch_size: int) -> None:
    """Write a ``.ptpj`` artifact for the PJRT C-API inference path
    (native/src/pjrt_capi.cpp): the raw StableHLO module bytecode (weights
    baked in as constants) plus a serialized default CompileOptionsProto,
    in a flat binary container the C side can read without zip/json/proto
    libraries. On a real TPU host the C client dlopens the platform's
    PJRT plugin (libtpu.so), compiles the module, and runs inference with
    no Python in the process — SURVEY §7 item 11's "C ABI over PJRT".
    ``batch_size`` is pinned (the C ABI binds fixed shapes)."""
    import struct

    import jax
    from jax import export as jexport

    outs, _topo, data_nodes, forward, args = _dense_forward_spec(
        output_layers, parameters, batch_size, context="export_pjrt")
    exported = jexport.export(jax.jit(forward))(*args)
    mlir = exported.mlir_module_serialized

    from jax._src.lib import _jax as _xc
    opts = _xc.CompileOptions().SerializeAsString()

    with open(path, "wb") as f:
        w = f.write
        w(b"PTPJ")
        w(struct.pack("<I", 2))
        w(struct.pack("<I", len(data_nodes)))
        for n in data_nodes:
            name = n.name.encode()
            w(struct.pack("<H", len(name)))
            w(name)
            # v2 spec matches the traced entry signature per input:
            # integer feeds are i32 rank-1 [B], dense are f32 rank-2
            # [B, size] (ADVICE r4: v1 declared everything f32 rank-2,
            # contradicting the StableHLO signature for embedding models)
            if _is_int_feed(n):
                w(struct.pack("<BB", 1, 1))  # i32, rank 1
                w(struct.pack("<q", int(batch_size)))
            else:
                w(struct.pack("<BB", 0, 2))  # f32, rank 2
                w(struct.pack("<2q", int(batch_size), int(n.size)))
        w(struct.pack("<I", len(outs)))
        w(struct.pack("<Q", len(mlir)))
        w(mlir)
        w(struct.pack("<Q", len(opts)))
        w(opts)


# ---------------------------------------------------------------------------
# AOT program export: the interpreter-free C inference artifact
# ---------------------------------------------------------------------------
#
# Reference analog: paddle/capi's pure-C embedded deployment
# (capi/gradient_machine.h:36-112, Android cross-compile) — inference with
# NO Python interpreter in the process. The forward jaxpr (the same traced
# computation the StableHLO export uses) is translated into a flat tensor
# program (.ptnm) executed by the dependency-free C++ runtime in
# native/src/aot_runtime.cpp. Restricted to dense inference graphs; the
# translator fails loudly on unsupported primitives.

_PTNM_MAGIC = b"PTNM"
_PTNM_VERSION = 1

# opcodes (keep in sync with native/src/aot_runtime.cpp)
OP_ADD, OP_SUB, OP_MUL, OP_DIV, OP_MAX, OP_MIN = 1, 2, 3, 4, 5, 6
OP_EXP, OP_LOG, OP_TANH, OP_LOGISTIC, OP_RSQRT = 7, 8, 9, 10, 11
OP_SQRT, OP_NEG, OP_ABS = 12, 13, 14
OP_DOT, OP_BCAST, OP_RESHAPE, OP_TRANSPOSE = 15, 16, 17, 18
OP_RSUM, OP_RMAX, OP_CONV2D, OP_MAXPOOL, OP_SUMPOOL = 19, 20, 21, 22, 23
OP_SELECT_N, OP_CLAMP, OP_CONCAT, OP_IPOW, OP_IDENT = 24, 25, 26, 27, 28
OP_LT, OP_LE, OP_GT, OP_GE, OP_EQ, OP_NE = 29, 30, 31, 32, 33, 34
OP_GATHER_ROWS, OP_TRUNC = 35, 36

_UNARY = {"exp": OP_EXP, "log": OP_LOG, "tanh": OP_TANH,
          "logistic": OP_LOGISTIC, "rsqrt": OP_RSQRT, "sqrt": OP_SQRT,
          "neg": OP_NEG, "abs": OP_ABS}
_BINARY = {"add": OP_ADD, "sub": OP_SUB, "mul": OP_MUL, "div": OP_DIV,
           "max": OP_MAX, "min": OP_MIN, "lt": OP_LT, "le": OP_LE,
           "gt": OP_GT, "ge": OP_GE, "eq": OP_EQ, "ne": OP_NE}
_CALL_PRIMS = {"jit", "pjit", "custom_jvp_call", "custom_vjp_call",
               "closed_call", "core_call", "remat", "checkpoint"}


class _AotBuilder:
    def __init__(self):
        self.tensors: List[Tuple[int, Tuple[int, ...]]] = []  # (dtype, dims)
        self.consts: List[Tuple[int, np.ndarray]] = []
        self.ops: List[Tuple[int, List[int], int, List[int]]] = []

    def tensor(self, dtype: str, shape) -> int:
        # bools ride as f32 0/1 in the f32-only runtime
        code = {"float32": 0, "int32": 1, "bool": 0,
                "int64": 1}.get(str(dtype))
        enforce_that(code is not None,
                     f"AOT export supports f32/i32/bool tensors, got {dtype}",
                     context="export_aot")
        self.tensors.append((code, tuple(int(d) for d in shape)))
        return len(self.tensors) - 1

    def const(self, value: np.ndarray) -> int:
        value = np.asarray(value)
        if value.dtype not in (np.float32, np.int32):
            value = value.astype(
                np.int32 if np.issubdtype(value.dtype, np.integer)
                else np.float32)  # bools become f32 0/1
        tid = self.tensor(str(value.dtype), value.shape)
        self.consts.append((tid, np.ascontiguousarray(value)))
        return tid

    def emit(self, opcode: int, ins: List[int], out: int,
             attrs: List[int] = ()):  # noqa: B006
        self.ops.append((opcode, list(ins), out, [int(a) for a in attrs]))


def _translate_jaxpr(jaxpr, consts, arg_ids, b: "_AotBuilder") -> List[int]:
    """Walk eqns, emitting ops; call-like primitives are inlined."""
    env: Dict = {}

    def read(var):
        from jax.extend.core import Literal

        if isinstance(var, Literal):
            return b.const(np.asarray(var.val))
        return env[var]

    def write(var, tid):
        env[var] = tid

    for v, c in zip(jaxpr.constvars, consts):
        write(v, b.const(np.asarray(c)))
    for v, tid in zip(jaxpr.invars, arg_ids):
        write(v, tid)

    for eq in jaxpr.eqns:
        prim = eq.primitive.name
        out_av = eq.outvars[0].aval
        if prim in _CALL_PRIMS:
            sub = eq.params.get("jaxpr") or eq.params.get("call_jaxpr")
            closed = getattr(sub, "jaxpr", None)
            inner = closed if closed is not None else sub
            sub_consts = getattr(sub, "consts", [])
            outs = _translate_jaxpr(inner, sub_consts,
                                    [read(v) for v in eq.invars], b)
            for ov, tid in zip(eq.outvars, outs):
                write(ov, tid)
            continue

        def out_tid():
            return b.tensor(str(out_av.dtype), out_av.shape)

        if prim in _BINARY:
            t = out_tid()
            b.emit(_BINARY[prim], [read(v) for v in eq.invars], t)
        elif prim in _UNARY:
            t = out_tid()
            b.emit(_UNARY[prim], [read(eq.invars[0])], t)
        elif prim == "integer_pow":
            t = out_tid()
            b.emit(OP_IPOW, [read(eq.invars[0])], t, [eq.params["y"]])
        elif prim == "dot_general":
            dn = eq.params["dimension_numbers"]
            enforce_that(dn == (((1,), (0,)), ((), ())),
                         f"AOT dot_general supports plain 2D matmul, "
                         f"got dims {dn}", context="export_aot")
            t = out_tid()
            b.emit(OP_DOT, [read(v) for v in eq.invars], t)
        elif prim == "broadcast_in_dim":
            t = out_tid()
            b.emit(OP_BCAST, [read(eq.invars[0])], t,
                   list(eq.params["broadcast_dimensions"]))
        elif prim in ("reshape", "squeeze", "expand_dims"):
            t = out_tid()
            b.emit(OP_RESHAPE, [read(eq.invars[0])], t)
        elif prim == "transpose":
            t = out_tid()
            b.emit(OP_TRANSPOSE, [read(eq.invars[0])], t,
                   list(eq.params["permutation"]))
        elif prim in ("reduce_sum", "reduce_max"):
            t = out_tid()
            b.emit(OP_RSUM if prim == "reduce_sum" else OP_RMAX,
                   [read(eq.invars[0])], t, list(eq.params["axes"]))
        elif prim == "conv_general_dilated":
            p = eq.params
            dn = p["dimension_numbers"]
            enforce_that(
                tuple(dn.lhs_spec) == (0, 3, 1, 2)
                and tuple(dn.rhs_spec) == (3, 2, 0, 1)
                and tuple(dn.out_spec) == (0, 3, 1, 2)
                and p["feature_group_count"] == 1
                and p["batch_group_count"] == 1
                and tuple(p["lhs_dilation"]) == (1, 1)
                and tuple(p["rhs_dilation"]) == (1, 1),
                "AOT conv supports NHWC/HWIO stride+pad convs",
                context="export_aot")
            (pt, pb_), (pl, pr) = p["padding"]
            sh, sw = p["window_strides"]
            t = out_tid()
            b.emit(OP_CONV2D, [read(v) for v in eq.invars], t,
                   [sh, sw, pt, pb_, pl, pr])
        elif prim in ("reduce_window_max", "reduce_window_sum"):
            p = eq.params
            wd, ws, pad = (p["window_dimensions"], p["window_strides"],
                           p["padding"])
            enforce_that(
                len(wd) == 4 and wd[0] == wd[3] == 1
                and ws[0] == ws[3] == 1
                and tuple(p["base_dilation"]) == (1, 1, 1, 1)
                and tuple(p["window_dilation"]) == (1, 1, 1, 1)
                and pad[0] == (0, 0) and pad[3] == (0, 0),
                "AOT pooling supports NHWC spatial windows",
                context="export_aot")
            t = out_tid()
            b.emit(OP_MAXPOOL if prim.endswith("max") else OP_SUMPOOL,
                   [read(eq.invars[0])], t,
                   [wd[1], wd[2], ws[1], ws[2],
                    pad[1][0], pad[1][1], pad[2][0], pad[2][1]])
        elif prim == "gather":
            dn = eq.params["dimension_numbers"]
            op_av = eq.invars[0].aval
            idx_av = eq.invars[1].aval
            ss = tuple(eq.params["slice_sizes"])
            enforce_that(
                tuple(dn.offset_dims) == (1,)
                and tuple(dn.collapsed_slice_dims) == (0,)
                and tuple(dn.start_index_map) == (0,)
                and len(op_av.shape) == 2 and len(idx_av.shape) == 2
                and idx_av.shape[1] == 1
                and ss == (1, op_av.shape[1]),
                "AOT gather supports row lookup (embedding tables): "
                "[V,D] table, [N,1] indices", context="export_aot")
            t = out_tid()
            b.emit(OP_GATHER_ROWS, [read(v) for v in eq.invars], t)
        elif prim == "select_n":
            t = out_tid()
            b.emit(OP_SELECT_N, [read(v) for v in eq.invars], t)
        elif prim == "clamp":
            t = out_tid()
            b.emit(OP_CLAMP, [read(v) for v in eq.invars], t)
        elif prim == "concatenate":
            t = out_tid()
            b.emit(OP_CONCAT, [read(v) for v in eq.invars], t,
                   [eq.params["dimension"]])
        elif prim in ("stop_gradient", "copy"):
            write(eq.outvars[0], read(eq.invars[0]))
            continue
        elif prim == "convert_element_type":
            src = eq.invars[0].aval.dtype
            dst = out_av.dtype
            if src == dst:
                write(eq.outvars[0], read(eq.invars[0]))
                continue
            # the runtime stores everything as f32 (i32 consts widened at
            # load): widening casts are copies; casts TO integer truncate
            # toward zero (exact for |x| < 2^24, jax's f32->i32 semantics)
            to_int = np.issubdtype(np.dtype(dst), np.integer)
            t = out_tid()
            b.emit(OP_TRUNC if to_int else OP_IDENT,
                   [read(eq.invars[0])], t)
        else:
            raise EnforceError(
                f"AOT export: unsupported primitive {prim!r} — this graph "
                "needs the merged StableHLO path (CPython capi) instead",
                context="export_aot")
        write(eq.outvars[0], t)

    return [read(v) for v in jaxpr.outvars]


def export_aot_program(output_layers, parameters: Parameters, path: str,
                       batch_size: int) -> None:
    """Translate the forward graph into a .ptnm tensor program the pure-C++
    runtime (native/src/aot_runtime.cpp) executes with NO Python and no
    jax/XLA in the process — the embedded-deployment property of the
    reference's paddle/capi. ``batch_size`` is pinned (embedders fix their
    batch; export several programs for several batch sizes)."""
    import struct

    import jax

    from paddle_tpu.platform.flags import FLAGS

    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False  # the C runtime is f32-only
    try:
        outs, _topo, data_nodes, forward, args = _dense_forward_spec(
            output_layers, parameters, batch_size, context="export_aot")
        enforce_that(len(data_nodes) == 1,
                     "AOT export v1 is single-input (the C ABI binds one "
                     "dense feed); concat extra features host-side or use "
                     "the merged StableHLO path", context="export_aot")
        closed = jax.make_jaxpr(forward)(*args)
    finally:
        FLAGS.use_bf16 = old_bf16

    b = _AotBuilder()
    arg_ids = [b.tensor("int32", (int(batch_size),)) if _is_int_feed(n)
               else b.tensor("float32", (int(batch_size), n.size))
               for n in data_nodes]
    out_ids = _translate_jaxpr(closed.jaxpr, closed.consts, arg_ids, b)

    with open(path, "wb") as f:
        w = f.write
        w(_PTNM_MAGIC)
        w(struct.pack("<I", _PTNM_VERSION))
        w(struct.pack("<I", len(b.tensors)))
        for dtype, dims in b.tensors:
            w(struct.pack("<BB", dtype, len(dims)))
            w(struct.pack(f"<{len(dims)}q", *dims))
        w(struct.pack("<I", len(data_nodes)))
        for n, tid in zip(data_nodes, arg_ids):
            name = n.name.encode()
            w(struct.pack("<IH", tid, len(name)))
            w(name)
        w(struct.pack("<I", len(out_ids)))
        for tid in out_ids:
            w(struct.pack("<I", tid))
        w(struct.pack("<I", len(b.consts)))
        for tid, arr in b.consts:
            raw = arr.tobytes()
            w(struct.pack("<IQ", tid, len(raw)))
            w(raw)
        w(struct.pack("<I", len(b.ops)))
        for opcode, ins, out, attrs in b.ops:
            w(struct.pack("<II", opcode, len(ins)))
            if ins:
                w(struct.pack(f"<{len(ins)}I", *ins))
            w(struct.pack("<II", out, len(attrs)))
            if attrs:
                w(struct.pack(f"<{len(attrs)}q", *attrs))
