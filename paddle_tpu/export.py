"""Merged-model export: one self-contained inference artifact.

Reference analog: ``paddle merge_model`` (trainer/MergeModel.cpp) packs
ModelConfig proto + weights into a single file consumed by the C
inference API (paddle/capi gradient_machine loading).

TPU-native design: instead of a config proto + a C++ engine to interpret
it, the whole forward graph is compiled and serialized as **StableHLO**
via ``jax.export`` with the trained weights baked in as constants. The
artifact is a zip with the serialized executable plus a json manifest of
input/output specs. Loading needs no layer library at all — any PJRT
runtime (incl. the C API used by capi_runtime.cpp) can execute it, which
is the capability the reference's merged model + capi pair provided.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.parameters import Parameters
from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.topology import LayerOutput, Topology

_FORMAT_VERSION = 1


def merge_model(output_layers, parameters: Parameters, path: str,
                batch_size: Optional[int] = None) -> None:
    """Compile forward(feeds) with weights baked in and write ``path``.

    ``batch_size=None`` exports with a symbolic batch dimension (any
    batch size at load time); an int pins it."""
    import jax
    from jax import export as jexport

    outs = output_layers if isinstance(output_layers, (list, tuple)) \
        else [output_layers]
    topo = Topology(list(outs))
    state = topo.init_state()
    params = {k: np.asarray(v) for k, v in parameters.as_dict().items()}

    data_nodes = [n for n in topo.nodes if n.layer_type == "data"]
    data_nodes.sort(key=lambda n: getattr(n, "declare_idx", 0))
    feed_specs = []
    for n in data_nodes:
        enforce_that(not n.is_sequence,
                     "merge_model currently exports dense-input graphs "
                     "(sequence feeds carry host-side ragged metadata)",
                     context="export")
        if "INTEGER" in str(getattr(n.input_type, "kind", "")).upper() \
                or getattr(n.input_type, "dtype", None) == "int32":
            dtype = "int32"
            shape: Tuple = ()
        else:
            dtype = "float32"
            shape = (n.size,)
        feed_specs.append({"name": n.name, "dtype": dtype,
                           "feature_shape": list(shape)})

    if batch_size is None:
        (b,) = jexport.symbolic_shape("b")
    else:
        b = int(batch_size)

    args = tuple(
        jax.ShapeDtypeStruct((b,) + tuple(s["feature_shape"]),
                             np.dtype(s["dtype"]))
        for s in feed_specs)

    def forward(*feed_vals):
        feeds = {s["name"]: v for s, v in zip(feed_specs, feed_vals)}
        outs_v, _ = topo.forward(params, state, feeds, train=False)
        return tuple(o.data if hasattr(o, "segment_ids") else o
                     for o in outs_v)

    exported = jexport.export(jax.jit(forward))(*args)
    blob = exported.serialize()

    manifest = {
        "format_version": _FORMAT_VERSION,
        "inputs": feed_specs,
        "outputs": [n.name for n in outs],
        "symbolic_batch": batch_size is None,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("manifest.json", json.dumps(manifest))
        z.writestr("model.stablehlo", blob)


class MergedModel:
    """Loaded merged model: ``infer(feeds)`` with no layer library needed
    (the capi paddle_gradient_machine_create_for_inference analog)."""

    def __init__(self, path: str):
        from jax import export as jexport

        with zipfile.ZipFile(path) as z:
            self.manifest = json.loads(z.read("manifest.json"))
            enforce_that(
                self.manifest.get("format_version") == _FORMAT_VERSION,
                "unsupported merged-model version", context="export")
            self._exported = jexport.deserialize(z.read("model.stablehlo"))
        self.input_names = [s["name"] for s in self.manifest["inputs"]]
        self.output_names = self.manifest["outputs"]

    def infer(self, feeds: Dict[str, np.ndarray]) -> List[np.ndarray]:
        args = []
        for spec in self.manifest["inputs"]:
            enforce_that(spec["name"] in feeds,
                         f"missing feed {spec['name']!r}", context="export")
            args.append(np.asarray(feeds[spec["name"]],
                                   dtype=np.dtype(spec["dtype"])))
        outs = self._exported.call(*args)
        return [np.asarray(o) for o in outs]


def load_merged_model(path: str) -> MergedModel:
    return MergedModel(path)
