"""Inference API (reference: python/paddle/v2/inference.py — Inference/infer).

``Inference`` compiles a test-mode forward of the requested output layers and
runs it over a reader or feed dict.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.parameters import Parameters
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import LayerOutput, Topology


class Inference:
    def __init__(self, output_layer, parameters: Parameters,
                 model_state=None):
        outputs = [output_layer] if isinstance(output_layer, LayerOutput) else list(output_layer)
        self.topology = Topology(outputs)
        self.parameters = parameters
        # merge the caller's (possibly larger, training-topology) state over
        # init defaults: shared namespaces get trained values, anything the
        # inference graph needs but the caller lacks falls back to init
        init = self.topology.init_state()
        if model_state is not None:
            for ns in init:
                if ns in model_state:
                    init[ns] = {**init[ns], **model_state[ns]}
        self.model_state = init
        from paddle_tpu.analysis.retrace import audit_jit

        self._fn = audit_jit(self._forward, site="inference.forward")

    def _forward(self, params, state, feeds):
        outs, _ = self.topology.forward(params, state, feeds, train=False)
        return outs

    def iter_infer(self, input, feeding=None):
        data_types = [(n.name, n.input_type) for n in self.topology.data_nodes]
        feeder = DataFeeder(data_types, feeding)
        params = self.parameters.as_dict()
        for batch in input:
            feeds = feeder.feed(batch)
            yield self._fn(params, self.model_state, feeds)

    def infer(self, input, feeding=None, field: str = "value",
              batch_size: int = 256):
        """input: a list of sample tuples (v2 semantics); batched internally.

        The final partial batch is PADDED (repeating the last sample) and
        the padded rows sliced off the result, so the tail reuses an
        already-compiled jit specialization instead of compiling a fresh
        one per distinct tail size: with multiple batches the tail pads
        up to ``batch_size`` (sharing the full-batch executable); a
        single short batch pads to the next power of two (a bounded
        bucket ladder across calls).  Topologies with SEQUENCE outputs
        keep the exact tail (padded samples would concatenate extra
        tokens into the packed output that no batch-axis slice can
        remove)."""
        n = len(input)
        if n == 0:
            return None
        batches = [input[i:i + batch_size]
                   for i in range(0, n, batch_size)]
        tail = len(batches[-1])
        if any(o.is_sequence for o in self.topology.outputs):
            target = tail
        elif len(batches) > 1:
            target = batch_size
        else:
            target = 1
            while target < tail:
                target *= 2
        pad = target - tail
        if pad:
            batches[-1] = list(batches[-1]) + [input[-1]] * pad
        results: List[List[np.ndarray]] = None
        for outs in self.iter_infer(batches, feeding):
            arrays = [_to_numpy(o) for o in outs]
            if results is None:
                results = [[a] for a in arrays]
            else:
                for acc, a in zip(results, arrays):
                    acc.append(a)
        if results is None:
            return None
        merged = [np.concatenate(parts, axis=0) if parts[0].ndim else np.stack(parts)
                  for parts in results]
        if pad:
            # slice the padding off every output whose leading axis is
            # the (padded) batch; other shapes (packed sequences,
            # reductions) pass through untouched
            merged = [a[:n] if a.ndim and a.shape[0] == n + pad else a
                      for a in merged]
        return merged[0] if len(merged) == 1 else merged


def _to_numpy(o):
    if isinstance(o, SequenceBatch):
        return np.asarray(o.data)
    return np.asarray(o)


def infer(output_layer, parameters: Parameters, input, feeding=None,
          field: str = "value", model_state=None, batch_size: int = 256):
    """One-shot inference.  ``model_state`` forwards a trainer's model
    state (batch-norm moving statistics) so trained stats are used
    without constructing :class:`Inference` directly."""
    return Inference(output_layer, parameters,
                     model_state=model_state).infer(
        input, feeding=feeding, field=field, batch_size=batch_size)
