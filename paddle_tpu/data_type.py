"""Input type descriptors for data layers and the DataFeeder.

Reference: python/paddle/v2/data_type.py re-exporting PyDataProvider2 slot
types (dense_vector, sparse_binary_vector, sparse_float_vector, integer_value,
plus *_sequence and *_sub_sequence variants — PyDataProvider2.cpp slot/seq
types).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class SeqKind(Enum):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class SlotKind(Enum):
    DENSE = 0
    SPARSE_BINARY = 1
    SPARSE_FLOAT = 2
    INDEX = 3


@dataclass(frozen=True)
class InputType:
    dim: int
    slot: SlotKind
    seq: SeqKind = SeqKind.NO_SEQUENCE


def dense_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE)


def dense_array(dim: int) -> InputType:  # alias used by some v2 code
    return InputType(dim, SlotKind.DENSE)


def sparse_binary_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY)


def sparse_float_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_FLOAT)


def integer_value(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX)


def dense_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE, SeqKind.SEQUENCE)


def sparse_binary_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY, SeqKind.SEQUENCE)


def sparse_float_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.SPARSE_FLOAT, SeqKind.SEQUENCE)


def integer_value_sequence(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX, SeqKind.SEQUENCE)


def dense_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE, SeqKind.SUB_SEQUENCE)


def integer_value_sub_sequence(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX, SeqKind.SUB_SEQUENCE)
