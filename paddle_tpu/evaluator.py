"""Evaluators — in-graph metric layers (the gserver/evaluators analog).

Reference: paddle/gserver/evaluators/Evaluator.h:42-72 + REGISTER_EVALUATOR
list (classification_error, sum, rankauc, pnpair, precision_recall,
ctc_edit_distance, chunk, seq_classification_error + printers) and
python/paddle/trainer_config_helpers/evaluators.py.

Each evaluator returns a metric LayerOutput; the trainer computes it per batch
in-graph (cheap — fused into the step) and averages across the pass. Pass them
to ``trainer.SGD(..., extra_layers=[...])`` exactly like the v2 API.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.ops import losses as ploss
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import LayerOutput, unique_name

__all__ = ["classification_error", "sum", "column_sum", "auc",
           "precision_recall", "pnpair", "seq_classification_error",
           "value_printer", "maxid_printer"]


def _data_of(v):
    return v.data if isinstance(v, SequenceBatch) else v


def _metric_node(name, ltype, inputs, fn) -> LayerOutput:
    node = LayerOutput(name=name, layer_type=ltype, inputs=inputs, fn=fn, size=1)
    node.is_metric = True
    return node


def classification_error(input, label, top_k: int = 1, weight=None,
                         name: Optional[str] = None) -> LayerOutput:
    """Top-k error rate (reference: classification_error_evaluator)."""
    name = name or unique_name("classification_error_evaluator")
    inputs = [input, label] + ([weight] if weight is not None else [])

    def compute(ctx, p, ins):
        logits, lab = ins[0], ins[1]

        def f(lg, lb):
            lb = lb.reshape(lb.shape[0]).astype(jnp.int32)
            return ploss.classification_error(lg, lb, top_k)

        if isinstance(logits, SequenceBatch):
            err = f(logits.data, _data_of(lab))
            return logits.with_data(jnp.where(logits.valid_mask, err, 0.0))
        err = f(logits, lab)
        if weight is not None:
            err = err * _data_of(ins[2]).reshape(-1)
        return err

    return _metric_node(name, "classification_error_evaluator", inputs, compute)


def seq_classification_error(input, label, name: Optional[str] = None) -> LayerOutput:
    """Per-sequence all-token-correct error (reference:
    seq_classification_error_evaluator): a sequence counts as wrong if ANY
    token is wrong."""
    name = name or unique_name("seq_classification_error_evaluator")

    def compute(ctx, p, ins):
        sb, lab = ins[0], ins[1]
        err = ploss.classification_error(sb.data, _data_of(lab).reshape(-1))
        seg = jnp.where(sb.valid_mask, sb.segment_ids, sb.num_seqs)
        any_err = jax.ops.segment_max(jnp.where(sb.valid_mask, err, 0.0), seg,
                                      num_segments=sb.num_seqs + 1)[: sb.num_seqs]
        return any_err

    return _metric_node(name, "seq_classification_error_evaluator",
                        [input, label], compute)


def sum(input, name: Optional[str] = None) -> LayerOutput:
    """Sum evaluator (reference: sum_evaluator)."""
    name = name or unique_name("sum_evaluator")

    def compute(ctx, p, ins):
        v = ins[0]
        d = _data_of(v)
        out = d.reshape(d.shape[0], -1).sum(-1)
        if isinstance(v, SequenceBatch):
            return v.with_data(jnp.where(v.valid_mask, out, 0.0))
        return out

    return _metric_node(name, "sum_evaluator", [input], compute)


def column_sum(input, name: Optional[str] = None) -> LayerOutput:
    """Column-mean evaluator (reference: column_sum_evaluator)."""
    name = name or unique_name("column_sum_evaluator")

    def compute(ctx, p, ins):
        return _data_of(ins[0]).mean(-1)

    return _metric_node(name, "column_sum_evaluator", [input], compute)


def auc(input, label, name: Optional[str] = None) -> LayerOutput:
    """Batch AUC via rank statistic (reference: auc_evaluator/AucEvaluator).

    Uses the Mann-Whitney U formulation on the positive-class score.
    """
    name = name or unique_name("auc_evaluator")

    def compute(ctx, p, ins):
        scores = _data_of(ins[0])
        if scores.ndim > 1 and scores.shape[-1] > 1:
            scores = scores[..., 1]  # P(class=1)
        scores = scores.reshape(-1)
        y = _data_of(ins[1]).reshape(-1).astype(jnp.float32)
        order = jnp.argsort(scores)
        ranks = jnp.zeros_like(scores).at[order].set(
            jnp.arange(1, scores.shape[0] + 1, dtype=scores.dtype))
        n_pos = jnp.sum(y)
        n_neg = y.shape[0] - n_pos
        u = jnp.sum(ranks * y) - n_pos * (n_pos + 1) / 2.0
        auc_val = jnp.where((n_pos > 0) & (n_neg > 0),
                            u / jnp.maximum(n_pos * n_neg, 1.0), 0.5)
        return jnp.broadcast_to(auc_val, (1,))

    return _metric_node(name, "auc_evaluator", [input, label], compute)


def pnpair(input, label, query_id, name: Optional[str] = None) -> LayerOutput:
    """Positive-negative pair ratio within queries (reference:
    pnpair_evaluator). Simplified: global pos/neg pair ratio per batch."""
    name = name or unique_name("pnpair_evaluator")

    def compute(ctx, p, ins):
        s = _data_of(ins[0]).reshape(-1)
        y = _data_of(ins[1]).reshape(-1).astype(jnp.float32)
        q = _data_of(ins[2]).reshape(-1)
        same_q = q[:, None] == q[None, :]
        better = (y[:, None] > y[None, :]) & same_q
        correct = jnp.sum(jnp.where(better & (s[:, None] > s[None, :]), 1.0, 0.0))
        total = jnp.maximum(jnp.sum(jnp.where(better, 1.0, 0.0)), 1.0)
        return jnp.broadcast_to(correct / total, (1,))

    return _metric_node(name, "pnpair_evaluator", [input, label, query_id], compute)


def precision_recall(input, label, name: Optional[str] = None) -> LayerOutput:
    """Macro F1 proxy (reference: precision_recall_evaluator). Emits the
    batch F1 for the positive class of binary problems, else accuracy."""
    name = name or unique_name("precision_recall_evaluator")

    def compute(ctx, p, ins):
        logits = _data_of(ins[0])
        y = _data_of(ins[1]).reshape(-1).astype(jnp.int32)
        pred = jnp.argmax(logits, -1).astype(jnp.int32)
        tp = jnp.sum(jnp.where((pred == 1) & (y == 1), 1.0, 0.0))
        fp = jnp.sum(jnp.where((pred == 1) & (y == 0), 1.0, 0.0))
        fn = jnp.sum(jnp.where((pred == 0) & (y == 1), 1.0, 0.0))
        prec = tp / jnp.maximum(tp + fp, 1.0)
        rec = tp / jnp.maximum(tp + fn, 1.0)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
        return jnp.broadcast_to(f1, (1,))

    return _metric_node(name, "precision_recall_evaluator", [input, label], compute)


def value_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Host-side value printer (reference: value_printer_evaluator) — uses
    jax.debug.print so it works under jit."""
    name = name or unique_name("value_printer_evaluator")

    def compute(ctx, p, ins):
        v = _data_of(ins[0])
        jax.debug.print(name + ": {}", v)
        return jnp.zeros((1,))

    return _metric_node(name, "value_printer_evaluator", [input], compute)


def maxid_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Prints argmax ids (reference: maxid_printer_evaluator)."""
    name = name or unique_name("maxid_printer_evaluator")

    def compute(ctx, p, ins):
        v = _data_of(ins[0])
        jax.debug.print(name + ": {}", jnp.argmax(v, -1))
        return jnp.zeros((1,))

    return _metric_node(name, "maxid_printer_evaluator", [input], compute)


def rankauc(input, label, weight=None, name: Optional[str] = None) -> LayerOutput:
    """AUC over ranking scores (reference: rankauc_evaluator →
    RankAucEvaluator.cpp). Same statistic as auc but reads a raw score
    column instead of a 2-class distribution."""
    name = name or unique_name("rankauc_evaluator")
    inputs = [input, label] + ([weight] if weight is not None else [])

    def compute(ctx, p, ins):
        score = _data_of(ins[0]).reshape(-1)
        y = _data_of(ins[1]).reshape(-1).astype(jnp.float32)
        w = (_data_of(ins[2]).reshape(-1) if weight is not None
             else jnp.ones_like(score))
        # weighted Mann-Whitney with tie correction, O(N log N): sort by
        # score; per element find its tie group via searchsorted, then
        # AUC = sum_neg w_n (P_above + 0.5 P_equal) / (W_pos W_neg)
        pos_w = w * y
        neg_w = w * (1.0 - y)
        order = jnp.argsort(score)
        s_ = score[order]
        pw, nw = pos_w[order], neg_w[order]
        cpos = jnp.cumsum(pw)
        total_pos = cpos[-1]
        total_neg = jnp.sum(nw)
        lo = jnp.searchsorted(s_, s_, side="left")
        hi = jnp.searchsorted(s_, s_, side="right")
        pos_below = jnp.where(lo > 0, cpos[jnp.maximum(lo - 1, 0)], 0.0)
        pos_in_group = cpos[hi - 1] - pos_below
        pos_above = total_pos - pos_below - pos_in_group
        num = jnp.sum(nw * (pos_above + 0.5 * pos_in_group))
        den = jnp.maximum(total_pos * total_neg, 1e-8)
        return jnp.broadcast_to(num / den, (1,))

    return _metric_node(name, "rankauc_evaluator", inputs, compute)


def chunk(input, label, num_chunk_types: int,
          chunk_scheme: str = "IOB", name: Optional[str] = None) -> LayerOutput:
    """Chunk F1 for sequence labeling (reference: chunk_evaluator →
    ChunkEvaluator.cpp). IOB encoding: tag 2t = B-type_t, 2t+1 = I-type_t,
    2*num_chunk_types = O."""
    name = name or unique_name("chunk_evaluator")
    if chunk_scheme not in ("IOB", "plain"):
        raise ValueError(f"unsupported chunk scheme {chunk_scheme}")
    plain = chunk_scheme == "plain"
    # id layout: IOB → 2t=B-t, 2t+1=I-t, O=2T; plain → t=chunk type, O=T
    O = num_chunk_types if plain else 2 * num_chunk_types

    def type_of(tags):
        return tags if plain else tags // 2

    def starts_of(tags, prev_tags, valid):
        """IOB: starts at B-t or non-continuing I-t. plain: starts where
        the type differs from the previous token's."""
        in_c = tags < O
        prev_in = prev_tags < O
        if plain:
            cont = in_c & prev_in & (prev_tags == tags)
            return valid & in_c & ~cont
        is_b = (tags % 2 == 0) & in_c
        is_i = (tags % 2 == 1) & in_c
        cont = is_i & prev_in & (type_of(prev_tags) == type_of(tags))
        return valid & (is_b | (is_i & ~cont))

    def compute(ctx, p, ins):
        pred_v, lab_v = ins[0], ins[1]
        pred = _data_of(pred_v)
        if pred.ndim > 1 and pred.shape[-1] > 1:
            pred = jnp.argmax(pred, -1)
        pred = pred.reshape(-1).astype(jnp.int32)
        lab = _data_of(lab_v).reshape(-1).astype(jnp.int32)
        if isinstance(pred_v, SequenceBatch):
            seg = pred_v.segment_ids
            valid = pred_v.valid_mask
        else:
            seg = jnp.zeros_like(pred)
            valid = jnp.ones_like(pred, dtype=bool)
        n = pred.shape[0]
        idx = jnp.arange(n)

        def shift_prev(tags):
            prev = jnp.concatenate([jnp.array([O], jnp.int32), tags[:-1]])
            prev_seg = jnp.concatenate([jnp.array([-1], seg.dtype), seg[:-1]])
            return jnp.where(seg != prev_seg, O, prev)

        def ends_of(tags, starts):
            """Chunk ends where in-chunk and the next token starts a new
            chunk / is O / is another sequence (conlleval endOfChunk)."""
            in_c = valid & (tags < O)
            nxt_start = jnp.concatenate([starts[1:], jnp.array([True])])
            nxt_tag = jnp.concatenate([tags[1:], jnp.array([O], jnp.int32)])
            nxt_seg = jnp.concatenate([seg[1:], jnp.array([-1], seg.dtype)])
            nxt_valid = jnp.concatenate([valid[1:], jnp.array([False])])
            broken = nxt_start | (nxt_tag >= O) | (nxt_seg != seg) | ~nxt_valid
            return in_c & broken

        ps = starts_of(pred, shift_prev(pred), valid)
        ls = starts_of(lab, shift_prev(lab), valid)
        pe = ends_of(pred, ps)
        le = ends_of(lab, ls)
        # conlleval: a chunk is correct iff its start, end, and type all
        # coincide. last_start[i] = most recent start position <= i.
        last_ps = jax.lax.cummax(jnp.where(ps, idx, -1))
        last_ls = jax.lax.cummax(jnp.where(ls, idx, -1))
        safe_p = jnp.maximum(last_ps, 0)
        safe_l = jnp.maximum(last_ls, 0)
        type_eq = type_of(pred[safe_p]) == type_of(lab[safe_l])
        correct = jnp.sum(jnp.where(
            pe & le & (last_ps == last_ls) & (last_ps >= 0) & type_eq,
            1.0, 0.0))
        n_pred = jnp.maximum(jnp.sum(ps.astype(jnp.float32)), 1e-8)
        n_lab = jnp.maximum(jnp.sum(ls.astype(jnp.float32)), 1e-8)
        f1 = 2 * correct / (n_pred + n_lab)
        return jnp.broadcast_to(f1, (1,))

    return _metric_node(name, "chunk_evaluator", [input, label], compute)


def ctc_edit_distance(input, label, blank: Optional[int] = None,
                      name: Optional[str] = None) -> LayerOutput:
    """Normalized edit distance between the CTC best-path decode of `input`
    and `label` (reference: ctc_edit_distance → CTCErrorEvaluator.cpp).

    input: prob sequence [tokens, C] (blank defaults to C-1);
    label: int sequence. Levenshtein runs as a fixed-shape DP over the
    static capacities (masked past true lengths) under jit."""
    name = name or unique_name("ctc_edit_distance_evaluator")

    def compute(ctx, p, ins):
        probs, lab = ins[0], ins[1]
        blank_id = blank if blank is not None else probs.data.shape[-1] - 1
        path = jnp.argmax(probs.data, -1).astype(jnp.int32)   # [cap]
        labd = _data_of(lab).reshape(-1).astype(jnp.int32)

        n_seq = probs.num_seqs
        capP, capL = path.shape[0], labd.shape[0]
        segP, segL = probs.segment_ids, lab.segment_ids

        def per_seq(s):
            # best-path collapse: keep where != prev and != blank
            in_s = segP == s
            prev = jnp.concatenate([jnp.array([-1], jnp.int32), path[:-1]])
            prev_in = jnp.concatenate([jnp.array([False]), in_s[:-1]])
            keep = in_s & (path != blank_id) & ((path != prev) | ~prev_in)
            # compact decoded ids to the front (static shape capP)
            order = jnp.argsort(~keep, stable=True)
            dec = jnp.where(keep[order], path[order], -1)
            m = jnp.sum(keep.astype(jnp.int32))
            lab_in = segL == s
            orderL = jnp.argsort(~lab_in, stable=True)
            ref = jnp.where(lab_in[orderL], labd[orderL], -2)
            n = jnp.sum(lab_in.astype(jnp.int32))

            # Levenshtein DP rows over ref (length capL), cols over dec
            row0 = jnp.arange(capP + 1, dtype=jnp.float32)

            def dp(row, j_ref):
                j, r = j_ref
                active = j < n
                sub = row[:-1] + jnp.where(dec == r, 0.0, 1.0)
                dele = row[1:] + 1.0

                def inner(carry, xs):
                    s_, d_ = xs
                    best = jnp.minimum(jnp.minimum(s_, d_), carry + 1.0)
                    return best, best
                _, rest = jax.lax.scan(inner, row[0] + 1.0, (sub, dele))
                new_row = jnp.concatenate([(row[0] + 1.0)[None], rest])
                return jnp.where(active, new_row, row), None

            rowN, _ = jax.lax.scan(
                dp, row0, (jnp.arange(capL), ref))
            dist = rowN[m]
            return dist / jnp.maximum(n.astype(jnp.float32), 1.0)

        dists = jax.vmap(per_seq)(jnp.arange(n_seq))
        return jnp.mean(dists)[None]

    return _metric_node(name, "ctc_edit_distance_evaluator", [input, label],
                        compute)


def detection_map(detections, label, num_classes: int, keep_top_k: int,
                  max_boxes: int = 16, overlap_threshold: float = 0.5,
                  background_id: int = 0,
                  name: Optional[str] = None) -> LayerOutput:
    """11-point interpolated mAP over a batch (reference:
    detection_map_evaluator → DetectionMAPEvaluator.cpp).

    detections: detection_output layer ([B, K*6] label/score/box rows);
    label: dense [B, max_boxes*5] gt (class, box), class<0 = pad."""
    from paddle_tpu.ops.detection import iou_matrix
    name = name or unique_name("detection_map_evaluator")

    def compute(ctx, p, ins):
        det = _data_of(ins[0]).reshape(-1, keep_top_k, 6)
        gt = _data_of(ins[1]).reshape(det.shape[0], max_boxes, 5)

        def tp_flags(det_i, gt_i):
            """Greedy match in (already score-sorted) order; one gt each."""
            iou = iou_matrix(det_i[:, 2:6], gt_i[:, 1:5])   # [K, G]
            cls_ok = det_i[:, 0:1] == gt_i[None, :, 0]
            valid_gt = gt_i[None, :, 0] >= 0
            cand = iou * jnp.where(cls_ok & valid_gt, 1.0, 0.0)

            def body(used, k):
                row = jnp.where(used, 0.0, cand[k])
                j = jnp.argmax(row)
                hit = (row[j] >= overlap_threshold) & (det_i[k, 0] >= 0)
                used = used.at[j].set(used[j] | hit)
                return used, hit
            _, hits = jax.lax.scan(body,
                                   jnp.zeros(gt_i.shape[0], bool),
                                   jnp.arange(det_i.shape[0]))
            return hits

        hits = jax.vmap(tp_flags)(det, gt)                  # [B, K]
        flat_scores = jnp.where(det[:, :, 0] >= 0, det[:, :, 1],
                                -jnp.inf).reshape(-1)
        flat_cls = det[:, :, 0].reshape(-1)
        flat_tp = hits.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(-flat_scores)
        tp_sorted = flat_tp[order]
        valid = jnp.isfinite(flat_scores[order])
        cls_sorted = flat_cls[order]

        def ap_for(c):
            sel = (cls_sorted == c) & valid
            tp_c = jnp.where(sel, tp_sorted, 0.0)
            cum_tp = jnp.cumsum(tp_c)
            cum_n = jnp.cumsum(sel.astype(jnp.float32))
            n_gt = jnp.sum(jnp.where(gt[:, :, 0] == c, 1.0, 0.0))
            prec = cum_tp / jnp.maximum(cum_n, 1.0)
            rec = cum_tp / jnp.maximum(n_gt, 1.0)
            pts = jnp.linspace(0.0, 1.0, 11)
            ap = jnp.mean(jax.vmap(
                lambda r: jnp.max(jnp.where(rec >= r, prec, 0.0)))(pts))
            return jnp.where(n_gt > 0, ap, jnp.nan)

        cls_ids = jnp.array([c for c in range(num_classes)
                             if c != background_id])
        aps = jax.vmap(ap_for)(cls_ids.astype(jnp.float32))
        return jnp.nanmean(aps)[None]

    return _metric_node(name, "detection_map_evaluator",
                        [detections, label], compute)


def gradient_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Prints the gradient flowing through this node during backward
    (reference: gradient_printer_evaluator). Implemented as an identity
    with a custom vjp that debug-prints its cotangent — faithful to the
    reference even though autodiff is whole-program here."""
    name = name or unique_name("gradient_printer_evaluator")

    @jax.custom_vjp
    def probe(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        jax.debug.print(name + " grad: {}", g)
        return (g,)

    probe.defvjp(fwd, bwd)

    def compute(ctx, p, ins):
        v = ins[0]
        d = probe(_data_of(v))
        if isinstance(v, SequenceBatch):
            return v.with_data(d)
        return d

    node = _metric_node(name, "gradient_printer_evaluator", [input], compute)
    node.size = input.size
    node.is_sequence = input.is_sequence
    return node


def max_frame_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Prints the frame with the max value per sequence (reference:
    max_frame_printer_evaluator)."""
    name = name or unique_name("max_frame_printer_evaluator")

    def compute(ctx, p, ins):
        v = ins[0]
        d = _data_of(v)
        score = d.reshape(d.shape[0], -1).max(-1)
        if isinstance(v, SequenceBatch):
            score = jnp.where(v.valid_mask, score, -jnp.inf)
        jax.debug.print(name + ": frame {}", jnp.argmax(score))
        return jnp.zeros((1,))

    return _metric_node(name, "max_frame_printer_evaluator", [input], compute)


def seq_text_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Prints sequence token ids (reference: seq_text_printer_evaluator;
    the id→word file mapping is host-side in the reference too)."""
    name = name or unique_name("seq_text_printer_evaluator")

    def compute(ctx, p, ins):
        v = ins[0]
        d = _data_of(v)
        ids = d if d.ndim == 1 else jnp.argmax(d, -1)
        jax.debug.print(name + ": {}", ids)
        return jnp.zeros((1,))

    return _metric_node(name, "seq_text_printer_evaluator", [input], compute)


def classification_error_printer(input, label,
                                 name: Optional[str] = None) -> LayerOutput:
    """Prints the per-sample 0/1 error vector (reference:
    classification_error_printer_evaluator)."""
    name = name or unique_name("classification_error_printer_evaluator")

    def compute(ctx, p, ins):
        logits = _data_of(ins[0])
        y = _data_of(ins[1]).reshape(-1).astype(jnp.int32)
        err = (jnp.argmax(logits, -1).astype(jnp.int32) != y)
        jax.debug.print(name + ": {}", err.astype(jnp.int32))
        return jnp.zeros((1,))

    return _metric_node(name, "classification_error_printer_evaluator",
                        [input, label], compute)


__all__ += ["rankauc", "chunk", "ctc_edit_distance", "detection_map",
            "gradient_printer", "max_frame_printer", "seq_text_printer",
            "classification_error_printer"]
