"""Evaluators — in-graph metric layers (the gserver/evaluators analog).

Reference: paddle/gserver/evaluators/Evaluator.h:42-72 + REGISTER_EVALUATOR
list (classification_error, sum, rankauc, pnpair, precision_recall,
ctc_edit_distance, chunk, seq_classification_error + printers) and
python/paddle/trainer_config_helpers/evaluators.py.

Each evaluator returns a metric LayerOutput; the trainer computes it per batch
in-graph (cheap — fused into the step) and averages across the pass. Pass them
to ``trainer.SGD(..., extra_layers=[...])`` exactly like the v2 API.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.ops import losses as ploss
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import LayerOutput, unique_name

__all__ = ["classification_error", "sum", "column_sum", "auc",
           "precision_recall", "pnpair", "seq_classification_error",
           "value_printer", "maxid_printer"]


def _data_of(v):
    return v.data if isinstance(v, SequenceBatch) else v


def _metric_node(name, ltype, inputs, fn) -> LayerOutput:
    node = LayerOutput(name=name, layer_type=ltype, inputs=inputs, fn=fn, size=1)
    node.is_metric = True
    return node


def classification_error(input, label, top_k: int = 1, weight=None,
                         name: Optional[str] = None) -> LayerOutput:
    """Top-k error rate (reference: classification_error_evaluator)."""
    name = name or unique_name("classification_error_evaluator")
    inputs = [input, label] + ([weight] if weight is not None else [])

    def compute(ctx, p, ins):
        logits, lab = ins[0], ins[1]

        def f(lg, lb):
            lb = lb.reshape(lb.shape[0]).astype(jnp.int32)
            return ploss.classification_error(lg, lb, top_k)

        if isinstance(logits, SequenceBatch):
            err = f(logits.data, _data_of(lab))
            return logits.with_data(jnp.where(logits.valid_mask, err, 0.0))
        err = f(logits, lab)
        if weight is not None:
            err = err * _data_of(ins[2]).reshape(-1)
        return err

    return _metric_node(name, "classification_error_evaluator", inputs, compute)


def seq_classification_error(input, label, name: Optional[str] = None) -> LayerOutput:
    """Per-sequence all-token-correct error (reference:
    seq_classification_error_evaluator): a sequence counts as wrong if ANY
    token is wrong."""
    name = name or unique_name("seq_classification_error_evaluator")

    def compute(ctx, p, ins):
        sb, lab = ins[0], ins[1]
        err = ploss.classification_error(sb.data, _data_of(lab).reshape(-1))
        seg = jnp.where(sb.valid_mask, sb.segment_ids, sb.num_seqs)
        any_err = jax.ops.segment_max(jnp.where(sb.valid_mask, err, 0.0), seg,
                                      num_segments=sb.num_seqs + 1)[: sb.num_seqs]
        return any_err

    return _metric_node(name, "seq_classification_error_evaluator",
                        [input, label], compute)


def sum(input, name: Optional[str] = None) -> LayerOutput:
    """Sum evaluator (reference: sum_evaluator)."""
    name = name or unique_name("sum_evaluator")

    def compute(ctx, p, ins):
        v = ins[0]
        d = _data_of(v)
        out = d.reshape(d.shape[0], -1).sum(-1)
        if isinstance(v, SequenceBatch):
            return v.with_data(jnp.where(v.valid_mask, out, 0.0))
        return out

    return _metric_node(name, "sum_evaluator", [input], compute)


def column_sum(input, name: Optional[str] = None) -> LayerOutput:
    """Column-mean evaluator (reference: column_sum_evaluator)."""
    name = name or unique_name("column_sum_evaluator")

    def compute(ctx, p, ins):
        return _data_of(ins[0]).mean(-1)

    return _metric_node(name, "column_sum_evaluator", [input], compute)


def auc(input, label, name: Optional[str] = None) -> LayerOutput:
    """Batch AUC via rank statistic (reference: auc_evaluator/AucEvaluator).

    Uses the Mann-Whitney U formulation on the positive-class score.
    """
    name = name or unique_name("auc_evaluator")

    def compute(ctx, p, ins):
        scores = _data_of(ins[0])
        if scores.ndim > 1 and scores.shape[-1] > 1:
            scores = scores[..., 1]  # P(class=1)
        scores = scores.reshape(-1)
        y = _data_of(ins[1]).reshape(-1).astype(jnp.float32)
        order = jnp.argsort(scores)
        ranks = jnp.zeros_like(scores).at[order].set(
            jnp.arange(1, scores.shape[0] + 1, dtype=scores.dtype))
        n_pos = jnp.sum(y)
        n_neg = y.shape[0] - n_pos
        u = jnp.sum(ranks * y) - n_pos * (n_pos + 1) / 2.0
        auc_val = jnp.where((n_pos > 0) & (n_neg > 0),
                            u / jnp.maximum(n_pos * n_neg, 1.0), 0.5)
        return jnp.broadcast_to(auc_val, (1,))

    return _metric_node(name, "auc_evaluator", [input, label], compute)


def pnpair(input, label, query_id, name: Optional[str] = None) -> LayerOutput:
    """Positive-negative pair ratio within queries (reference:
    pnpair_evaluator). Simplified: global pos/neg pair ratio per batch."""
    name = name or unique_name("pnpair_evaluator")

    def compute(ctx, p, ins):
        s = _data_of(ins[0]).reshape(-1)
        y = _data_of(ins[1]).reshape(-1).astype(jnp.float32)
        q = _data_of(ins[2]).reshape(-1)
        same_q = q[:, None] == q[None, :]
        better = (y[:, None] > y[None, :]) & same_q
        correct = jnp.sum(jnp.where(better & (s[:, None] > s[None, :]), 1.0, 0.0))
        total = jnp.maximum(jnp.sum(jnp.where(better, 1.0, 0.0)), 1.0)
        return jnp.broadcast_to(correct / total, (1,))

    return _metric_node(name, "pnpair_evaluator", [input, label, query_id], compute)


def precision_recall(input, label, name: Optional[str] = None) -> LayerOutput:
    """Macro F1 proxy (reference: precision_recall_evaluator). Emits the
    batch F1 for the positive class of binary problems, else accuracy."""
    name = name or unique_name("precision_recall_evaluator")

    def compute(ctx, p, ins):
        logits = _data_of(ins[0])
        y = _data_of(ins[1]).reshape(-1).astype(jnp.int32)
        pred = jnp.argmax(logits, -1).astype(jnp.int32)
        tp = jnp.sum(jnp.where((pred == 1) & (y == 1), 1.0, 0.0))
        fp = jnp.sum(jnp.where((pred == 1) & (y == 0), 1.0, 0.0))
        fn = jnp.sum(jnp.where((pred == 0) & (y == 1), 1.0, 0.0))
        prec = tp / jnp.maximum(tp + fp, 1.0)
        rec = tp / jnp.maximum(tp + fn, 1.0)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
        return jnp.broadcast_to(f1, (1,))

    return _metric_node(name, "precision_recall_evaluator", [input, label], compute)


def value_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Host-side value printer (reference: value_printer_evaluator) — uses
    jax.debug.print so it works under jit."""
    name = name or unique_name("value_printer_evaluator")

    def compute(ctx, p, ins):
        v = _data_of(ins[0])
        jax.debug.print(name + ": {}", v)
        return jnp.zeros((1,))

    return _metric_node(name, "value_printer_evaluator", [input], compute)


def maxid_printer(input, name: Optional[str] = None) -> LayerOutput:
    """Prints argmax ids (reference: maxid_printer_evaluator)."""
    name = name or unique_name("maxid_printer_evaluator")

    def compute(ctx, p, ins):
        v = _data_of(ins[0])
        jax.debug.print(name + ": {}", jnp.argmax(v, -1))
        return jnp.zeros((1,))

    return _metric_node(name, "maxid_printer_evaluator", [input], compute)
