"""Beam-search sequence generation — the SequenceGenerator analog.

Reference: paddle/api/SequenceGenerator.cpp:38-96 (host loop: forward one
step, top-k expand, prune to beam, stop at EOS) and the in-graph
RecurrentGradientMachine::generateSequence/beamSearch
(RecurrentGradientMachine.cpp:539, .h:307-342) with GeneratedInput
(trainer_config_helpers layers.py beam_search).

TPU-native: the whole beam loop is ONE ``lax.scan`` over max_length inside
jit — beams are a batch dimension (B*K flattening), beam reordering is a
gather, EOS handling is masking. No per-step host round trips (the reference
paid a full python→C++ forward per token).
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.attr import ParamAttr
from paddle_tpu.ops.embedding import embedding_lookup
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.recurrent import (StaticInput, group_state_slots,
                                  make_static_node, pin_param_names,
                                  read_group_state, resolve_memory_links,
                                  trace_step)
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import (Context, LayerOutput, ParamSpec, Topology,
                                 unique_name)

__all__ = ["GeneratedInput", "BeamState", "beam_search"]


class BeamState(NamedTuple):
    """Read-only beam snapshot handed to the user control hooks (the analog
    of the reference's beam-search callback arguments,
    RecurrentGradientMachine.h:73-148).

    All fields are traced jax arrays — hooks run INSIDE the compiled beam
    scan, so they must be jax-traceable (no data-dependent python control
    flow; use jnp.where). ``t`` is the current expansion index."""

    t: jax.Array          # scalar int32 — expansion step
    tokens: jax.Array     # [B, K] int32 — last token of each beam
    scores: jax.Array     # [B, K] f32  — cumulative log-prob per beam
    finished: jax.Array   # [B, K] bool — beams that already emitted EOS
    lengths: jax.Array    # [B, K] int32 — generated length per beam


class GeneratedInput:
    """The token fed back from the previous beam step, embedded (reference:
    GeneratedInput in trainer_config_helpers)."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int):
        self.size = size                    # vocabulary size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


def beam_search(step, input, bos_id: int, eos_id: int, beam_size: int = 5,
                max_length: int = 30, name: Optional[str] = None,
                candidate_adjust: Optional[Callable] = None,
                host_candidate_adjust: Optional[Callable] = None,
                path_filter: Optional[Callable] = None,
                stop_condition: Optional[Callable] = None) -> LayerOutput:
    """Generate with beam search. ``step(*frame_args)`` must return the
    per-step *probability* layer ([B*K, vocab], softmax output), exactly like
    the reference's beam_search step contract.

    The returned node's value is ``(tokens [B, K, max_length] int32,
    lengths [B, K] int32, scores [B, K] float32)`` — beams sorted best-first.
    Evaluate it with paddle.infer / Inference.

    User control hooks (reference: RecurrentGradientMachine.h:73-148
    ``beamSearchCandidateAdjust``/``stopBeamSearch`` + the host-loop
    SequenceGenerator, api/SequenceGenerator.cpp:38-96):

    - ``candidate_adjust(logp, beam)``: traced into the beam step. ``logp``
      is the [B, K, V] continuation log-probs of the LIVE beams before the
      finished-beam freeze; return an adjusted [B, K, V] (e.g. set a column
      to -1e9 to forbid a token, add lexical bonuses, length penalties via
      ``beam.lengths``). ``beam`` is a :class:`BeamState`.
    - ``host_candidate_adjust(logp, tokens, t)``: the escape hatch for
      python logic jnp can't express — runs on HOST via
      ``jax.pure_callback`` with numpy arrays ([B,K,V] f32, [B,K] i32,
      () i32) and must return a [B,K,V] array. It must be PURE: JAX may
      cache, elide, or re-invoke it, so hooks must not rely on
      exactly-once side effects (mutable blacklists, counters — use
      ``jax.experimental.io_callback`` semantics yourself if you need
      ordering). COST: one device→host→device round trip per generated
      token and an XLA fusion break; prefer ``candidate_adjust`` whenever
      the logic is expressible in jnp (SURVEY §7: host callbacks are
      dispatch-bound, ~O(ms) per step over PCIe/ICI).
    - ``path_filter(beam)``: called AFTER top-k selection with the new
      :class:`BeamState`; return a [B, K] bool keep-mask. Dropped beams get
      score -1e9, so any surviving alternative outranks them from then on
      (the reference's candidate-drop). If a row's beams are ALL dropped,
      top-k must still pick K continuations, so filtered prefixes can
      reappear with ~-1e9 scores — callers enforcing hard constraints
      should treat scores below ~-1e8 as "no hypothesis satisfied the
      filter".
    - ``stop_condition(beam)``: return a [] or [B] bool; once true for a
      batch row, that row's beams freeze and remaining steps pass through
      (XLA's static-shape analog of the reference's stopBeamSearch — the
      compiled scan still runs max_length iterations, but frozen rows do
      no state updates, so results match an early exit).
    """
    name = name or unique_name("beam_search")
    inputs = input if isinstance(input, (list, tuple)) else [input]

    gen: Optional[GeneratedInput] = None
    static_inputs: List[StaticInput] = []
    frame_args: List[LayerOutput] = []
    static_nodes: List[LayerOutput] = []
    gen_node: Optional[LayerOutput] = None

    for item in inputs:
        if isinstance(item, GeneratedInput):
            enforce_that(gen is None, "only one GeneratedInput allowed",
                         context="beam_search")
            gen = item
            gen_node = LayerOutput(name=unique_name(f"{name}_token_emb"),
                                   layer_type="frame", inputs=[], fn=None,
                                   size=item.embedding_size, is_sequence=False)
            frame_args.append(gen_node)
        elif isinstance(item, StaticInput):
            node = make_static_node(name, item)
            static_inputs.append(item)
            static_nodes.append(node)
            frame_args.append(node)
        else:
            raise EnforceError(
                "beam_search inputs must be GeneratedInput or StaticInput",
                context="beam_search")
    enforce_that(gen is not None, "beam_search needs a GeneratedInput",
                 context="beam_search")

    prob_layer, memories = trace_step(step, frame_args)
    enforce_that(not isinstance(prob_layer, (list, tuple)),
                 "beam_search step must return a single probability layer",
                 context="beam_search")

    for m in memories:
        enforce_that(not getattr(m, "is_seq", False),
                     "beam_search steps use dense memories (sequence "
                     "memories belong to hierarchical recurrent_groups)",
                     context="beam_search")
    link_nodes = resolve_memory_links(Topology([prob_layer]), memories,
                                      "beam_search")
    sub_topo = Topology([prob_layer] + link_nodes)

    outer_inputs = [s.input for s in static_inputs] + \
        [m.boot_layer for m in memories if m.boot_layer is not None]

    # pin canonical names so generation shares weights with the training
    # recurrent_group built from the same step (see recurrent.py)
    group_params = pin_param_names(sub_topo)
    emb_key = gen.embedding_name
    if emb_key not in group_params:
        group_params[emb_key] = ParamSpec(
            (gen.size, gen.embedding_size), ParamAttr(name=emb_key))

    n_static = len(static_inputs)
    K = beam_size
    V = gen.size
    NEG = -1e9

    def compute(ctx: Context, p, ins):
        static_vals = ins[:n_static]
        boot_vals = ins[n_static:]
        emb_table = p[emb_key]

        # batch size from the first boot/static input (boots are enforced
        # non-sequence at memory() creation, so shape[0] is B)
        if boot_vals:
            B = boot_vals[0].shape[0]
        elif static_vals:
            sv = static_vals[0]
            B = sv.num_seqs if isinstance(sv, SequenceBatch) else sv.shape[0]
        else:
            raise EnforceError("beam_search needs a static or boot input to "
                               "infer batch size", context="beam_search")

        # tile statics across beams: dense [B,D] -> [B*K,D]; sequences are
        # beam-tiled by repeating sequence entries
        tiled_statics = []
        for sv in static_vals:
            if isinstance(sv, SequenceBatch):
                padded, _ = sv.to_padded()
                D = padded.shape[-1]
                T = padded.shape[1]
                rep = jnp.repeat(padded, K, axis=0)  # [B*K, T, D]
                lens = jnp.repeat(sv.lengths, K, axis=0)
                tiled_statics.append(SequenceBatch.from_padded(
                    rep, lens, capacity=B * K * T))
            else:
                tiled_statics.append(jnp.repeat(sv, K, axis=0))

        init_mems = {}
        bi = 0
        for m in memories:
            if m.boot_layer is not None:
                bv = boot_vals[bi]
                bi += 1
                init_mems[m.node.name] = jnp.repeat(bv.astype(jnp.float32), K, axis=0)
            else:
                init_mems[m.node.name] = jnp.zeros((B * K, m.size), jnp.float32)

        # trained sub-layer state (batch_norm moving stats) comes in through
        # namespaces keyed by the SUB-LAYER names — shared with the training
        # recurrent_group built from the same stably-named step, so a
        # trainer's model_state plugs in directly (not a fresh init_state,
        # which would normalise with untrained statistics)
        sub_state = read_group_state(ctx, sub_topo)
        rngkey = ctx.rng_for(ctx._current or name)

        init = {
            "tokens": jnp.full((B, K), bos_id, jnp.int32),
            "scores": jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG)
                       * jnp.ones((B, 1)),
            "finished": jnp.zeros((B, K), bool),
            "lengths": jnp.zeros((B, K), jnp.int32),
            "stopped": jnp.zeros((B,), bool),
            "mems": init_mems,
        }

        def beam_step(state, t):
            cur = state["tokens"].reshape(B * K)
            emb = embedding_lookup(emb_table, cur)  # [B*K, E]
            feeds = {gen_node.name: emb}
            for node, sv in zip(static_nodes, tiled_statics):
                feeds[node.name] = sv
            for m in memories:
                feeds[m.node.name] = state["mems"][m.node.name]
            outs, _st = sub_topo.forward(p, sub_state, feeds, train=False,
                                         rng=rngkey)
            probs = outs[0]
            probs = probs.data if isinstance(probs, SequenceBatch) else probs
            logp = jnp.log(jnp.clip(probs, 1e-20, 1.0)).reshape(B, K, V)

            fin = state["finished"]
            beam_now = BeamState(t, state["tokens"], state["scores"], fin,
                                 state["lengths"])
            if candidate_adjust is not None:
                logp = candidate_adjust(logp, beam_now)
            if host_candidate_adjust is not None:
                def _host(lp, tk, tt):
                    return np.asarray(
                        host_candidate_adjust(lp, tk, tt), np.float32)
                logp = jax.pure_callback(
                    _host, jax.ShapeDtypeStruct(logp.shape, jnp.float32),
                    logp.astype(jnp.float32), state["tokens"], t)
            # finished beams: freeze (only 'eos' continuation at zero cost) —
            # applied AFTER the user adjust so hooks cannot unfreeze them
            cont = jnp.where(fin[..., None],
                             jnp.where(jnp.arange(V)[None, None, :] == eos_id,
                                       0.0, NEG),
                             logp)
            total = state["scores"][..., None] + cont          # [B, K, V]
            flat = total.reshape(B, K * V)
            top_scores, top_idx = jax.lax.top_k(flat, K)        # [B, K]
            parent = top_idx // V
            token = (top_idx % V).astype(jnp.int32)

            batch_ix = jnp.arange(B)[:, None]
            new_fin = fin[batch_ix, parent] | (token == eos_id)
            new_len = state["lengths"][batch_ix, parent] + \
                jnp.where(fin[batch_ix, parent], 0, 1)
            if path_filter is not None:
                keep = path_filter(BeamState(t, token, top_scores, new_fin,
                                             new_len))
                top_scores = jnp.where(keep, top_scores, NEG)
            new_mems = {}
            for mi, m in enumerate(memories):
                lo = outs[1 + mi]
                val = (lo.data if isinstance(lo, SequenceBatch) else lo)
                val = val.reshape(B, K, -1)
                keep_prev = state["mems"][m.node.name].reshape(B, K, -1)
                # finished beams keep their memory
                sel = jnp.where(fin[batch_ix, parent][..., None],
                                keep_prev[batch_ix, parent],
                                val[batch_ix, parent])
                new_mems[m.node.name] = sel.reshape(B * K, -1)

            # rows already stopped by stop_condition: pass everything
            # through untouched and emit identity parents so backtracking
            # reconstructs the frozen sequences
            stopped = state["stopped"]
            if stop_condition is not None:
                row = stopped[:, None]
                token = jnp.where(row, jnp.full_like(token, eos_id), token)
                parent = jnp.where(
                    row, jnp.broadcast_to(jnp.arange(K)[None, :], (B, K)),
                    parent)
                top_scores = jnp.where(row, state["scores"], top_scores)
                new_fin = jnp.where(row, fin, new_fin)
                new_len = jnp.where(row, state["lengths"], new_len)
                new_mems = {
                    k: jnp.where(jnp.repeat(stopped, K)[:, None],
                                 state["mems"][k], v)
                    for k, v in new_mems.items()}
                stop_now = jnp.asarray(stop_condition(
                    BeamState(t, token, top_scores, new_fin, new_len)))
                stopped = stopped | jnp.broadcast_to(stop_now, (B,))

            new_state = {
                "tokens": token,
                "scores": top_scores,
                "finished": new_fin,
                "lengths": new_len,
                "stopped": stopped,
                "mems": new_mems,
            }
            return new_state, (token, parent)

        final, (toks, parents) = jax.lax.scan(
            beam_step, init, jnp.arange(max_length, dtype=jnp.int32))

        # backtrack beam parents to recover full sequences [B, K, T]
        def back(nxt_beam, tp):
            tok_t, par_t = tp   # [B, K]
            batch_ix = jnp.arange(B)[:, None]
            beam_here = par_t[batch_ix, nxt_beam]
            tok_here = tok_t[batch_ix, nxt_beam]
            return beam_here, tok_here

        last_beam = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, seq_rev = jax.lax.scan(back, last_beam, (toks, parents),
                                  reverse=True)
        tokens = jnp.moveaxis(seq_rev, 0, 2)   # [B, K, T]
        # mask tokens after eos with eos
        t_ix = jnp.arange(max_length)[None, None, :]
        valid = t_ix < final["lengths"][..., None]
        tokens = jnp.where(valid, tokens, eos_id)
        return tokens, final["lengths"], final["scores"]

    node = LayerOutput(name=name, layer_type="beam_search", inputs=outer_inputs,
                       fn=compute, params=group_params,
                       foreign_state=group_state_slots(sub_topo),
                       size=max_length, is_sequence=False)
    node.beam_size = beam_size
    node.max_length = max_length
    return node
