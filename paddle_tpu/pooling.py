"""Pooling descriptors for sequence pooling and image pooling.

Reference: python/paddle/trainer_config_helpers/poolings.py (MaxPooling,
AvgPooling, SumPooling, SquareRootNPooling, CudnnMaxPooling/CudnnAvgPooling).
"""

from __future__ import annotations


class BasePoolingType:
    name = "base"


class MaxPooling(BasePoolingType):
    name = "max"

    def __init__(self, output_max_index: bool = False):
        self.output_max_index = output_max_index


class AvgPooling(BasePoolingType):
    name = "avg"


class SumPooling(BasePoolingType):
    name = "sum"


class SqrtNPooling(BasePoolingType):
    """sum / sqrt(len) — the reference's SquareRootNPooling."""

    name = "sqrtn"


def get(arg) -> BasePoolingType:
    if arg is None:
        return MaxPooling()
    if isinstance(arg, BasePoolingType):
        return arg
    if isinstance(arg, type) and issubclass(arg, BasePoolingType):
        return arg()
    if isinstance(arg, str):
        table = {c.name: c for c in [MaxPooling, AvgPooling, SumPooling, SqrtNPooling]}
        return table[arg]()
    raise TypeError(f"cannot resolve pooling from {arg!r}")
