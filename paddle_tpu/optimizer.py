"""Optimizers, LR schedules, regularizers — the paddle/parameter optimizer suite.

Reference: paddle/parameter/FirstOrderOptimizer.h:24-346 (Sgd/SparseMomentum/
Adagrad/AdaDelta/RMSProp/DecayedAdagrad/Adam/Adamax + OptimizerWithGradient
Clipping), AverageOptimizer.h:23, Regularizer.h, LearningRateScheduler.cpp:
50-172 (constant, poly, caffe_poly, exp, discexp, linear, manual, pass_manual),
and python/paddle/v2/optimizer.py.

TPU-native design: an optimizer is a *pure transform* — ``init_state(params)``
builds the slot pytree (the reference's MOMENTUM/GRADIENT_SQURESUM buffers),
``apply(params, grads, state, step)`` returns new params+state. Everything
is jit-friendly and shards with the params under pjit (ZeRO-style optimizer
state sharding falls out for free — see parallel/).

Per-parameter attrs (lr mult, decay, static, clipping) come from ParamSpecs.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.topology import ParamSpec

# ---------------------------------------------------------------------------
# learning-rate schedules (LearningRateScheduler.cpp analog)
# ---------------------------------------------------------------------------


def make_lr_schedule(args: Dict[str, Any]) -> Callable[[jax.Array], jax.Array]:
    """Build step -> lr-multiplier fn from v1-style config keys:
    learning_rate_schedule ∈ {constant, poly, caffe_poly, exp, discexp,
    linear, manual, pass_manual}, with learning_rate_decay_a/_b and
    learning_rate_args (reference: LearningRateScheduler.cpp:50-172)."""
    kind = args.get("learning_rate_schedule", "constant")
    a = float(args.get("learning_rate_decay_a", 0.0))
    b = float(args.get("learning_rate_decay_b", 0.0))
    spec = args.get("learning_rate_args", "")

    if kind == "constant":
        return lambda step: jnp.ones(())
    if kind == "poly":
        return lambda step: jnp.power(1.0 + a * step, -b)
    if kind == "caffe_poly":
        return lambda step: jnp.power(jnp.maximum(0.0, 1.0 - step / a), b)
    if kind == "exp":
        return lambda step: jnp.power(a, step / b)
    if kind == "discexp":
        return lambda step: jnp.power(a, jnp.floor(step / b))
    if kind == "linear":
        return lambda step: jnp.maximum(1.0 - a * step, b)
    if kind in ("manual", "pass_manual"):
        # "seg1:lr1,seg2:lr2,..." — segments by sample count (manual) or pass
        segs = []
        for part in str(spec).split(","):
            if not part:
                continue
            s, lr = part.split(":")
            segs.append((float(s), float(lr)))
        enforce_that(len(segs) > 0, f"empty {kind} schedule", context="optimizer")
        bounds = jnp.asarray([s for s, _ in segs])
        rates = jnp.asarray([r for _, r in segs])

        def manual(step):
            idx = jnp.searchsorted(bounds, step, side="left")
            return rates[jnp.minimum(idx, len(segs) - 1)]

        return manual
    raise EnforceError(f"unknown lr schedule {kind!r}", context="optimizer")


# ---------------------------------------------------------------------------
# base optimizer
# ---------------------------------------------------------------------------


class Optimizer:
    """Base: handles lr schedule, per-param multipliers, decay, clipping,
    model averaging. Subclasses implement ``_update(g, slots, lr)``."""

    def __init__(self, learning_rate: float = 1e-3,
                 regularization=None, gradient_clipping_threshold: float = 0.0,
                 model_average=None, **sched_args):
        self.learning_rate = learning_rate
        self.schedule = make_lr_schedule(sched_args)
        self.regularization = regularization
        self.global_clip = float(gradient_clipping_threshold or 0.0)
        self.model_average = model_average
        self._specs: Dict[str, ParamSpec] = {}
        self._zero_plan = None  # ZeRO-1 shard plan (parallel/zero.py)

    # -- wiring ------------------------------------------------------------

    def set_param_specs(self, specs: Dict[str, ParamSpec]) -> None:
        self._specs = dict(specs)

    def _attr(self, name):
        spec = self._specs.get(name)
        return spec.attr if spec is not None else None

    def set_zero_plan(self, plan) -> None:
        """Enable ZeRO-1 optimizer-state sharding (parallel/zero.py): slot
        state is allocated and updated as padded 1/N flat shards per
        replica; params/grads pass through the same shard view around
        ``_update``.  One wrapper for every optimizer — subclasses keep
        their elementwise ``_update`` untouched."""
        self._zero_plan = plan

    # -- slots -------------------------------------------------------------

    def slot_names(self) -> Tuple[str, ...]:
        return ()

    def init_state(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        # prune masks are value-quantile-based: always computed on the FULL
        # tensors (a padded flat view would skew the quantile with zeros)
        masks = self._make_prune_masks(params)
        if self._zero_plan is not None:
            # hand _init_state the flat sharded views so every slot
            # (zeros_like and param-copy alike) is BORN sharded — no device
            # ever materializes a replicated slot of a planned param
            params = self._zero_plan.shard_tree(params)
        state = self._init_state(params)
        if masks:
            state["prune_masks"] = (self._zero_plan.shard_tree(masks)
                                    if self._zero_plan is not None else masks)
        return state

    def _init_state(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        """Build the slot pytree from (possibly ZeRO-shard-view) params."""
        slots = {
            s: {k: jnp.zeros_like(v) for k, v in params.items()}
            for s in self.slot_names()
        }
        state = {"step": jnp.zeros((), jnp.int32), "slots": slots}
        if self.model_average is not None:
            state["avg"] = {k: jnp.array(v) for k, v in params.items()}
            state["avg_count"] = jnp.zeros(())
        return state

    def _make_prune_masks(self, params) -> Dict[str, jax.Array]:
        """Static pruning masks from initial weights (StaticPruningHook,
        ParameterUpdaterHook.cpp:39-104): keep the largest
        (1 - sparsity_ratio) fraction by |value|. The reference partial-sorts
        on the host; a quantile threshold is the O(n) XLA-friendly analog."""
        from paddle_tpu.attr import HookAttr

        masks = {}
        for name, p in params.items():
            attr = self._attr(name)
            if attr is None:
                continue
            for hook in HookAttr.to_hooks(getattr(attr, "update_hooks", None)):
                enforce_that(hook.type == "pruning",
                             f"unknown update hook {hook.type!r}",
                             context="optimizer")
                thresh = jnp.quantile(jnp.abs(p).astype(jnp.float32).ravel(),
                                      float(hook.sparsity_ratio))
                masks[name] = (jnp.abs(p) >= thresh).astype(p.dtype)
        return masks

    def prune_mask(self, state, name: str):
        return state.get("prune_masks", {}).get(name)

    # -- update ------------------------------------------------------------

    def _update(self, name: str, p: jax.Array, g: jax.Array,
                slots: Dict[str, jax.Array], lr: jax.Array, step: jax.Array
                ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    # optional scalar recursions computed once per apply (SparseMomentum's
    # alpha/beta/tau); default: stateless
    def _pre_update(self, state, base_lr):
        return None

    def _post_update(self, new_state, aux) -> None:
        pass

    def apply(self, params: Dict[str, jax.Array], grads: Dict[str, jax.Array],
              state: Dict[str, Any]) -> Tuple[Dict[str, jax.Array], Dict[str, Any]]:
        plan = self._zero_plan
        if plan is None:
            return self._apply(params, grads, state)
        # ZeRO-1 (arXiv 2004.13336): grads reduce-scatter into 1/N flat
        # shards (GSPMD lowers psum + this constraint into psum_scatter),
        # the whole update pipeline below runs on the shard views (slot
        # state already lives flat-sharded), and the updated weights
        # all-gather back to full replicated tensors.
        new_flat, new_state = self._apply(plan.shard_tree(params),
                                          plan.shard_tree(grads), state)
        return plan.gather_tree(new_flat), new_state

    def _apply(self, params: Dict[str, jax.Array], grads: Dict[str, jax.Array],
               state: Dict[str, Any]) -> Tuple[Dict[str, jax.Array], Dict[str, Any]]:
        step = state["step"]
        base_lr = self.learning_rate * self.schedule(step.astype(jnp.float32))
        self._aux = self._pre_update(state, base_lr)

        # global-norm clipping (reference: OptimizerWithGradientClipping used
        # per-parameter thresholds; pjit-era default is global norm, and
        # per-param thresholds from ParamAttr are applied below)
        if self.global_clip > 0.0:
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in grads.values()))
            scale = jnp.minimum(1.0, self.global_clip / jnp.maximum(gnorm, 1e-12))
            grads = {k: g * scale for k, g in grads.items()}

        new_params: Dict[str, jax.Array] = {}
        new_slots = {s: {} for s in self.slot_names()}
        for name, p in params.items():
            g = grads[name]
            attr = self._attr(name)
            if attr is not None and attr.is_static:
                new_params[name] = p
                for s in self.slot_names():
                    new_slots[s][name] = state["slots"][s][name]
                continue
            if attr is not None and attr.gradient_clipping_threshold > 0.0:
                t = attr.gradient_clipping_threshold
                g = jnp.clip(g, -t, t)
            # decay (regularizer): applied as grad += decay * p, the
            # reference's L2Regularizer semantics; L1 adds sign(p)*decay.
            l1, l2 = 0.0, 0.0
            if self.regularization is not None:
                l1 = getattr(self.regularization, "l1", 0.0)
                l2 = getattr(self.regularization, "l2", 0.0)
            if attr is not None:
                l1 = attr.l1_decay or l1
                l2 = attr.l2_decay or l2
            if l2:
                g = g + l2 * p
            if l1:
                g = g + l1 * jnp.sign(p)
            mask = self.prune_mask(state, name)
            if mask is not None:
                # StaticPruningHook.update: grad *= mask before the rule
                g = g * mask
            lr = base_lr * (attr.learning_rate if attr is not None else 1.0)
            slots = {s: state["slots"][s][name] for s in self.slot_names()}
            np_, ns = self._update(name, p, g.astype(p.dtype), slots, lr, step)
            if mask is not None:
                # and value *= mask (the hook's init masking, re-asserted so
                # weight decay/averaging can never resurrect pruned weights)
                np_ = np_ * mask
            new_params[name] = np_
            for s in self.slot_names():
                new_slots[s][name] = ns[s]

        new_state = {"step": step + 1, "slots": new_slots}
        if "prune_masks" in state:
            new_state["prune_masks"] = state["prune_masks"]
        self._post_update(new_state, self._aux)
        if self.model_average is not None:
            w = self.model_average.average_window
            decay = jnp.minimum(state["avg_count"] / (state["avg_count"] + 1.0),
                                jnp.asarray(1.0 - 1.0 / max(1.0, w * 1000)))
            new_state["avg"] = {
                k: decay * state["avg"][k] + (1 - decay) * new_params[k]
                for k in new_params
            }
            new_state["avg_count"] = state["avg_count"] + 1.0
        return new_params, new_state


# ---------------------------------------------------------------------------
# concrete optimizers (FirstOrderOptimizer.h analogs)
# ---------------------------------------------------------------------------


class Sgd(Optimizer):
    """Plain SGD (reference: SgdOptimizer)."""

    def _update(self, name, p, g, slots, lr, step):
        return p - lr * g, {}


class Momentum(Optimizer):
    """Heavy-ball momentum; the reference folds momentum into Parameter
    MOMENTUM buffers (SgdOptimizer with momentum / SparseMomentumParameter
    Optimizer for the sparse path)."""

    def __init__(self, momentum: float = 0.9, sparse: bool = False, **kw):
        super().__init__(**kw)
        self.momentum = momentum
        self.sparse = sparse

    def slot_names(self):
        return ("momentum",)

    def _update(self, name, p, g, slots, lr, step):
        m = self.momentum * slots["momentum"] - lr * g
        return p + m, {"momentum": m}


class SparseMomentum(Optimizer):
    """Lazy-momentum scheme (reference SparseMomentumParameterOptimizer,
    FirstOrderOptimizer.h:61-125 / .cpp:30-115): momentum refactored into
    two additive accumulators u, v plus scalar recursions

        tau_t = tau_{t-1} + beta_t / alpha_t
        alpha_t = alpha_{t-1} / k,   beta_t = beta_{t-1} / (1 + lambda*lr)
        u_t = u_{t-1} - alpha_t*lr*g_t,   v_t = v_{t-1} + tau_t*alpha_t*lr*g_t
        theta_t = (tau_t/beta_t + 1/alpha_t)*u_t + v_t/beta_t

    so untouched (sparse) rows need no per-step work. Mathematically equal
    to heavy-ball momentum for decay_rate=0 (verified in
    tests/test_optimizers_hooks.py). alpha grows as k^-t, so past the
    reference's 1e6 threshold the scalars restart (u /= alpha, v = theta) —
    here as a jit-friendly masked select instead of a special traversal."""

    def __init__(self, momentum: float = 0.9, decay_rate: float = 0.0,
                 threshold: float = 1e6, **kw):
        super().__init__(**kw)
        enforce_that(0.0 < momentum < 1.0,
                     "SparseMomentum needs 0 < momentum < 1",
                     context="optimizer")
        self.momentum = momentum
        self.decay_rate = decay_rate
        self.threshold = threshold

    def slot_names(self):
        return ("u", "v")

    def _init_state(self, params):
        state = super()._init_state(params)
        # v_0 = theta_0 (the reference's first-touch assign, t0Vec_)
        state["slots"]["v"] = {k: jnp.array(v) for k, v in params.items()}
        state["sm"] = {"alpha": jnp.ones(()), "beta": jnp.ones(()),
                       "tau": -jnp.ones(())}
        return state

    def _pre_update(self, state, base_lr):
        sm = state["sm"]
        tau = sm["tau"] + sm["beta"] / sm["alpha"]
        alpha = sm["alpha"] / self.momentum
        beta = sm["beta"] / (1.0 + self.decay_rate * base_lr)
        return {"tau": tau, "alpha": alpha, "beta": beta, "lr": base_lr}

    def _update(self, name, p, g, slots, lr, step):
        a = self._aux
        tau, alpha, beta = a["tau"], a["alpha"], a["beta"]
        # per-param lr multipliers scale g via lr/base_lr
        scale = lr / jnp.maximum(a["lr"], 1e-30)
        u = slots["u"] - alpha * a["lr"] * scale * g
        v = slots["v"] + tau * alpha * a["lr"] * scale * g
        theta = (tau / beta + 1.0 / alpha) * u + v / beta
        # numeric restart (needSpecialTraversal): alpha ~ k^-t diverges
        restart = alpha > self.threshold
        u = jnp.where(restart, u / alpha, u)
        v = jnp.where(restart, theta, v)
        return theta, {"u": u, "v": v}

    def _post_update(self, new_state, aux) -> None:
        restart = aux["alpha"] > self.threshold
        one = jnp.ones(())
        new_state["sm"] = {
            "alpha": jnp.where(restart, one, aux["alpha"]),
            "beta": jnp.where(restart, one, aux["beta"]),
            "tau": jnp.where(restart, -one, aux["tau"]),
        }


class Adagrad(Optimizer):
    """Reference: AdagradParameterOptimizer (FirstOrderOptimizer.h:106)."""

    def __init__(self, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.eps = epsilon

    def slot_names(self):
        return ("accum",)

    def _update(self, name, p, g, slots, lr, step):
        acc = slots["accum"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self.eps), {"accum": acc}


class AdaDelta(Optimizer):
    """Reference: AdaDeltaParameterOptimizer (rou/epsilon)."""

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.eps = rho, epsilon

    def slot_names(self):
        return ("accum_g", "accum_dx")

    def _update(self, name, p, g, slots, lr, step):
        ag = self.rho * slots["accum_g"] + (1 - self.rho) * jnp.square(g)
        dx = -jnp.sqrt((slots["accum_dx"] + self.eps) / (ag + self.eps)) * g
        adx = self.rho * slots["accum_dx"] + (1 - self.rho) * jnp.square(dx)
        return p + lr * dx, {"accum_g": ag, "accum_dx": adx}


class RMSProp(Optimizer):
    """Reference: RMSPropParameterOptimizer (rou, epsilon, +mean-grad term)."""

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.eps = rho, epsilon

    def slot_names(self):
        return ("accum_g", "accum_mean")

    def _update(self, name, p, g, slots, lr, step):
        ag = self.rho * slots["accum_g"] + (1 - self.rho) * jnp.square(g)
        am = self.rho * slots["accum_mean"] + (1 - self.rho) * g
        denom = jnp.sqrt(ag - jnp.square(am) + self.eps)
        return p - lr * g / denom, {"accum_g": ag, "accum_mean": am}


class DecayedAdagrad(Optimizer):
    """Reference: DecayedAdagradParameterOptimizer."""

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.eps = rho, epsilon

    def slot_names(self):
        return ("accum",)

    def _update(self, name, p, g, slots, lr, step):
        acc = self.rho * slots["accum"] + (1 - self.rho) * jnp.square(g)
        return p - lr * g / jnp.sqrt(acc + self.eps), {"accum": acc}


class Adam(Optimizer):
    """Reference: AdamParameterOptimizer (FirstOrderOptimizer.h:274)."""

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, **kw):
        super().__init__(**kw)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def slot_names(self):
        return ("m", "v")

    def _update(self, name, p, g, slots, lr, step):
        t = step.astype(jnp.float32) + 1.0
        m = self.b1 * slots["m"] + (1 - self.b1) * g
        v = self.b2 * slots["v"] + (1 - self.b2) * jnp.square(g)
        mhat = m / (1 - jnp.power(self.b1, t))
        vhat = v / (1 - jnp.power(self.b2, t))
        return p - lr * mhat / (jnp.sqrt(vhat) + self.eps), {"m": m, "v": v}


class Adamax(Optimizer):
    """Reference: AdamaxParameterOptimizer (FirstOrderOptimizer.h:313)."""

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999, **kw):
        super().__init__(**kw)
        self.b1, self.b2 = beta1, beta2

    def slot_names(self):
        return ("m", "u")

    def _update(self, name, p, g, slots, lr, step):
        t = step.astype(jnp.float32) + 1.0
        m = self.b1 * slots["m"] + (1 - self.b1) * g
        u = jnp.maximum(self.b2 * slots["u"], jnp.abs(g))
        return p - (lr / (1 - jnp.power(self.b1, t))) * m / (u + 1e-12), \
            {"m": m, "u": u}


# ---------------------------------------------------------------------------
# regularization / model average config objects (v2 API surface)
# ---------------------------------------------------------------------------


class L2Regularization:
    def __init__(self, rate: float):
        self.l1, self.l2 = 0.0, rate


class L1Regularization:
    def __init__(self, rate: float):
        self.l1, self.l2 = rate, 0.0


class L1L2Regularization:
    def __init__(self, l1: float, l2: float):
        self.l1, self.l2 = l1, l2


class ModelAverage:
    """Running average of parameters for eval (reference: AverageOptimizer.h,
    v2 ModelAverage(average_window=...))."""

    def __init__(self, average_window: float = 0.1,
                 max_average_window: Optional[int] = None):
        self.average_window = average_window
        self.max_average_window = max_average_window
