"""``python -m paddle_tpu.resilience`` — run / check the fault-tolerant
training runtime.

``run [--save-dir D] [--seed N] [--json]``
    Demo + operator entry point: restart a seeded chaos training run
    (kills mid-pass, a kill between blob write and meta commit, injected
    NaN gradients, a slow-step window) across injected deaths under the
    resume supervisor, against an uninterrupted control, and print one
    JSON summary line (restarts, skipped bad steps, parity, checkpoint
    stall/write split).

``check``
    The tier-1 gate (the fleet-check convention): run the same seeded
    chaos replay PLUS the torn-save probe and exit 0 only when every
    acceptance invariant holds — final params bit-identical to control,
    every death resumed from a verified checkpoint, injected non-finite
    steps skipped with optimizer slots untouched, zero corrupt surviving
    artifacts, and the kill-between-blob-and-meta case leaving the
    previous checkpoint loadable.  Findings print one line each (plus
    any ``CKPT-CORRUPT`` lines from the loader) and exit 1;
    ``tools_tier1.sh`` branches on this exit status into ladder exit 10.
    A crash exits 2.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from typing import List, Optional


def _run_scenarios(save_dir: Optional[str], seed: int) -> dict:
    import os
    import shutil

    from paddle_tpu.resilience.chaos import seeded_chaos, torn_save_probe

    if save_dir is None:
        tmp = tempfile.mkdtemp(prefix="paddle_tpu_resilience_")
        save_dir = tmp
    chaos_dir = os.path.join(save_dir, "chaos")
    torn_dir = os.path.join(save_dir, "torn")
    # the replay owns these two scratch subdirs: stale checkpoints from
    # a previous invocation would make attempt 0 resume at a completed
    # cursor and falsely fail the parity assertions
    for d in (chaos_dir, torn_dir):
        shutil.rmtree(d, ignore_errors=True)
    out = seeded_chaos(chaos_dir, seed=seed)
    probe = torn_save_probe(torn_dir, seed=seed + 1)
    out["problems"] = list(out["problems"]) + list(probe["problems"])
    probe.pop("problems")
    out.update(probe)
    out["save_dir"] = save_dir
    return out


def cmd_run(args) -> int:
    out = _run_scenarios(args.save_dir, args.seed)
    problems = out.pop("problems")
    out["ok"] = int(not problems)
    print(json.dumps(out), flush=True)
    for p in problems:
        print(f"resilience: {p}", flush=True)
    return 0 if not problems else 1

def cmd_check(args) -> int:
    import shutil

    out = _run_scenarios(None, 0)
    # the gate's scratch dir is always a fresh tempdir: remove it, or
    # every CI invocation would leak a checkpoint-filled tree in /tmp
    shutil.rmtree(out.pop("save_dir"), ignore_errors=True)
    problems: List[str] = out.pop("problems")
    if problems:
        for p in problems:
            print(f"CKPT-CHECK: {p}", flush=True)
        print(f"CKPT-CORRUPT-GATE: {len(problems)} finding(s) — the "
              "chaos replay's checkpoint/resume invariants do not hold",
              flush=True)
        return 1
    print(f"resilience check ok: {out['train_chaos_deaths']} injected "
          f"deaths resumed, {out['train_chaos_bad_steps_skipped']} bad "
          f"steps skipped, params bit-identical to control, "
          f"0 corrupt artifacts", flush=True)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.resilience",
        description="fault-tolerant training runtime: chaos run + gate")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="supervised seeded-chaos training "
                                   "demo; prints one JSON summary line")
    p.add_argument("--save-dir", default=None,
                   help="checkpoint root (default: a fresh temp dir). "
                        "The replay owns and CLEARS the chaos/ and "
                        "torn/ subdirs under it on every invocation")
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("check", help="tier-1 gate: seeded chaos replay + "
                                     "torn-save probe; exit 1 on any "
                                     "violated invariant (ladder exit 10)")
    p.set_defaults(fn=cmd_check)

    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except SystemExit:
        raise
    except BaseException as e:   # crash != findings: distinct exit code
        print(f"resilience checker crashed: {e!r}", flush=True)
        return 2


if __name__ == "__main__":
    sys.exit(main())
