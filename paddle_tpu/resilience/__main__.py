import sys

from paddle_tpu.resilience.cli import main

sys.exit(main())
