"""Async checkpoint writer: training stalls only for the device->host
copy, never the disk write.

The synchronous ``checkpoint.save_checkpoint`` gathers (ZeRO), copies to
host, tars, pickles AND md5s while the train loop waits.  The
:class:`AsyncCheckpointer` splits that along the line
``checkpoint.snapshot_checkpoint`` / ``checkpoint.write_checkpoint``
already draws:

- :meth:`save` runs the SNAPSHOT phase inline (the device->host copy
  must happen before the train loop donates those buffers into the next
  step) and hands the host-resident payload to ONE background writer
  thread for the tar/pkl/meta commit (tmp+rename+md5, meta last);
- depth-one pipelining: a new :meth:`save` first waits out the previous
  write, so at most one write is in flight and commit order equals
  submit order;
- :meth:`wait` is the durability barrier (the elastic trainer acks
  master tasks only past it) and the error surface: a writer-thread
  failure — including an injected
  :class:`~paddle_tpu.resilience.faults.InjectedTrainerDeath` from a
  ``kill_save_at`` plan — is re-raised HERE, on the training thread, at
  the next durability point.  A killed write leaves a meta-less dir the
  commit protocol already tolerates: the previous checkpoint stays
  ``latest``.

Timing is accounted on an injectable clock-free basis (perf counters on
the host; this module is trainer-side, not under the serving/obs
injected-clock lint scope): ``stall_s`` totals what the train loop
actually waited (snapshot + any wait on a previous write), ``write_s``
totals background disk time — the bench's headline async win is their
ratio.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from paddle_tpu import checkpoint as ckpt
from paddle_tpu.analysis.concurrency.lifecycle import record_transition

__all__ = ["AsyncCheckpointer"]


class AsyncCheckpointer:
    """Depth-one pipelined checkpoint writer (see module doc).

    ``keep``: prune budget applied after every successful commit (only
    VERIFIED dirs count toward it — see ``checkpoint.prune_checkpoints``).
    0 disables pruning.
    """

    def __init__(self, keep: int = 2):
        self.keep = int(keep)
        # no lock: with ONE writer in flight at a time, the join() in
        # wait()/drain() is the happens-before edge for everything the
        # writer thread touches (_error, commits, write_s, last_path);
        # a concurrent scrape of the counters may read a stale value,
        # never a torn one (they are plain ints/floats)
        # guarded_by(serialized: depth-one writer; join happens-before)
        self._thread: Optional[threading.Thread] = None
        # guarded_by(serialized: depth-one writer; join happens-before)
        self._error: Optional[BaseException] = None
        # counters (host-side bookkeeping, read by bench/tests)
        self.saves = 0   # guarded_by(serialized: training thread only)
        # guarded_by(serialized: writer thread, join() happens-before)
        self.commits = 0
        self.stall_s = 0.0   # guarded_by(serialized: training thread only)
        # guarded_by(serialized: training thread only)
        self.snapshot_s = 0.0
        # guarded_by(serialized: writer thread, join() happens-before)
        self.write_s = 0.0
        # guarded_by(serialized: writer thread, join() happens-before)
        self.last_path: Optional[str] = None

    # ---- durability barrier ----------------------------------------------

    def wait(self) -> None:
        """Block until the in-flight write (if any) committed; re-raise
        the writer's failure on THIS thread.  The durability point: an
        elastic trainer acks only past it, and a train loop returns
        only past it."""
        t = self._thread
        if t is not None:
            # stall accounting measures real elapsed time, never drives
            # scheduling — the injectable clock would hide true stalls
            t0 = time.perf_counter()     # lint: allow(wall-clock)
            t.join()
            self.stall_s += time.perf_counter() - t0  # lint: allow(wall-clock)
            self._thread = None
        err = self._error
        if err is not None:
            self._error = None
            raise err

    def drain(self) -> None:
        """Best-effort join WITHOUT re-raising (the death-path cleanup:
        when the train loop is already unwinding on an injected death,
        the in-flight write is allowed to finish — deterministic — and
        any writer error is kept recorded for the next wait())."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    def idle(self) -> bool:
        """True when no write is in flight (non-blocking): the elastic
        trainer polls this once per step to ack a committed write's
        tasks EARLY instead of holding them leased until the next
        flush."""
        t = self._thread
        return t is None or not t.is_alive()

    def take_error(self) -> Optional[BaseException]:
        """Pop the recorded writer error without raising — for a caller
        about to DISCARD this checkpointer (per-call rebuild, unwind):
        a failed write must at least be reported loudly, never
        silently dropped with the object."""
        err = self._error
        self._error = None
        return err

    # ---- save -------------------------------------------------------------

    def save(self, root: str, pass_id: int, parameters,
             opt_state: Any = None, model_state: Any = None,
             extra_meta: Optional[Dict] = None, shard_plan: Any = None,
             commit_hook: Optional[Callable[[str], None]] = None) -> None:
        """Snapshot now (blocking: device->host, plus ZeRO gather through
        the plan's compiled identity), write in the background.  Waits
        out the previous write first, so callers get depth-one
        pipelining and in-order commits for free."""
        self.wait()
        record_transition("checkpoint_commit", "idle", "snapshot")
        # snapshot/write timers measure real elapsed time for perf
        # accounting, never drive scheduling
        t0 = time.perf_counter()         # lint: allow(wall-clock)
        host = ckpt.snapshot_checkpoint(parameters, opt_state=opt_state,
                                        model_state=model_state,
                                        shard_plan=shard_plan)
        dt = time.perf_counter() - t0    # lint: allow(wall-clock)
        self.snapshot_s += dt
        self.stall_s += dt
        self.saves += 1
        record_transition("checkpoint_commit", "snapshot", "write")

        def _write() -> None:
            w0 = time.perf_counter()     # lint: allow(wall-clock)
            try:
                path = ckpt.write_checkpoint(root, pass_id, host,
                                             extra_meta=extra_meta,
                                             commit_hook=commit_hook)
                record_transition("checkpoint_commit", "write", "commit")
                if self.keep > 0:
                    record_transition("checkpoint_commit", "commit",
                                      "prune")
                    ckpt.prune_checkpoints(root, keep=self.keep)
                    record_transition("checkpoint_commit", "prune",
                                      "idle")
                else:
                    record_transition("checkpoint_commit", "commit",
                                      "idle")
                self.commits += 1
                self.last_path = path
            except BaseException as e:   # surfaces at the next wait()
                record_transition("checkpoint_commit", "write", "failed")
                record_transition("checkpoint_commit", "failed", "idle")
                self._error = e
            finally:
                self.write_s += time.perf_counter() - w0  # lint: allow(wall-clock)

        t = threading.Thread(target=_write, name="ckpt-writer", daemon=True)
        self._thread = t
        t.start()
