"""paddle_tpu.resilience — the fault-tolerant training runtime.

Training's mirror of the serving stack's chaos machinery (PRs 3/6/7):
failure is the steady state on preemptible TPU capacity, so every
recovery path here is deterministic, seeded and CI-replayable.

Four connected parts:

- :mod:`~paddle_tpu.resilience.faults` — :class:`TrainFaultPlan`, the
  seedable injected-failure schedule (deaths, NaN gradients, slow
  steps, kill-during-save) threaded through ``trainer.SGD(faults=...)``
  on an injected clock;
- :mod:`~paddle_tpu.resilience.guard` — :class:`BadStepGuard`, the
  in-step skip / hysteresis / rollback-to-last-good policy ladder over
  one fused grad-norm+finiteness reduction;
- :mod:`~paddle_tpu.resilience.checkpointer` —
  :class:`AsyncCheckpointer`, step-granular background checkpoint
  writes over the tmp+rename+md5 commit protocol (training stalls only
  for the device->host snapshot);
- :mod:`~paddle_tpu.resilience.supervisor` — :func:`run_supervised`,
  restarting a training fn across deaths/rollbacks from the newest
  verified checkpoint.

``python -m paddle_tpu.resilience run`` replays the seeded chaos demo;
``... check`` is the tier-1 gate (ladder exit 10 via tools_tier1.sh).
"""

from paddle_tpu.resilience.checkpointer import AsyncCheckpointer
from paddle_tpu.resilience.faults import (BadStepRollback,
                                          InjectedTrainerDeath,
                                          ManualClock, TrainFaultPlan)
from paddle_tpu.resilience.guard import BadStepGuard
from paddle_tpu.resilience.supervisor import (RunReport, SupervisorGaveUp,
                                              run_supervised)

__all__ = [
    "TrainFaultPlan", "InjectedTrainerDeath", "BadStepRollback",
    "ManualClock", "BadStepGuard", "AsyncCheckpointer",
    "run_supervised", "RunReport", "SupervisorGaveUp",
]
