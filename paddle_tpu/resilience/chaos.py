"""The seeded training-chaos replay: one scenario, three consumers.

``seeded_chaos`` trains a small classifier twice under the SAME seeded
gradient-poison schedule:

- **control** — uninterrupted, no checkpoints, bad-step guard on;
- **chaos** — kill-at-step deaths, a slow-step window, a kill between
  blob write and meta commit, step-granular async checkpoints, and the
  resume supervisor restarting after every death.

The acceptance bar (ISSUE 14 / ``worker_train_chaos``): the chaos run's
final parameters and optimizer slots are BIT-IDENTICAL to the control's,
its per-step loss trajectory matches exactly, every injected non-finite
step was skipped with slots untouched, every death resumed from a
verified checkpoint, no surviving artifact is corrupt, and the torn save
left the previous checkpoint loadable.  The bench worker reports the
numbers; ``python -m paddle_tpu.resilience check`` turns any violation
into exit 1 (tier-1 ladder exit 10); tests/test_resilience.py pins the
pieces individually.

Shared by CLI, bench and tests so "bit-identical across chaos" has ONE
definition (the ``obs.cli.seeded_chaos`` precedent).
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["seeded_chaos", "torn_save_probe"]

# the default plan: 24 global steps (3 passes x 8), three scheduled
# deaths each with a durable checkpoint behind it, three poisoned steps
# (one NaN pair mid-pass-0, one lone Inf in pass 1), one slow-step
# window, and checkpoint id 4 killed between state blob and meta commit
KILLS = (4, 11, 17)
BAD_STEPS = (5, 6, 13)
SLOW_STEPS = {9: 2.0}
KILL_SAVE = {4: "meta"}


def _build_trainer(guard=None, faults=None, tracer=None, seed=5, lr=0.1):
    """The scenario's small classifier — ONE definition shared by the
    CLI gate, the bench worker AND tests/test_resilience.py, so every
    consumer of "bit-identical across chaos" pins the same model."""
    import paddle_tpu as paddle
    from paddle_tpu import layer, optimizer, trainer

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    cost = layer.classification_cost(
        input=layer.fc(input=layer.fc(input=x, size=16, act="relu"),
                       size=2), label=y)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=seed)
    return trainer.SGD(cost=cost, parameters=params,
                       update_equation=optimizer.Momentum(
                           momentum=0.9, learning_rate=lr),
                       guard=guard, faults=faults, tracer=tracer)


def _dataset(seed: int = 0, n: int = 32):
    import numpy as np

    rng = np.random.RandomState(seed)
    w = rng.randn(8)
    return [(x.astype(np.float32), int(x @ w > 0))
            for x in rng.randn(n, 8)]


def _snap(sgd) -> Dict[str, "object"]:
    import numpy as np

    return {k: np.asarray(sgd.parameters[k])
            for k in sgd.parameters.names()}


def _slots(sgd) -> Dict[str, "object"]:
    import numpy as np

    return {f"{s}/{k}": np.asarray(v)
            for s, d in sgd.opt_state["slots"].items()
            for k, v in d.items()}


def _cost_recorder(out: Dict):
    from paddle_tpu import event as v2_event

    def handler(ev) -> None:
        if isinstance(ev, v2_event.EndIteration):
            # keyed by (pass, batch): a chaos run re-executes lost steps
            # after each resume; last-write-wins is exactly the "what
            # the run actually applied" trajectory to pin vs control
            out[(ev.pass_id, ev.batch_id)] = float(ev.cost)

    return handler


def seeded_chaos(save_dir: str, *, seed: int = 0, passes: int = 3,
                 batch: int = 8, samples: int = 64,
                 save_period_steps: int = 3, async_save: bool = True,
                 keep: int = 3, max_restarts: int = 10) -> Dict:
    """Run control + chaos (see module doc); returns a metrics dict with
    a ``problems`` list (empty = every acceptance assertion held)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.resilience.faults import ManualClock, TrainFaultPlan
    from paddle_tpu.resilience.guard import BadStepGuard
    from paddle_tpu.resilience.supervisor import run_supervised
    from paddle_tpu import checkpoint as ckpt

    data = _dataset(seed, samples)
    reader = paddle.batch(lambda: iter(data), batch)
    guard = BadStepGuard(policy="skip")

    plan = TrainFaultPlan(seed=seed, clock=ManualClock(tick_s=0.01),
                          kill_at=set(KILLS), bad_steps=set(BAD_STEPS),
                          slow_steps=dict(SLOW_STEPS),
                          kill_save_at=dict(KILL_SAVE))

    # ---- control: same poison, no kills, no checkpoints ------------------
    control_costs: Dict = {}
    control = _build_trainer(guard, faults=plan.control_twin())
    control.train(reader, num_passes=passes,
                  event_handler=_cost_recorder(control_costs))
    control_params, control_slots = _snap(control), _slots(control)
    control_bad = getattr(control, "bad_steps_total", 0)

    # ---- chaos: supervised across deaths ---------------------------------
    chaos_costs: Dict = {}
    resumed_fresh = {"n": 0}   # attempts that found NO checkpoint
    bad_per_attempt: List[int] = []

    def attempt(i: int):
        sgd = _build_trainer(guard, faults=plan)
        if i > 0 and not any(
                ckpt.verify_pass_dir(save_dir, pid) is None
                for pid in ckpt._pass_ids(save_dir)):
            # metadata-level probe (md5 results are stat-cached): no
            # second full deserialization next to train()'s own load
            resumed_fresh["n"] += 1
        try:
            sgd.train(reader, num_passes=passes, save_dir=save_dir,
                      save_period_steps=save_period_steps, resume=True,
                      async_save=async_save, keep=keep,
                      event_handler=_cost_recorder(chaos_costs))
        finally:
            # per-attempt skip count (flushed at each pass end); re-run
            # windows legitimately re-skip, so the cross-attempt sum is
            # >= the schedule, never ==
            bad_per_attempt.append(getattr(sgd, "bad_steps_total", 0))
        return sgd

    report, chaos = run_supervised(attempt, max_restarts=max_restarts)
    chaos_params, chaos_slots = _snap(chaos), _slots(chaos)
    chaos_bad = sum(bad_per_attempt)

    # one scrape surface: the chaos run's recovery history lands on the
    # default registry next to serving/trainer metrics
    from paddle_tpu.obs import default_registry, publish_resilience

    publish_resilience(default_registry(), checkpointer=chaos._async_ckpt,
                       report=report)

    # ---- acceptance assertions -------------------------------------------
    problems: List[str] = []
    bitwise = all(np.array_equal(control_params[k], chaos_params[k])
                  for k in control_params)
    if not bitwise:
        problems.append("final params NOT bit-identical to the "
                        "uninterrupted control")
    if set(control_slots) != set(chaos_slots) or not all(
            np.array_equal(control_slots[k], chaos_slots[k])
            for k in control_slots):
        problems.append("final optimizer slots diverged from control "
                        "(a skipped bad step touched state)")
    if control_costs != chaos_costs:
        diff = [k for k in sorted(set(control_costs) | set(chaos_costs))
                if control_costs.get(k) != chaos_costs.get(k)]
        problems.append(f"loss trajectory diverged at {diff[:4]}")
    if control_bad != len(BAD_STEPS) or chaos_bad < len(BAD_STEPS):
        problems.append(f"bad-step count wrong: control={control_bad} "
                        f"(expected {len(BAD_STEPS)}), chaos skipped "
                        f"{chaos_bad} (expected >= {len(BAD_STEPS)})")
    expected_deaths = len(KILLS) + len(KILL_SAVE)
    if report.deaths != expected_deaths or not report.completed:
        problems.append(f"supervisor saw {report.deaths} deaths "
                        f"(expected {expected_deaths}), "
                        f"completed={report.completed}")
    if resumed_fresh["n"]:
        problems.append(f"{resumed_fresh['n']} restart(s) found no "
                        "checkpoint — a death was not covered by a "
                        "durable artifact")
    # every surviving meta-bearing artifact must verify clean
    corrupt = [pid for pid in ckpt._pass_ids(save_dir)
               if ckpt.verify_pass_dir(save_dir, pid)
               not in (None, "missing meta.json")]
    if corrupt:
        problems.append(f"surviving corrupt checkpoint dirs: {corrupt}")

    return {
        "train_chaos_parity_ok": int(bitwise and not problems),
        "train_chaos_steps": passes * (samples // batch),
        "train_chaos_deaths": report.deaths,
        "train_chaos_restarts": report.restarts,
        "train_chaos_bad_steps_skipped": chaos_bad,
        "train_chaos_ckpt_corrupt_surviving": len(corrupt),
        "train_chaos_ckpt_saves": getattr(chaos._async_ckpt, "saves", 0)
        if chaos._async_ckpt is not None else 0,
        "train_chaos_ckpt_stall_s": round(
            getattr(chaos._async_ckpt, "stall_s", 0.0), 4)
        if chaos._async_ckpt is not None else None,
        "train_chaos_ckpt_write_s": round(
            getattr(chaos._async_ckpt, "write_s", 0.0), 4)
        if chaos._async_ckpt is not None else None,
        "problems": problems,
    }


def torn_save_probe(save_dir: str, *, seed: int = 1) -> Dict:
    """The commit-protocol pin, end to end: kill checkpoint N between
    the state blob and the meta commit, and prove the PREVIOUS
    checkpoint is still ``latest`` and loadable.  Returns a dict with a
    ``problems`` list (the ``check`` CLI folds it into exit 10)."""
    import paddle_tpu as paddle
    from paddle_tpu import checkpoint as ckpt
    from paddle_tpu.resilience.faults import (InjectedTrainerDeath,
                                              TrainFaultPlan)
    from paddle_tpu.resilience.guard import BadStepGuard

    problems: List[str] = []
    data = _dataset(seed, 32)
    reader = paddle.batch(lambda: iter(data), 8)   # 4 steps/pass
    plan = TrainFaultPlan(seed=seed, kill_save_at={1: "meta"})
    sgd = _build_trainer(BadStepGuard(), faults=plan)
    died = False
    try:
        # sync saves: the death fires inside write_checkpoint itself
        sgd.train(reader, num_passes=2, save_dir=save_dir,
                  save_period_steps=2, resume=True, async_save=False,
                  keep=0)
    except InjectedTrainerDeath:
        died = True
    if not died:
        problems.append("kill-during-save never fired")
    latest = ckpt.latest_pass(save_dir)
    if latest != 0:
        problems.append(f"torn save did not leave checkpoint 0 as "
                        f"latest (got {latest})")
    got: Optional[tuple] = ckpt.load_latest(save_dir)
    if got is None:
        problems.append("previous checkpoint not loadable after the "
                        "torn save")
    reason = ckpt.verify_pass_dir(save_dir, 1)
    if reason != "missing meta.json":
        problems.append(f"torn dir should be meta-less, verify said "
                        f"{reason!r}")
    # a resumed run overwrites the torn dir and completes
    sgd2 = _build_trainer(BadStepGuard(), faults=plan)
    sgd2.train(reader, num_passes=2, save_dir=save_dir,
               save_period_steps=2, resume=True, async_save=False, keep=0)
    if ckpt.verify_pass_dir(save_dir, 1) is not None:
        problems.append("resume did not rewrite the torn checkpoint dir")
    return {"torn_save_ok": int(not problems), "problems": problems}
