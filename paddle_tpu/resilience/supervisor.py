"""Resume supervisor: restart a training fn across injected (or real)
trainer deaths and bad-step rollbacks.

The serving fleet already treats replica death as routine
(``FleetRouter`` resubmits and moves on); this is the training-side
mirror: ``run_supervised`` keeps calling ``train_fn`` until it returns,
catching :class:`~paddle_tpu.resilience.faults.InjectedTrainerDeath`
(a preemption / crash) and
:class:`~paddle_tpu.resilience.faults.BadStepRollback` (the guard's
K-consecutive-bad-steps escalation) up to ``max_restarts`` times.  Each
``train_fn(attempt)`` is expected to build a FRESH trainer and call
``train(..., save_dir=..., resume=True)`` (or ``train(master=...)``,
whose resume is implicit) so every restart resumes from the newest
verified checkpoint — exactly what a replacement worker on preemptible
capacity does.

Restarts land on the obs timeline (``trainer_restart`` instants) and the
unified registry (``train_restarts_total``), so a chaos replay's
recovery history exports next to its serving twin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

from paddle_tpu.resilience.faults import (BadStepRollback,
                                          InjectedTrainerDeath)

__all__ = ["run_supervised", "RunReport", "SupervisorGaveUp"]


class SupervisorGaveUp(RuntimeError):
    """``max_restarts`` exhausted without the training fn completing."""


@dataclass
class RunReport:
    """What the supervisor observed across one supervised run."""

    completed: bool = False
    restarts: int = 0
    deaths: int = 0
    rollbacks: int = 0
    # (attempt, kind, message) per restart, for postmortems/benches
    history: List[Tuple[int, str, str]] = field(default_factory=list)


def run_supervised(train_fn: Callable[[int], Any], *,
                   max_restarts: int = 32, tracer=None, registry=None,
                   on_restart: Optional[Callable[[int, BaseException],
                                                 None]] = None
                   ) -> Tuple[RunReport, Any]:
    """Run ``train_fn(attempt)`` to completion across deaths/rollbacks.

    Returns ``(report, result)`` where ``result`` is ``train_fn``'s
    return value on the attempt that completed.  ``on_restart(attempt,
    exc)`` runs between a failure and the next attempt — the seam for
    advancing an injected clock past a lease TTL, or clearing a
    transient fault window after a rollback."""
    from paddle_tpu.obs.trace import NULL_TRACER
    from paddle_tpu.platform import plog

    tracer = tracer if tracer is not None else NULL_TRACER
    log = plog.logger()
    report = RunReport()
    while True:
        try:
            result = train_fn(report.restarts)
            report.completed = True
            return report, result
        except (InjectedTrainerDeath, BadStepRollback) as e:
            kind = ("rollback" if isinstance(e, BadStepRollback)
                    else "death")
            if kind == "rollback":
                report.rollbacks += 1
            else:
                report.deaths += 1
            report.restarts += 1
            report.history.append((report.restarts, kind, str(e)))
            tracer.instant("trainer_restart", cat="train", kind=kind,
                           attempt=report.restarts)
            if registry is not None:
                registry.counter(
                    "train_restarts_total",
                    "supervised trainer restarts after a death or "
                    "bad-step rollback").labels(kind=kind).inc()
            log.info("supervisor: restart %d after %s: %s",
                     report.restarts, kind, e)
            if report.restarts > max_restarts:
                raise SupervisorGaveUp(
                    f"gave up after {max_restarts} restarts "
                    f"(last: {kind}: {e})") from e
            if on_restart is not None:
                on_restart(report.restarts, e)
