"""Deterministic fault injection for the TRAINING runtime.

The serving engine proved its guardrails with ``serving/faults.py`` — a
seedable :class:`~paddle_tpu.serving.faults.FaultPlan` on an injected
clock, threaded through ``ServingEngine(faults=...)`` so every recovery
path runs in CI without sleeps or real kills.  This module is the
training twin: a :class:`TrainFaultPlan` threaded through
``trainer.SGD(faults=...)`` so checkpoint/resume, bad-step guards and
the resume supervisor are chaos-tested the same way.

Injection points (all host-side, all deterministic):

- **clock** — a :class:`ManualClock` (shared with serving) advanced
  ``tick_s`` per train step plus any extra from ``slow_steps`` (global
  step -> added seconds), so lease-TTL paths (elastic training) and obs
  timelines fire on chosen steps without wall-clock dependence.
- **process "crashes"** — ``kill_at`` (global steps) and/or a seeded
  ``kill_rate`` raise :class:`InjectedTrainerDeath` at the top of the
  chosen step, before it executes.  Each kill fires ONCE per plan
  object (a resumed run re-executing the step survives it, like a real
  preemption that does not repeat), and the rate draw is a pure
  function of ``(seed, step)`` so a re-run of any step replays the same
  schedule regardless of how many restarts preceded it.
- **non-finite gradients** — ``bad_steps`` / seeded ``bad_rate`` make
  :meth:`grad_inject` return ``bad_value`` (NaN by default) for the
  chosen global steps.  The trainer adds it to every gradient INSIDE
  the jitted step (a same-shape scalar argument, so no retrace and no
  extra host sync); the bad-step guard must then skip the update.
  Deterministic per ``(seed, step)``, so an uninterrupted control run
  and a kill-riddled chaos run poison exactly the same steps — the
  bit-identical-parity contract ``worker_train_chaos`` pins.
- **kill during save** — ``kill_save_at`` (checkpoint id -> commit
  phase from ``checkpoint.COMMIT_PHASES``) raises the death inside
  :func:`~paddle_tpu.checkpoint.write_checkpoint` just before that
  phase's write.  ``{ck: "meta"}`` is the classic torn save: both blobs
  durable, meta never committed, previous checkpoint still ``latest``.
  Fires once per checkpoint id (the re-written save after resume
  completes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set

import numpy as np

from paddle_tpu.serving.faults import ManualClock

__all__ = ["TrainFaultPlan", "InjectedTrainerDeath", "BadStepRollback",
           "ManualClock"]


class InjectedTrainerDeath(RuntimeError):
    """A fault-plan-injected trainer "crash" (the in-process stand-in
    for a preempted TPU worker / OOM-killed process).  Catchable, so the
    resume supervisor restarts the training fn deterministically."""


class BadStepRollback(RuntimeError):
    """Raised by the bad-step guard when ``rollback_after`` consecutive
    bad steps accumulate: the run must roll back to its last verified
    checkpoint (the supervisor treats it like a death — restart and
    resume — after the guard has dumped its flight-recorder
    postmortem)."""


@dataclass
class TrainFaultPlan:
    """A seeded, replayable schedule of injected training failures.

    All randomized draws are pure functions of ``(seed, step)`` — NOT a
    sequential RNG stream — because chaos runs re-execute steps after
    every resume: a re-run step must see the same injection decision it
    saw the first time, and an uninterrupted control run must see the
    same schedule as a kill-riddled one.
    """

    seed: int = 0
    clock: Optional[ManualClock] = None
    # process crashes: global steps to die at + a seeded per-step rate
    kill_at: Set[int] = field(default_factory=set)
    kill_rate: float = 0.0
    # non-finite gradient injection: explicit steps + a seeded rate
    bad_steps: Set[int] = field(default_factory=set)
    bad_rate: float = 0.0
    bad_value: float = float("nan")
    # global step -> extra injected seconds (on top of clock.tick_s)
    slow_steps: Dict[int, float] = field(default_factory=dict)
    # checkpoint id -> commit phase (checkpoint.COMMIT_PHASES) to die at
    kill_save_at: Dict[int, str] = field(default_factory=dict)

    def __post_init__(self):
        self._fired_kills: Set[int] = set()
        self._fired_saves: Set[int] = set()

    # ---- plan surface ----------------------------------------------------

    def injects_grads(self) -> bool:
        """True when the plan poisons gradients — the trainer requires a
        bad-step guard in that case (without the in-step reduction the
        poison would silently corrupt optimizer slots forever)."""
        return bool(self.bad_steps) or self.bad_rate > 0.0

    def control_twin(self) -> "TrainFaultPlan":
        """The uninterrupted-control version of this plan: same seed and
        same gradient poison schedule, NO kills / slow windows / save
        kills.  A chaos run resumed across every injected death must end
        bit-identical to a run under its control twin — the
        ``worker_train_chaos`` acceptance bar."""
        return TrainFaultPlan(seed=self.seed, bad_steps=set(self.bad_steps),
                              bad_rate=self.bad_rate,
                              bad_value=self.bad_value)

    # ---- hooks the trainer calls -----------------------------------------

    def _draw(self, step: int, salt: int) -> float:
        # order-independent: a per-(seed, step, salt) RandomState, so a
        # resumed run re-drawing an already-run step replays identically
        rs = np.random.RandomState(
            (self.seed * 1000003 + step * 9176 + salt) % (2 ** 31 - 1))
        return float(rs.random_sample())

    def step_begin(self, step: int) -> None:
        """Advance the injected clock for this global step and raise the
        scheduled death, if any.  Called at the TOP of the step — before
        the batch is applied — so a killed step's work is provably lost
        and must be re-run from the last checkpoint."""
        if self.clock is not None:
            self.clock.advance(self.clock.tick_s +
                               self.slow_steps.get(step, 0.0))
        kill = step in self.kill_at or (
            self.kill_rate > 0.0 and self._draw(step, 1) < self.kill_rate)
        if kill and step not in self._fired_kills:
            self._fired_kills.add(step)
            raise InjectedTrainerDeath(
                f"injected trainer death at step {step}")

    def grad_inject(self, step: int) -> float:
        """The value the trainer adds to every gradient this step: 0.0
        normally, ``bad_value`` on poisoned steps."""
        if step in self.bad_steps:
            return self.bad_value
        if self.bad_rate > 0.0 and self._draw(step, 2) < self.bad_rate:
            return self.bad_value
        return 0.0

    def save_hook(self, ck_id: int) -> Callable[[str], None]:
        """The ``commit_hook`` for checkpoint ``ck_id``: raises the
        scheduled :class:`InjectedTrainerDeath` just before the chosen
        commit phase, once.  On the async path the death lands on the
        writer thread, is recorded by the AsyncCheckpointer, and
        re-raises on the trainer's next durability wait — exactly the
        delayed failure surface a real lost writer has."""
        def hook(phase: str) -> None:
            if self.kill_save_at.get(ck_id) == phase \
                    and ck_id not in self._fired_saves:
                self._fired_saves.add(ck_id)
                raise InjectedTrainerDeath(
                    f"injected death during save of checkpoint {ck_id} "
                    f"(before {phase} commit)")

        return hook
