"""Bad-step guards: the in-step policy that keeps one NaN gradient from
poisoning optimizer slots forever.

The check itself lives INSIDE the jitted train step (``trainer.SGD``
builds it when a :class:`BadStepGuard` is set): one fused f32
global-sq-norm reduction over all gradients decides ``good`` (finite,
and under ``max_norm`` when set), the optimizer update runs as usual,
and every params / slot / model-state leaf is selected back to its OLD
value on a bad step — so a skipped step is a true no-op on training
state while costing zero extra host syncs (the bad counters ride the
same lazy device-scalar contract as ``.cost``).

Policy ladder:

- ``"skip"`` — never apply a bad step; count it (the per-step floor
  every policy includes);
- ``"rollback"`` — additionally, ``rollback_after`` CONSECUTIVE bad
  steps raise :class:`~paddle_tpu.resilience.faults.BadStepRollback`
  after dumping a flight-recorder postmortem: persistent badness means
  the inputs or state are wrong and the run must restart from its last
  verified checkpoint (the resume supervisor does exactly that).  The
  consecutive counter is kept ON DEVICE and read back only every
  ``check_every`` steps (default: ``rollback_after``), so a persisting
  streak is caught within one window while healthy steps never sync.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["BadStepGuard", "screen_grads", "select_good", "guard_init",
           "guard_outputs"]


@dataclass(frozen=True)
class BadStepGuard:
    """Configuration for the in-step bad-step guard.

    - ``policy``: ``"skip"`` or ``"rollback"`` (the ladder above);
    - ``max_norm``: global grad-norm ceiling — a FINITE step whose norm
      exceeds it is also treated bad (0 = finiteness check only);
    - ``rollback_after``: K consecutive bad steps trigger the rollback
      (policy ``"rollback"`` only);
    - ``check_every``: host-readback cadence for the consecutive
      counter, in steps (0 = ``rollback_after``).
    """

    policy: str = "skip"
    max_norm: float = 0.0
    rollback_after: int = 3
    check_every: int = 0

    def __post_init__(self):
        if self.policy not in ("skip", "rollback"):
            raise ValueError(f"BadStepGuard.policy must be 'skip' or "
                             f"'rollback', got {self.policy!r}")
        if self.policy == "rollback" and self.rollback_after < 1:
            # 0 would make `consec >= rollback_after` true on a healthy
            # step: every cadence check rolls back a perfectly good run
            raise ValueError("BadStepGuard.rollback_after must be >= 1, "
                             f"got {self.rollback_after}")

    @property
    def cadence(self) -> int:
        return max(1, int(self.check_every or self.rollback_after))


def guard_init():
    """Fresh host-side guard-state pytree, passed as the train step's
    extra argument.  ``inject`` is re-stamped by the trainer from the
    fault plan every step (0.0 outside injection windows); the counters
    are replaced by the step's device outputs."""
    import numpy as np

    return {"inject": np.float32(0.0),
            "bad_consec": np.int32(0),
            "bad_total": np.int32(0)}


def screen_grads(grads, inject, max_norm: float):
    """Traced-side: poison + screen the gradient tree.

    Adds ``inject`` (a scalar; 0.0 = no-op, NaN/Inf = an injected bad
    step) to every gradient, then computes ONE fused f32 global
    sq-norm reduction and the ``good`` verdict: all-finite, and under
    ``max_norm`` when set.  Returns ``(grads, good, sq_norm)``; the
    reduction fuses into the surrounding jitted step — no host
    callback, no extra sync."""
    import functools

    import jax.numpy as jnp

    grads = {k: g + inject.astype(g.dtype) for k, g in grads.items()}
    sq = functools.reduce(
        jnp.add,
        [jnp.sum(jnp.square(g.astype(jnp.float32)))
         for g in grads.values()],
        jnp.zeros((), jnp.float32))
    good = jnp.isfinite(sq)
    if max_norm > 0.0:
        good = jnp.logical_and(good, sq <= jnp.float32(max_norm) ** 2)
    return grads, good, sq


def select_good(good, new_tree, old_tree):
    """Traced-side: per-leaf ``where(good, new, old)`` over matching
    pytrees — the skip-step select.  On a good step this is the
    identity on ``new``; on a bad one params/slots/model-state come out
    bit-identical to their pre-step values (pinned vs an uninterrupted
    control by the chaos bench)."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda n, o: jnp.where(good, n, o),
                        new_tree, old_tree)


def guard_outputs(good, guard_state):
    """Traced-side: next guard counters — consecutive resets on a good
    step, total accumulates."""
    import jax.numpy as jnp

    consec = jnp.where(good, 0,
                       guard_state["bad_consec"] + 1).astype(jnp.int32)
    total = (guard_state["bad_total"]
             + jnp.where(good, 0, 1)).astype(jnp.int32)
    return {"bad_consec": consec, "bad_total": total}
