"""``python -m paddle_tpu.obs`` — export traces, replay the seeded
chaos scenario.

Subcommands:

- ``export <events.jsonl | postmortem.json> [-o out.json]`` — convert a
  raw event dump (``Tracer.save`` JSONL or a flight-recorder postmortem
  file) into Chrome-trace JSON.  Open the output at ``ui.perfetto.dev``
  (Open trace file) or ``chrome://tracing``.
- ``chaos [-o out.json] [--replicas N] [--seed S]`` — run the seeded
  4-replica kill + partition + slow chaos replay (the acceptance
  scenario) with tracing on and write its Chrome trace.  Deterministic:
  two runs with the same seed write byte-identical traces.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = ["main", "seeded_chaos"]


def seeded_chaos(num_replicas: int = 4, seed: int = 0,
                 n_requests: int = 10, registry=None):
    """The acceptance chaos scenario on one injected clock: a shared
    8-token prefix over ``n_requests`` prompts, replica 0 killed at
    tick 8, replica 1 heartbeat-partitioned from tick 2 past the lease
    TTL (lease-expiry death + resubmit, the second death mode), replica
    2 slowed to every other tick.  Returns ``(tracer, fleet, frids)``
    after a full drain (conservation checked).

    Lives here (not in a test) so the CLI, the bench, and the obs tests
    all replay the SAME trace — and so "byte-identical across two
    replays" is checked against one definition of the replay."""
    import jax
    import numpy as np

    from paddle_tpu.obs.trace import Tracer
    from paddle_tpu.serving.engine import DecoderLM, ServingEngine
    from paddle_tpu.serving.faults import FleetFaultPlan, ManualClock
    from paddle_tpu.serving.fleet import FleetRouter

    model = DecoderLM(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    clock = ManualClock(tick_s=0.01)
    plan = FleetFaultPlan(seed=seed, clock=clock, kill_at={8: 0},
                          slow_replicas={2: 2}, partitions={1: (2, 999)})
    tracer = Tracer(time_fn=clock, registry=registry)

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=1, page_size=4,
                             num_pages=32, max_pages_per_seq=8, max_slots=4,
                             buckets=(8, 16), time_fn=time_fn)

    fleet = FleetRouter(mk, num_replicas, heartbeat_s=0.04,
                        resubmit_budget=2, faults=plan, tracer=tracer)
    rng = np.random.RandomState(seed)
    system = rng.randint(2, 64, size=8).tolist()     # 2 full shared pages
    frids = [fleet.submit(system + rng.randint(2, 64, size=4).tolist(),
                          max_tokens=12) for _ in range(n_requests)]
    fleet.run(max_ticks=2000)
    return tracer, fleet, frids


def _parse(args: Sequence[str], flag: str,
           default: Optional[str] = None) -> Tuple[List[str], Optional[str]]:
    args = list(args)
    if flag in args:
        i = args.index(flag)
        if i + 1 >= len(args):      # trailing flag with no value
            del args[i]
            return args, default
        val = args[i + 1]
        del args[i:i + 2]
        return args, val
    return args, default


def main(argv: Optional[Sequence[str]] = None) -> int:
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print(__doc__)
        return 2
    cmd, args = args[0], args[1:]
    if cmd == "export":
        from paddle_tpu.obs.export import load_events, save_chrome_trace

        args, out = _parse(args, "-o")
        if not args:
            print("usage: python -m paddle_tpu.obs export <events-file> "
                  "[-o out.json]")
            return 2
        src = args[0]
        out = out or (src.rsplit(".", 1)[0] + ".chrome.json")
        events = load_events(src)
        save_chrome_trace(events, out)
        print(f"wrote {out} ({len(events)} events) — open in "
              "ui.perfetto.dev or chrome://tracing")
        return 0
    if cmd == "chaos":
        from paddle_tpu.obs.export import save_chrome_trace

        args, out = _parse(args, "-o", "chaos_trace.json")
        args, replicas = _parse(args, "--replicas", "4")
        args, seed = _parse(args, "--seed", "0")
        tracer, fleet, frids = seeded_chaos(int(replicas), int(seed))
        save_chrome_trace(tracer.events, out)
        snap = fleet.snapshot()
        print(f"wrote {out} ({len(tracer.events)} events): "
              f"{snap['fleet_completed']}/{len(frids)} completed, "
              f"{snap['fleet_resubmits']} resubmits, "
              f"{snap['fleet_replicas_dead']} replicas dead")
        return 0
    print(f"unknown command {cmd!r}; see python -m paddle_tpu.obs")
    return 2
