import sys

from paddle_tpu.obs.cli import main

sys.exit(main())
