"""Trainer-event -> obs-span bridge: training and serving share one
timeline format.

The v2 trainer already fires :mod:`paddle_tpu.event` objects
(BeginPass / EndPass / BeginIteration / EndIteration / TestResult) at
every loop edge; this module turns an ordinary ``event_handler`` into
one that ALSO records those edges as obs spans, so a training run
exports through the same ``obs.export`` pipeline as a serving chaos
replay:

- each pass becomes an async ``train_pass`` span (``b``/``e`` paired by
  pass id);
- each iteration becomes a complete ``train_iteration`` span (begin at
  BeginIteration, closed at EndIteration);
- TestResult becomes a ``test_result`` instant.

Usage::

    tracer = Tracer(registry=obs.default_registry())
    trainer.train(reader, event_handler=trainer_event_bridge(tracer,
                                                             my_handler))

The bridge never reads the event's lazy ``.cost``/``.metrics``
properties — those force a device sync the trainer deliberately avoids
per batch — so wrapping a handler adds zero host syncs.
"""

from __future__ import annotations

from typing import Callable, Optional

from paddle_tpu import event as v2_event

__all__ = ["trainer_event_bridge"]


def trainer_event_bridge(tracer, handler: Optional[Callable] = None,
                         registry=None) -> Callable:
    """Wrap ``handler`` (or nothing) so trainer events are mirrored as
    obs spans on ``tracer``.  ``registry`` additionally counts passes /
    iterations (defaults to the tracer's registry, if any)."""
    reg = registry if registry is not None else getattr(tracer, "registry",
                                                        None)

    def on_event(ev) -> None:
        if isinstance(ev, v2_event.BeginPass):
            tracer.async_begin("train_pass", id=ev.pass_id,
                               id_space="pass", cat="train",
                               pass_id=ev.pass_id)
        elif isinstance(ev, v2_event.EndPass):
            tracer.async_end("train_pass", id=ev.pass_id,
                             id_space="pass", cat="train",
                             pass_id=ev.pass_id)
            if reg is not None:
                reg.counter("train_passes_total",
                            "completed training passes").inc()
        elif isinstance(ev, v2_event.BeginIteration):
            tracer.begin("train_iteration", key=(ev.pass_id, ev.batch_id),
                         cat="train", pass_id=ev.pass_id,
                         batch=ev.batch_id)
        elif isinstance(ev, v2_event.EndIteration):
            tracer.end("train_iteration", key=(ev.pass_id, ev.batch_id),
                       cat="train")
            if reg is not None:
                reg.counter("train_iterations_total",
                            "completed training iterations").inc()
        elif isinstance(ev, v2_event.TestResult):
            tracer.instant("test_result", cat="train")
        if handler is not None:
            handler(ev)

    return on_event
