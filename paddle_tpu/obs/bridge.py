"""Trainer-event -> obs-span bridge: training and serving share one
timeline format.

The v2 trainer already fires :mod:`paddle_tpu.event` objects
(BeginPass / EndPass / BeginIteration / EndIteration / TestResult) at
every loop edge; this module turns an ordinary ``event_handler`` into
one that ALSO records those edges as obs spans, so a training run
exports through the same ``obs.export`` pipeline as a serving chaos
replay:

- each pass becomes an async ``train_pass`` span (``b``/``e`` paired by
  pass id);
- each iteration becomes a complete ``train_iteration`` span (begin at
  BeginIteration, closed at EndIteration);
- TestResult becomes a ``test_result`` instant.

Usage::

    tracer = Tracer(registry=obs.default_registry())
    trainer.train(reader, event_handler=trainer_event_bridge(tracer,
                                                             my_handler))

The bridge never reads the event's lazy ``.cost``/``.metrics``
properties — those force a device sync the trainer deliberately avoids
per batch — so wrapping a handler adds zero host syncs.
"""

from __future__ import annotations

from typing import Callable, Optional

from paddle_tpu import event as v2_event

__all__ = ["trainer_event_bridge", "publish_resilience"]


def trainer_event_bridge(tracer, handler: Optional[Callable] = None,
                         registry=None) -> Callable:
    """Wrap ``handler`` (or nothing) so trainer events are mirrored as
    obs spans on ``tracer``.  ``registry`` additionally counts passes /
    iterations (defaults to the tracer's registry, if any)."""
    reg = registry if registry is not None else getattr(tracer, "registry",
                                                        None)

    def on_event(ev) -> None:
        if isinstance(ev, v2_event.BeginPass):
            tracer.async_begin("train_pass", id=ev.pass_id,
                               id_space="pass", cat="train",
                               pass_id=ev.pass_id)
        elif isinstance(ev, v2_event.EndPass):
            tracer.async_end("train_pass", id=ev.pass_id,
                             id_space="pass", cat="train",
                             pass_id=ev.pass_id)
            if reg is not None:
                reg.counter("train_passes_total",
                            "completed training passes").inc()
        elif isinstance(ev, v2_event.BeginIteration):
            tracer.begin("train_iteration", key=(ev.pass_id, ev.batch_id),
                         cat="train", pass_id=ev.pass_id,
                         batch=ev.batch_id)
        elif isinstance(ev, v2_event.EndIteration):
            tracer.end("train_iteration", key=(ev.pass_id, ev.batch_id),
                       cat="train")
            if reg is not None:
                reg.counter("train_iterations_total",
                            "completed training iterations").inc()
        elif isinstance(ev, v2_event.TestResult):
            tracer.instant("test_result", cat="train")
        if handler is not None:
            handler(ev)

    return on_event


def publish_resilience(registry, checkpointer=None, report=None) -> None:
    """Land the fault-tolerant-training numbers on a unified
    :class:`~paddle_tpu.obs.registry.MetricsRegistry` — the same
    one-scrape-surface contract ``ServingMetrics.publish`` /
    ``StatSet.publish`` follow, so a supervised run's recovery history
    exports next to its serving twin.

    ``checkpointer`` (a ``resilience.AsyncCheckpointer``) contributes
    the async-save split — ``train_ckpt_stall_seconds_total`` (what the
    train loop actually waited: snapshot + pipeline waits) vs
    ``train_ckpt_write_seconds_total`` (background disk time) — plus
    save/commit counts; ``report`` (a ``resilience.RunReport``)
    contributes restart counts by kind and the completed flag.  The
    live per-event counters (``train_bad_steps_total``,
    ``train_rollbacks_total``, ``train_restarts_total``) are published
    by the trainer/supervisor as they happen; this call adds the
    end-of-run aggregates."""
    # gauges, so the names deliberately avoid the Prometheus counter
    # `_total` suffix — rate()/increase() tooling keys on that spelling
    if checkpointer is not None:
        registry.gauge(
            "train_ckpt_saves",
            "checkpoint saves submitted by the async checkpointer"
        ).set(checkpointer.saves)
        registry.gauge(
            "train_ckpt_commits",
            "checkpoint writes fully committed (meta durable)"
        ).set(checkpointer.commits)
        registry.gauge(
            "train_ckpt_stall_seconds",
            "train-loop seconds spent waiting on checkpointing "
            "(device->host snapshot + pipeline waits)"
        ).set(checkpointer.stall_s)
        registry.gauge(
            "train_ckpt_write_seconds",
            "background seconds spent writing checkpoint blobs"
        ).set(checkpointer.write_s)
    if report is not None:
        g = registry.gauge(
            "train_supervised_restarts",
            "restarts observed by the resume supervisor, by kind")
        g.labels(kind="death").set(report.deaths)
        g.labels(kind="rollback").set(report.rollbacks)
        registry.gauge(
            "train_supervised_completed",
            "1 when the supervised training fn ran to completion"
        ).set(1.0 if report.completed else 0.0)
