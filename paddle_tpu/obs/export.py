"""Chrome-trace / Perfetto JSON exporter for obs events.

Maps the tracer's :class:`~paddle_tpu.obs.trace.Event` stream to the
Chrome Trace Event Format (the JSON flavour ``ui.perfetto.dev`` and
``chrome://tracing`` both load):

- **replicas -> processes**: every event's ``replica`` becomes its
  ``pid`` (``0`` for engine-less / single-engine events), with
  ``process_name`` metadata ``"replica N"``;
- **slots -> threads**: ``slot`` becomes ``tid + 1`` with
  ``thread_name`` ``"slot N"``; slot-less control events (submit,
  route, lease transitions) run on the reserved ``tid 0`` control
  lane;
- span events (``X``) keep their injected-clock timestamps and
  durations (microseconds), instants map to ``ph: "i"``, and the
  fleet's per-rid root spans map to async ``b``/``e`` pairs so
  Perfetto draws one bar per fleet request spanning admission to its
  terminal transition, resubmits and all.

**Determinism**: ``dumps_chrome`` emits byte-identical JSON for two
replays of the same seeded ``FleetFaultPlan`` trace.  The only
replay-varying values a trace contains are the process-global rid
counters (engine rids and fleet rids keep counting across replays), so
export renormalizes them: each distinct rid is renamed to its dense
first-appearance index, separately per id space (``rid`` for engine
rids — ``erid`` args share the map — and ``frid`` for fleet rids).
Event order, injected-clock timestamps, slots, page ids and seeded
fault reasons are deterministic already; JSON is dumped with sorted
keys and fixed separators.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from paddle_tpu.obs.trace import Event

__all__ = ["chrome_trace", "dumps_chrome", "save_chrome_trace",
           "load_events", "save_events"]

# args keys that carry replay-varying rid counters, and the id space
# whose normalization map they share
_NORMALIZED_ARGS = {"rid": "rid", "erid": "rid", "frid": "frid"}


def _ts_us(ts: float) -> int:
    return int(round(ts * 1e6))


def chrome_trace(events: Sequence[Event],
                 normalize_ids: bool = True) -> Dict[str, object]:
    """Build the Chrome trace dict (``{"traceEvents": [...]}``).  With
    ``normalize_ids`` (the default) rid-valued ids and args are renamed
    to dense per-space indices in first-appearance order, which is what
    makes two seeded replays export identically."""
    maps: Dict[str, Dict[int, int]] = {}

    def norm(space: str, v):
        if not normalize_ids or not isinstance(v, int):
            return v
        m = maps.setdefault(space, {})
        if v not in m:
            m[v] = len(m)
        return m[v]

    pids = set()
    tids = set()          # (pid, tid)
    out: List[Dict[str, object]] = []
    for ev in events:
        pid = int(ev.replica) if ev.replica is not None else 0
        tid = int(ev.slot) + 1 if ev.slot is not None else 0
        pids.add(pid)
        tids.add((pid, tid))
        args = {}
        for k in sorted(ev.args):
            v = ev.args[k]
            if k in _NORMALIZED_ARGS:
                v = norm(_NORMALIZED_ARGS[k], v)
            args[k] = list(v) if isinstance(v, tuple) else v
        rec: Dict[str, object] = {"name": ev.name, "cat": ev.cat,
                                  "ts": _ts_us(ev.ts), "pid": pid,
                                  "tid": tid}
        if args:
            rec["args"] = args
        if ev.kind == "X":
            rec["ph"] = "X"
            rec["dur"] = max(0, _ts_us(ev.ts + ev.dur) - _ts_us(ev.ts))
        elif ev.kind == "i":
            rec["ph"] = "i"
            rec["s"] = "t"
        elif ev.kind in ("b", "e"):
            rec["ph"] = ev.kind
            rec["id"] = norm(ev.id_space, ev.id)
        else:
            continue
        out.append(rec)
    meta: List[Dict[str, object]] = []
    for pid in sorted(pids):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "args": {"name": f"replica {pid}"}})
    for pid, tid in sorted(tids):
        name = "control" if tid == 0 else f"slot {tid - 1}"
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": name}})
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def dumps_chrome(events: Sequence[Event],
                 normalize_ids: bool = True) -> str:
    """Deterministic serialization of :func:`chrome_trace` (sorted keys,
    fixed separators) — the byte-for-byte replay contract."""
    return json.dumps(chrome_trace(events, normalize_ids=normalize_ids),
                      sort_keys=True, separators=(",", ":"))


def save_chrome_trace(events: Sequence[Event], path: str,
                      normalize_ids: bool = True) -> str:
    with open(path, "w") as f:
        f.write(dumps_chrome(events, normalize_ids=normalize_ids))
    return path


def save_events(events: Sequence[Event], path: str) -> str:
    """Raw JSONL event dump (``Tracer.save`` shape)."""
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev.to_dict(), sort_keys=True,
                               separators=(",", ":")) + "\n")
    return path


def load_events(path: str) -> List[Event]:
    """Read raw events back: JSONL (``Tracer.save``) or a postmortem
    dump (``{"reason": ..., "events": [...]}``)."""
    with open(path) as f:
        text = f.read()
    try:
        payload = json.loads(text)
    except json.JSONDecodeError:
        payload = None
    if isinstance(payload, dict) and "events" in payload:
        return [Event.from_dict(d) for d in payload["events"]]
    if isinstance(payload, dict):       # a single-event JSONL file
        return [Event.from_dict(payload)]
    out: List[Event] = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            out.append(Event.from_dict(json.loads(line)))
    return out
