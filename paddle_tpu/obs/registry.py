"""Unified metrics registry: counter / gauge / histogram with labeled
series, one ``snapshot()`` / ``to_text()`` surface.

Before this module every layer kept its own counters —
``ServingMetrics`` (per engine), ``FleetMetrics`` (per router),
``platform/stats.StatSet`` (the trainer's timer table), the engine's
``healthz()`` — each with a private dict shape, so a scraper (or
``bench.py``) had to know every layer's spelling.  Now each of those
*publishes into* one :class:`MetricsRegistry` (``ServingMetrics.publish``
/ ``FleetMetrics.publish`` / ``StatSet.publish``) and everything reads
one surface:

- ``snapshot()`` — flat JSON-able dict ``{"name{k=v,...}": value}``
  (histograms contribute ``_count`` / ``_sum`` / ``_max`` series), the
  shape ``bench.py`` workers and ``healthz()`` consume;
- ``to_text()`` — Prometheus-style exposition for an external scraper.

Series are keyed by sorted label tuples, so two publishers using the
same labels in different order land on the same series.  All operations
are plain host dict math — safe on the serving tick hot path — and the
registry never reads the clock: time enters only through observed
values, so the repo's injectable-clock contract is preserved.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "default_registry"]

LabelKey = Tuple[Tuple[str, str], ...]

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: LabelKey) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


def _label_str_quoted(key: LabelKey) -> str:
    """Exposition-format spelling: label VALUES are double-quoted
    (``replica="0"``) — a real Prometheus scraper rejects the whole
    scrape otherwise.  ``snapshot()`` keys keep the unquoted spelling
    (the compact bench/healthz dict contract)."""
    return ",".join(f'{k}="{v}"' for k, v in key)


class _Metric:
    """Shared series bookkeeping.  ``labels(**kv)`` returns the series
    for that label set (created on first use); calling the value methods
    directly on the metric addresses the label-less series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        # one lock per metric, SHARED with every series it creates: a
        # scrape (snapshot/to_text) and a writer thread (checkpoint
        # writer bumping a counter, a master handler observing a
        # latency) race on the same series fields, and `value += n` /
        # the histogram's count-then-sum-then-bucket walk are not
        # atomic — the CONC-AUDIT fix that replaced the old unlocked
        # series (lost increments, torn count/sum pairs under scrape).
        self._series: Dict[LabelKey, object] = {}   # guarded_by(_lock)

    def _new_series(self):
        raise NotImplementedError

    def labels(self, **kv):
        key = _label_key(kv)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = self._new_series()
            return s

    def series(self) -> List[Tuple[LabelKey, object]]:
        with self._lock:
            return sorted(self._series.items())


class _CounterSeries:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock                 # the owning metric's lock
        self.value = 0.0                  # guarded_by(_lock)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Counter(_Metric):
    """Monotonic counter."""

    kind = "counter"

    def _new_series(self):
        return _CounterSeries(self._lock)

    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    @property
    def value(self) -> float:
        s = self.labels()
        with s._lock:
            return s.value


class _GaugeSeries:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock                 # the owning metric's lock
        self.value = 0.0                  # guarded_by(_lock)

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge(_Metric):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def _new_series(self):
        return _GaugeSeries(self._lock)

    def set(self, v: float) -> None:
        self.labels().set(v)

    @property
    def value(self) -> float:
        s = self.labels()
        with s._lock:
            return s.value


class _HistogramSeries:
    __slots__ = ("_lock", "buckets", "counts", "count", "sum", "max")

    def __init__(self, buckets: Sequence[float], lock: threading.Lock):
        self._lock = lock                 # the owning metric's lock
        self.buckets = tuple(buckets)     # immutable after init
        self.counts = [0] * (len(self.buckets) + 1)  # guarded_by(_lock)
        self.count = 0                    # guarded_by(_lock)
        self.sum = 0.0                    # guarded_by(_lock)
        self.max = 0.0                    # guarded_by(_lock)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.max = max(self.max, v)
            for i, edge in enumerate(self.buckets):
                if v <= edge:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    @property
    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else 0.0


class Histogram(_Metric):
    """Fixed-bucket histogram (count / sum / max / per-bucket counts)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(buckets)

    def _new_series(self):
        return _HistogramSeries(self.buckets, self._lock)

    def observe(self, v: float) -> None:
        self.labels().observe(v)


class MetricsRegistry:
    """Name -> metric table with get-or-create accessors.  A name keeps
    the kind it was first created with; asking for it as a different
    kind is a programming error and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}   # guarded_by(_lock)

    def _get(self, name: str, cls, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    # ---- the one scrape surface ------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        """Flat ``{"name{labels}": value}`` dict (deterministic order:
        names, then label keys).  Histograms flatten to ``_count`` /
        ``_sum`` / ``_max`` entries, so the whole snapshot is one level
        of JSON-able floats — the ``bench.py`` one-line contract."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            for key, s in m.series():
                tag = f"{m.name}{{{_label_str(key)}}}" if key else m.name
                if m.kind == "histogram":
                    with s._lock:     # count/sum/max read as one unit
                        out[tag + "_count"] = s.count
                        out[tag + "_sum"] = s.sum
                        out[tag + "_max"] = s.max
                else:
                    with s._lock:
                        out[tag] = s.value
        return out

    def to_text(self) -> str:
        """Prometheus-style text exposition (# HELP / # TYPE then one
        line per series), deterministically ordered."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, s in m.series():
                lbl = "{" + _label_str_quoted(key) + "}" if key else ""
                extra = "," + _label_str_quoted(key) if key else ""
                if m.kind == "histogram":
                    with s._lock:     # one consistent bucket/count/sum view
                        counts = list(s.counts)
                        count, total = s.count, s.sum
                    acc = 0
                    for edge, c in zip(s.buckets, counts):
                        acc += c
                        lines.append(f'{m.name}_bucket{{le="{edge}"'
                                     f"{extra}}} {acc}")
                    lines.append(f'{m.name}_bucket{{le="+Inf"'
                                 f"{extra}}} {count}")
                    lines.append(f"{m.name}_count{lbl} {count}")
                    lines.append(f"{m.name}_sum{lbl} {total}")
                else:
                    with s._lock:
                        lines.append(f"{m.name}{lbl} {s.value}")
        return "\n".join(lines) + ("\n" if lines else "")


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-global registry: layers with no owning engine/router
    (the trainer's StatSet publish, ad-hoc tooling) publish here."""
    return _DEFAULT
