"""Request-scoped span tracer + flight recorder for the serving fleet.

One seeded chaos replay used to leave its evidence scattered across
``ServingMetrics``, ``FleetMetrics.snapshot()``, ``healthz()`` and the
retrace auditor — none of which could answer "what happened to fleet
rid 17 between admission and its resubmit to replica 2".  The tracer
turns every lifecycle edge into a structured :class:`Event` on ONE
timeline:

- request edges: ``submit`` -> ``route`` -> ``admit`` ->
  ``prefill_chunk`` -> ``decode_tick`` -> ``preempt`` / ``resubmit`` ->
  ``terminal``, with a ``fleet_request`` async root span per fleet rid
  (begin at ``FleetRouter.submit``, end at its single terminal
  transition — the exactly-once invariant made visible);
- fleet control edges: replica join/ready/fence/reap/drain, lease
  register/renew-reject/expire/drop;
- pool edges: ``page_alloc`` / ``page_ref`` / ``page_free`` /
  ``page_evict``;
- compile edges: the retrace auditor reports each ``jit_compile`` when
  a tracer is attached (``RetraceAuditor.attach_tracer``).

Design contracts (the same ones the rest of the repo pins):

- **injected clock only** — the tracer stamps events with the
  ``time_fn`` it was built on (a fleet/fault-plan ``ManualClock`` in
  tests, ``time.monotonic`` as the injectable default in production).
  The ``analysis.lint`` wall-clock rule covers ``paddle_tpu/obs`` too,
  so the tracer itself cannot smuggle wall-clock reads into serving.
- **zero overhead when off** — ``tracer_for`` returns the
  :data:`NULL_TRACER` singleton unless ``FLAGS.obs_trace`` is on
  (checked at construction, the ``audit_jit`` wrap-time idiom).  Every
  null method is a constant no-op returning a shared context manager;
  no event objects, no clock reads, no device work — the sealed-auditor
  test pins that an obs-off engine decodes with the same compile count
  and the same one-readback-per-tick sync budget.
- **determinism** — events carry only deterministic payloads (ticks,
  slots, page ids, seeded reasons); process-global rid counters are
  normalized away at export time, so two replays of the same seeded
  ``FleetFaultPlan`` export byte-identical Chrome traces
  (``obs.export``).

The **flight recorder** is the tracer's bounded ring
(``FLAGS.obs_ring_size`` most recent events).  ``dump_postmortem``
writes the ring to ``FLAGS.obs_dump_dir`` and prints a grep-able
``OBS-POSTMORTEM: <path>`` line; the engine and fleet call it when a
tier-1 ladder invariant (PAGE-LEAK / REF-LEAK / FLEET-LEAK) trips, so a
leak report arrives WITH the event history that produced it
(``tools_tier1.sh`` surfaces the path on any ladder exit).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from paddle_tpu.platform.flags import FLAGS

__all__ = ["Event", "Tracer", "NULL_TRACER", "tracer_for"]

_POSTMORTEM_SEQ = itertools.count()


@dataclass
class Event:
    """One structured trace event.

    ``kind`` follows the Chrome trace phase alphabet the exporter maps
    to directly: ``"X"`` complete span (with ``dur``), ``"i"`` instant,
    ``"b"``/``"e"`` async span begin/end (paired by ``id`` within
    ``id_space``).  ``replica``/``slot`` become the exporter's
    process/thread lanes; everything else rides in ``args``."""

    kind: str
    name: str
    ts: float
    cat: str = "serving"
    dur: float = 0.0
    replica: Optional[int] = None
    slot: Optional[int] = None
    id: Optional[int] = None
    id_space: str = "rid"
    args: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, object]:
        d: Dict[str, object] = {"kind": self.kind, "name": self.name,
                                "ts": self.ts, "cat": self.cat}
        if self.kind == "X":
            d["dur"] = self.dur
        if self.replica is not None:
            d["replica"] = self.replica
        if self.slot is not None:
            d["slot"] = self.slot
        if self.id is not None:
            d["id"] = self.id
            d["id_space"] = self.id_space
        if self.args:
            d["args"] = {k: (list(v) if isinstance(v, tuple) else v)
                         for k, v in sorted(self.args.items())}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Event":
        return cls(kind=d["kind"], name=d["name"], ts=float(d["ts"]),
                   cat=d.get("cat", "serving"), dur=float(d.get("dur", 0.0)),
                   replica=d.get("replica"), slot=d.get("slot"),
                   id=d.get("id"), id_space=d.get("id_space", "rid"),
                   args=dict(d.get("args", {})))


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_replica", "_slot", "_args",
                 "_start")

    def __init__(self, tracer, name, cat, replica, slot, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._replica = replica
        self._slot = slot
        self._args = args
        self._start = 0.0

    def __enter__(self):
        self._start = self._tracer._time()
        return self

    def __exit__(self, *exc):
        t = self._tracer
        end = t._time()
        t._record(Event(kind="X", name=self._name, ts=self._start,
                        cat=self._cat, dur=max(0.0, end - self._start),
                        replica=self._replica, slot=self._slot,
                        args=self._args))
        return False


class Tracer:
    """Span/event recorder on an injected clock (see module doc).

    ``keep_all=True`` (the default) retains the full event list for
    export; the bounded ring (the flight recorder) always holds the
    most recent ``ring_size`` events either way, so a long-running
    production tracer can run ``keep_all=False`` and still dump a
    postmortem."""

    enabled = True

    def __init__(self, time_fn: Optional[Callable[[], float]] = None,
                 ring_size: Optional[int] = None,
                 registry=None, keep_all: bool = True):
        self._time = time_fn or time.monotonic
        if ring_size is None:
            ring_size = int(FLAGS.obs_ring_size)
        self.ring_size = max(1, int(ring_size))
        self._keep_all = bool(keep_all)
        self.registry = registry
        self._lock = threading.Lock()
        # one tracer is shared by every replica's engine AND the master
        # handler threads (the fleet hands out scoped() views of the
        # same base), so the event stores only move under the lock
        self.ring: Deque[Event] = deque(maxlen=self.ring_size)  # guarded_by(_lock)
        self.events: List[Event] = []                # guarded_by(_lock)
        self._open: Dict[Tuple, Tuple[float, Dict[str, object],
                                      Optional[int], Optional[int],
                                      str]] = {}    # guarded_by(_lock)
        # events past ring_size (keep_all=False)
        self.dropped = 0                             # guarded_by(_lock)
        self.last_postmortem: Optional[str] = None   # guarded_by(_lock)

    # ---- recording --------------------------------------------------------

    def _record(self, ev: Event) -> None:
        with self._lock:
            if self._keep_all:
                self.events.append(ev)
            if len(self.ring) == self.ring_size:
                # counts events displaced OUT of the ring in both modes,
                # so a postmortem's dropped_before_ring is honest even
                # when keep_all retains the full list elsewhere
                self.dropped += 1
            self.ring.append(ev)
        reg = self.registry
        if reg is not None and ev.kind == "X":
            reg.histogram("obs_span_seconds",
                          "duration of traced spans by name").labels(
                name=ev.name).observe(ev.dur)

    def span(self, name: str, cat: str = "serving",
             replica: Optional[int] = None, slot: Optional[int] = None,
             **args) -> _Span:
        """``with tracer.span("decode_tick", tick=7): ...`` — records one
        complete event whose duration is measured on the injected clock
        (zero-width under a ManualClock that advances per tick, real
        durations on a wall clock)."""
        return _Span(self, name, cat, replica, slot, args)

    def instant(self, name: str, cat: str = "serving",
                replica: Optional[int] = None, slot: Optional[int] = None,
                **args) -> None:
        self._record(Event(kind="i", name=name, ts=self._time(), cat=cat,
                           replica=replica, slot=slot, args=args))

    def begin(self, name: str, key=None, cat: str = "serving",
              replica: Optional[int] = None, slot: Optional[int] = None,
              **args) -> None:
        """Open an explicit span (the trainer event bridge's idiom, where
        begin and end happen in different callbacks).  ``key`` pairs it
        with the matching :meth:`end`; defaults to the name alone."""
        with self._lock:
            self._open[(name, key)] = (self._time(), dict(args),
                                       replica, slot, cat)

    def end(self, name: str, key=None, cat: Optional[str] = None,
            **args) -> None:
        """Close a :meth:`begin` span.  The category recorded is the one
        ``begin`` opened with unless ``cat`` overrides it here."""
        with self._lock:
            opened = self._open.pop((name, key), None)
        now = self._time()
        if opened is None:
            start, base, replica, slot, opened_cat = now, {}, None, None, \
                "serving"
        else:
            start, base, replica, slot, opened_cat = opened
        base.update(args)
        self._record(Event(kind="X", name=name, ts=start,
                           cat=cat if cat is not None else opened_cat,
                           dur=max(0.0, now - start), replica=replica,
                           slot=slot, args=base))

    def async_begin(self, name: str, id: int, id_space: str = "rid",
                    cat: str = "request", replica: Optional[int] = None,
                    **args) -> None:
        """Begin a root-level async span (e.g. one ``fleet_request`` per
        fleet rid) — paired with :meth:`async_end` by ``id`` at export."""
        self._record(Event(kind="b", name=name, ts=self._time(), cat=cat,
                           replica=replica, id=int(id), id_space=id_space,
                           args=args))

    def async_end(self, name: str, id: int, id_space: str = "rid",
                  cat: str = "request", replica: Optional[int] = None,
                  **args) -> None:
        self._record(Event(kind="e", name=name, ts=self._time(), cat=cat,
                           replica=replica, id=int(id), id_space=id_space,
                           args=args))

    # ---- views / scoping --------------------------------------------------

    def scoped(self, **labels) -> "_ScopedTracer":
        """A view of this tracer with ``replica=``/``slot=`` defaults
        bound (the fleet hands each engine ``scoped(replica=idx)``, so
        engine-side instrumentation needs no fleet awareness)."""
        return _ScopedTracer(self, labels)

    @property
    def base(self) -> "Tracer":
        return self

    # ---- persistence ------------------------------------------------------

    def save(self, path: str) -> str:
        """Write the full event list as JSONL (one event dict per line)
        — the raw format ``python -m paddle_tpu.obs export`` consumes.
        One writer: delegates to :func:`obs.export.save_events` so the
        on-disk shape cannot diverge between the two entry points."""
        from paddle_tpu.obs.export import save_events
        with self._lock:
            evs = list(self.events if self._keep_all else self.ring)
        return save_events(evs, path)

    def dump_postmortem(self, reason: str,
                        dump_dir: Optional[str] = None) -> str:
        """Flight-recorder dump: write the ring (the most recent
        ``ring_size`` events) plus the reason to a postmortem file under
        ``FLAGS.obs_dump_dir`` and print the grep-able
        ``OBS-POSTMORTEM: <path>`` line tools_tier1.sh surfaces.
        Filenames use a process-global sequence, not the wall clock."""
        d = dump_dir or str(FLAGS.obs_dump_dir)
        os.makedirs(d, exist_ok=True)
        slug = "".join(c if c.isalnum() else "-" for c in reason.lower())
        path = os.path.join(
            d, f"postmortem-{slug[:40]}-{next(_POSTMORTEM_SEQ):04d}.json")
        with self._lock:
            payload = {"reason": reason, "ring_size": self.ring_size,
                       "dropped_before_ring": self.dropped,
                       "events": [ev.to_dict() for ev in self.ring]}
        with open(path, "w") as f:
            json.dump(payload, f, sort_keys=True, separators=(",", ":"))
        with self._lock:
            self.last_postmortem = path
        print(f"OBS-POSTMORTEM: {path}", flush=True)
        return path


class _ScopedTracer:
    """Label-binding proxy over a :class:`Tracer` (or another scope).
    Every call forwards to the base with the bound ``replica``/``slot``
    filled in unless the call site overrides them."""

    __slots__ = ("_base", "_labels")
    enabled = True

    def __init__(self, base: Tracer, labels: Dict[str, object]):
        self._base = base
        self._labels = {k: v for k, v in labels.items()
                        if k in ("replica", "slot")}

    @property
    def base(self) -> Tracer:
        return self._base

    @property
    def registry(self):
        return self._base.registry

    def span(self, name: str, **kw):
        merged = dict(self._labels)
        merged.update(kw)
        return self._base.span(name, **merged)

    def instant(self, name: str, **kw) -> None:
        merged = dict(self._labels)
        merged.update(kw)
        self._base.instant(name, **merged)

    def begin(self, name: str, **kw) -> None:
        merged = dict(self._labels)
        merged.update(kw)
        self._base.begin(name, **merged)

    def end(self, name: str, **kw) -> None:
        self._base.end(name, **kw)

    def async_begin(self, name: str, id: int, **kw) -> None:
        merged = dict(self._labels)
        merged.update(kw)
        self._base.async_begin(name, id, **merged)

    def async_end(self, name: str, id: int, **kw) -> None:
        merged = dict(self._labels)
        merged.update(kw)
        self._base.async_end(name, id, **merged)

    def scoped(self, **labels) -> "_ScopedTracer":
        merged = dict(self._labels)
        merged.update(labels)
        return _ScopedTracer(self._base, merged)

    def dump_postmortem(self, reason: str,
                        dump_dir: Optional[str] = None) -> str:
        return self._base.dump_postmortem(reason, dump_dir)

    def save(self, path: str) -> str:
        return self._base.save(path)


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullContext()


class _NullTracer:
    """The obs-off tracer: every method is a constant no-op.  One shared
    instance (:data:`NULL_TRACER`) serves the whole process, so a
    disabled engine pays one attribute call per instrumentation point —
    no events, no clock reads, no device work."""

    enabled = False
    registry = None
    ring: Deque = deque(maxlen=1)
    events: List = []
    last_postmortem = None

    @property
    def base(self) -> "_NullTracer":
        return self

    def span(self, name: str, **kw) -> _NullContext:
        return _NULL_CTX

    def instant(self, name: str, **kw) -> None:
        pass

    def begin(self, name: str, **kw) -> None:
        pass

    def end(self, name: str, **kw) -> None:
        pass

    def async_begin(self, name: str, id: int, **kw) -> None:
        pass

    def async_end(self, name: str, id: int, **kw) -> None:
        pass

    def scoped(self, **labels) -> "_NullTracer":
        return self

    def dump_postmortem(self, reason: str,
                        dump_dir: Optional[str] = None) -> None:
        return None

    def save(self, path: str) -> None:
        return None


NULL_TRACER = _NullTracer()


def tracer_for(time_fn: Optional[Callable[[], float]] = None,
               registry=None):
    """The construction-time gate (the ``audit_jit`` wrap-time idiom):
    a real :class:`Tracer` on ``time_fn`` when ``FLAGS.obs_trace`` is
    on, the shared :data:`NULL_TRACER` otherwise.  Engines and routers
    call this once at construction — flip the flag BEFORE building the
    engine being traced."""
    if not getattr(FLAGS, "obs_trace", False):
        return NULL_TRACER
    # keep_all=False (FLAGS.obs_keep_all off) bounds a long-running
    # service's tracing memory to the flight-recorder ring; the default
    # retains everything for whole-replay export
    return Tracer(time_fn=time_fn, registry=registry,
                  keep_all=bool(getattr(FLAGS, "obs_keep_all", True)))
