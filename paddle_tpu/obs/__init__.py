"""paddle_tpu.obs — request-scoped tracing, unified metrics, and a
chaos flight recorder.

Three pieces, one timeline:

- :mod:`~paddle_tpu.obs.trace` — the span :class:`Tracer` on the
  injected clock (``FLAGS.obs_trace`` gates it at construction; the
  :data:`NULL_TRACER` singleton makes the off state a true no-op) plus
  the bounded flight-recorder ring that auto-dumps a postmortem file
  when a conservation invariant (PAGE-LEAK / REF-LEAK / FLEET-LEAK)
  trips;
- :mod:`~paddle_tpu.obs.registry` — counter/gauge/histogram
  :class:`MetricsRegistry` that ``ServingMetrics`` / ``FleetMetrics`` /
  ``platform.stats.StatSet`` publish into, with one ``snapshot()`` /
  ``to_text()`` scrape surface;
- :mod:`~paddle_tpu.obs.export` — Chrome-trace/Perfetto JSON exporter
  (replicas -> processes, slots -> threads), byte-deterministic across
  seeded replays; ``python -m paddle_tpu.obs export`` is the CLI.

:mod:`~paddle_tpu.obs.bridge` connects the v2 trainer's event stream to
the same span format, so training and serving traces open in the same
Perfetto view.
"""

from paddle_tpu.obs.bridge import publish_resilience, trainer_event_bridge
from paddle_tpu.obs.export import (chrome_trace, dumps_chrome, load_events,
                                   save_chrome_trace, save_events)
from paddle_tpu.obs.registry import (Counter, Gauge, Histogram,
                                     MetricsRegistry, default_registry)
from paddle_tpu.obs.trace import NULL_TRACER, Event, Tracer, tracer_for

__all__ = [
    "Event", "Tracer", "NULL_TRACER", "tracer_for",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "default_registry",
    "chrome_trace", "dumps_chrome", "save_chrome_trace", "save_events",
    "load_events", "trainer_event_bridge", "publish_resilience",
]
