"""minibatch.batch — group a sample reader into batches.

Reference: python/paddle/v2/minibatch.py (batch(reader, batch_size)).
``drop_last`` defaults True here: TPU compilation wants static batch shapes,
and a ragged final batch would trigger a recompile (documented divergence).
"""

from __future__ import annotations


def batch(reader, batch_size: int, drop_last: bool = True):
    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
