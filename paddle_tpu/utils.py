"""User utilities: config dump, model diagram, torch parameter import.

Reference analog: python/paddle/utils — make_model_diagram.py (graphviz
dot export of a ModelConfig), dump_config.py / show_pb.py, and
torch2paddle.py (import torch-trained weights).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.parameters import Parameters
from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.topology import Topology


def topology_to_config(topology: Topology) -> Dict:
    """Serialize a Topology to a JSON-able dict — the ModelConfig proto
    analog (config_parser output). Structural only: layer graph, sizes,
    parameter shapes; compute stays in python (the jit'd forward)."""
    layers: List[Dict] = []
    name_to_param = {}
    for node in topology.nodes:
        entry = {
            "name": node.name,
            "type": node.layer_type,
            "size": node.size,
            "inputs": [i.name for i in node.inputs],
            "is_sequence": bool(node.is_sequence),
        }
        if getattr(node, "img_shape", None):
            entry["img_shape"] = list(node.img_shape)
        if node.params:
            entry["params"] = {}
            for pname, spec in node.params.items():
                full = spec.attr.name or f"{node.name}.{pname}"
                entry["params"][pname] = {"name": full,
                                          "shape": list(spec.shape)}
                name_to_param[full] = list(spec.shape)
        layers.append(entry)
    return {
        "format": "paddle_tpu_model_config_v1",
        "layers": layers,
        "parameters": [{"name": k, "shape": v}
                       for k, v in sorted(name_to_param.items())],
        "input_layers": [n.name for n in topology.data_nodes],
        "output_layers": [n.name for n in topology.outputs],
    }


def dump_config(topology: Topology, indent: int = 2) -> str:
    """config dump (reference: paddle dump_config / utils.dump_v2_config)."""
    return json.dumps(topology_to_config(topology), indent=indent)


def make_model_diagram(topology: Topology,
                       graph_name: str = "model") -> str:
    """Graphviz dot text of the layer graph (reference:
    python/paddle/utils/make_model_diagram.py)."""
    cfg = topology_to_config(topology)
    lines = [f"digraph {graph_name} {{", "  rankdir=TB;"]
    for lay in cfg["layers"]:
        shape = "box"
        if lay["type"] == "data":
            shape = "oval"
        elif lay["name"] in cfg["output_layers"]:
            shape = "doubleoctagon"
        label = f"{lay['name']}\\n{lay['type']}"
        if lay["size"]:
            label += f" [{lay['size']}]"
        lines.append(f'  "{lay["name"]}" [shape={shape}, label="{label}"];')
    for lay in cfg["layers"]:
        for src in lay["inputs"]:
            lines.append(f'  "{src}" -> "{lay["name"]}";')
    lines.append("}")
    return "\n".join(lines)


def gradient_check(cost, parameters, feeds, *, sample_entries: int = 8,
                   eps: float = 1e-3, seed: int = 0,
                   rtol: float = 2e-2) -> Dict[str, float]:
    """Numeric-vs-analytic gradient check over a whole topology — the user
    surface of the reference trainer's gradient check job
    (Trainer::train's test_all_data_in_one_period gradient path and the
    per-layer testLayerGrad strategy, gserver/tests/LayerGradUtil.h:298).

    For each parameter, ``sample_entries`` random entries are perturbed
    (central differences, f64 accumulation of the cost) and compared to
    jax.grad of the summed cost. Returns {param_name: max relative error}
    and raises EnforceError when any exceeds ``rtol``.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.platform.flags import FLAGS
    from paddle_tpu.trainer import _reduce_cost  # local: avoids a cycle

    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False  # central differences drown in bf16 loss noise
    try:
        topo = Topology([cost])
        specs = topo.param_specs()
        pdict = {k: jnp.asarray(v) for k, v in dict(
            parameters.as_dict() if hasattr(parameters, "as_dict")
            else parameters).items() if k in specs}
        state = topo.init_state()

        def loss_fn(p):
            outs, _ = topo.forward(p, state, feeds, train=False)
            return _reduce_cost(outs[0])

        analytic = jax.grad(loss_fn)(pdict)
        loss_jit = jax.jit(loss_fn)
        rng = np.random.RandomState(seed)
        report: Dict[str, float] = {}

        def loss_at(name, val, i, delta):
            flat = np.asarray(val, np.float64).ravel()
            flat[i] += delta
            return float(loss_jit(
                {**pdict, name: jnp.asarray(flat.reshape(val.shape),
                                            val.dtype)}))

        for name, val in pdict.items():
            flat_size = int(val.size)
            idxs = rng.choice(flat_size, size=min(sample_entries, flat_size),
                              replace=False)
            ana_flat = np.asarray(analytic[name]).ravel()  # one D2H copy
            worst = 0.0
            for i in idxs:
                ana = float(ana_flat[i])

                def rel_err(e):
                    num = (loss_at(name, val, i, +e)
                           - loss_at(name, val, i, -e)) / (2 * e)
                    return abs(num - ana) / max(abs(num), abs(ana), 1e-4)

                err = rel_err(eps)
                if err > rtol:
                    # two ways central differences fail on a CORRECT
                    # gradient: a kink (relu/abs) inside ±eps — smaller
                    # eps shrinks the window — and f32 loss resolution
                    # drowning a small slope — larger eps lifts the
                    # signal above the ~1e-7 relative ulp. Retry both
                    # before calling it wrong (the reference's
                    # perturbation checks share these caveats,
                    # LayerGradUtil.h:203); a genuinely wrong analytic
                    # gradient fails at every eps.
                    err = min(err, rel_err(eps / 8), rel_err(eps * 8))
                worst = max(worst, err)
            report[name] = worst
        # full report first, ONE failure listing every offender
        bad = {k: v for k, v in report.items() if v > rtol}
        enforce_that(not bad, "gradient check failed: " + ", ".join(
            f"{k}: rel err {v:.4g} > {rtol}" for k, v in sorted(bad.items())),
            context="gradient_check")
        return report
    finally:
        FLAGS.use_bf16 = old_bf16


def compare_topologies(node_a, node_b, feeds_a, feeds_b=None, *,
                       seed: int = 0, param_link: Optional[Dict[str, str]] = None,
                       check_inputs: tuple = (), rtol: float = 1e-4,
                       atol: float = 1e-5, flags_a: Optional[Dict] = None,
                       flags_b: Optional[Dict] = None):
    """Assert two differently-expressed topologies compute the SAME function:
    identical outputs AND identical gradients on the same data.

    The network-equivalence harness (reference:
    gserver/tests/test_NetworkCompare.cpp + trainer/tests/
    test_CompareTwoNets.cpp — config pairs trained side by side with
    compareGradient): express one computation two ways (fc vs
    mixed-projections, lstmemory vs a recurrent_group of lstm_step, flash vs
    plain attention kernels, ...) and require bit-level agreement to float
    tolerance.

    Parameters are LINKED BY NAME: each topology is initialized with the
    same seed, then every parameter name they share (plus ``param_link``
    entries mapping b-name → a-name) is copied from A into B, so linked
    weights are identical. Use ``ParamAttr(name=...)`` in the configs to
    give corresponding weights the same name. Gradients of the
    mean-reduced first output are compared for every linked parameter and
    for each feed name in ``check_inputs`` (feeds must then be identical
    dense arrays in both feed dicts). ``flags_a``/``flags_b`` override
    FLAGS around each side's forward+grad (e.g. ``flags_b={"use_pallas":
    False}`` to compare a pallas kernel against its plain-XLA fallback).
    Returns (out_a, out_b, grads_a, grads_b).
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.platform.flags import FLAGS
    from paddle_tpu.sequence import SequenceBatch
    from paddle_tpu.trainer import _reduce_cost

    feeds_b = feeds_a if feeds_b is None else feeds_b
    param_link = dict(param_link or {})

    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False  # bit-compare needs one rounding behavior
    try:
        topo_a, topo_b = Topology([node_a]), Topology([node_b])
        pa = dict(Parameters.from_topology(topo_a, seed=seed).as_dict())
        pb = dict(Parameters.from_topology(topo_b, seed=seed).as_dict())
        shared = sorted(set(pa) & set(pb))
        for nb in shared:
            param_link.setdefault(nb, nb)
        enforce_that(bool(param_link) or bool(check_inputs),
                     "nothing to compare gradients through — link weights "
                     "via ParamAttr names or pass check_inputs",
                     context="compare")
        for nb, na in param_link.items():
            enforce_that(np.shape(pa[na]) == np.shape(pb[nb]),
                         f"linked param shape mismatch {na}~{nb}",
                         context="compare")
            pb[nb] = pa[na]

        def run(topo, params, feeds, overrides):
            olds = {k: getattr(FLAGS, k) for k in (overrides or {})}
            for k, v in (overrides or {}).items():
                setattr(FLAGS, k, v)
            in_names = list(check_inputs)

            # one forward+backward: params and checked inputs differentiate
            # together (argnums pair), instead of a second full pass
            def loss_fn(p, fvals):
                f = {**feeds, **dict(zip(in_names, fvals))}
                outs, _ = topo.forward(p, topo.init_state(), f, train=False)
                o = outs[0]
                return _reduce_cost(o), (o.data if isinstance(o, SequenceBatch)
                                         else o)

            fvals = [jnp.asarray(feeds[n], jnp.float32) for n in in_names]
            try:
                (loss, out), (gp, gf) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True)(params, fvals)
            finally:
                for k, v in olds.items():
                    setattr(FLAGS, k, v)
            return out, gp, dict(zip(in_names, gf))

        out_a, gpa, gia = run(topo_a, pa, feeds_a, flags_a)
        out_b, gpb, gib = run(topo_b, pb, feeds_b, flags_b)

        oa, ob = np.asarray(out_a), np.asarray(out_b)
        # image layers may emit [B,H,W,C] where an equivalent mixed/operator
        # path emits the flat [B,H*W*C]; canonicalize to per-example rows
        np.testing.assert_allclose(oa.reshape(oa.shape[0], -1),
                                   ob.reshape(ob.shape[0], -1),
                                   rtol=rtol, atol=atol,
                                   err_msg="outputs differ")
        for nb, na in sorted(param_link.items()):
            np.testing.assert_allclose(
                np.asarray(gpa[na]), np.asarray(gpb[nb]), rtol=rtol,
                atol=atol, err_msg=f"grad differs for linked param {na}~{nb}")
        for n in check_inputs:
            np.testing.assert_allclose(
                np.asarray(gia[n]), np.asarray(gib[n]), rtol=rtol, atol=atol,
                err_msg=f"grad differs for input {n}")
        return out_a, out_b, gpa, gpb
    finally:
        FLAGS.use_bf16 = old_bf16


def param_to_text(value, path: str) -> None:
    """Dump one parameter as the embedding-model text format (reference:
    v1_api_demo/model_zoo/embedding/paraconvert.py binary2text — header
    line ``version,floatSize,paraCount`` then comma-joined rows)."""
    arr = np.asarray(value, dtype=np.float32)
    rows = arr.reshape(-1, arr.shape[-1]) if arr.ndim > 1 else arr.reshape(1, -1)
    with open(path, "w") as f:
        f.write(f"0,4,{arr.size}\n")
        for row in rows:
            f.write(",".join(f"{x:.7f}" for x in row) + "\n")


def text_to_param(path: str, dim: Optional[int] = None) -> np.ndarray:
    """Load a text-format parameter file (paraconvert.py text2binary
    analog). Returns [rows, dim] float32 (or flat when rows carry no
    consistent dim)."""
    with open(path) as f:
        header = f.readline().strip().split(",")
        count = int(header[2])
        rows = [np.array(line.strip().split(","), dtype=np.float32)
                for line in f if line.strip()]
    flat = np.concatenate(rows) if rows else np.zeros(0, np.float32)
    if flat.size != count:
        raise ValueError(f"{path}: header says {count} values, got {flat.size}")
    if dim:
        return flat.reshape(-1, dim)
    widths = {r.size for r in rows}
    return flat.reshape(len(rows), rows[0].size) if len(widths) == 1 else flat


def extract_embedding(parameters: Parameters, name: str,
                      word_ids) -> np.ndarray:
    """Slice pretrained embedding rows for a word subset (reference:
    v1_api_demo/model_zoo/embedding/extract_para.py — the paragraph-vector
    extraction workflow: trained table -> the rows your task dict needs)."""
    table = np.asarray(parameters[name])
    return table[np.asarray(list(word_ids), dtype=np.int64)]


def torch2paddle(state_dict, parameters: Parameters,
                 name_map: Optional[Dict[str, str]] = None,
                 transpose_linear: bool = True) -> List[str]:
    """Import a torch ``state_dict`` into ``parameters``
    (reference: python/paddle/utils/torch2paddle.py).

    Matching is by ``name_map`` (torch name -> our param name) when given,
    else by identical name, else by unique shape match. torch Linear
    weights are [out, in]; ours are [in, out] (``transpose_linear``).
    Returns the list of imported parameter names."""
    ours = {k: np.asarray(v) for k, v in parameters.items()}
    imported: List[str] = []
    by_shape: Dict[tuple, List[str]] = {}
    for k, v in ours.items():
        by_shape.setdefault(tuple(v.shape), []).append(k)

    for tname, tval in state_dict.items():
        arr = np.asarray(tval.detach().cpu().numpy()
                         if hasattr(tval, "detach") else tval)
        target = None
        if name_map and tname in name_map:
            target = name_map[tname]
        elif tname in ours:
            target = tname
        else:
            cands = by_shape.get(tuple(arr.shape), [])
            cands_t = by_shape.get(tuple(arr.T.shape), []) \
                if arr.ndim == 2 else []
            if len(cands) == 1:
                target = cands[0]
            elif not cands and len(cands_t) == 1 and transpose_linear:
                target = cands_t[0]
        if target is None:
            continue
        dst_shape = ours[target].shape
        if arr.shape != dst_shape:
            if transpose_linear and arr.ndim == 2 \
                    and arr.T.shape == dst_shape:
                arr = arr.T
            else:
                continue
        elif (transpose_linear and arr.ndim == 2
              and arr.shape[0] == arr.shape[1]
              and tname.rsplit(".", 1)[-1] == "weight"):
            # square torch Linear weights match both ways; torch stores
            # [out, in] so '*.weight' still needs the transpose (square
            # embedding tables named '.weight' would be misflipped — pass
            # an explicit name_map for those)
            arr = arr.T
        parameters[target] = arr.astype(ours[target].dtype)
        imported.append(target)
    return imported
