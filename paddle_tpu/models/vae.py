"""Variational autoencoder.

Reference analog: v1_api_demo/vae/vae_train.py + vae_conf.py (MLP
encoder/decoder, reparameterised gaussian latent, BCE reconstruction +
KL). The reparameterisation noise comes from the per-step rng stream the
trainer already threads through the graph (ctx.rng_for), so the whole
model stays one pure jitted function.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu import data_type, layer
from paddle_tpu.topology import LayerOutput, unique_name


def _gaussian_sample(mu, logvar):
    """z = mu + eps * exp(0.5*logvar), eps ~ N(0, I) from the step rng."""
    name = unique_name("vae_sample")

    def compute(ctx, p, ins):
        m, lv = ins[0], ins[1]
        md = m.data if hasattr(m, "segment_ids") else m
        lvd = lv.data if hasattr(lv, "segment_ids") else lv
        eps = jax.random.normal(ctx.rng_for(name), md.shape, md.dtype)
        return md + eps * jnp.exp(0.5 * lvd)

    return LayerOutput(name=name, layer_type="gaussian_sample",
                       inputs=[mu, logvar], fn=compute, size=mu.size)


def _kl_cost(mu, logvar):
    """KL(q(z|x) || N(0,I)) per example."""
    name = unique_name("vae_kl")

    def compute(ctx, p, ins):
        m, lv = ins[0], ins[1]
        return -0.5 * jnp.sum(1.0 + lv - jnp.square(m) - jnp.exp(lv),
                              axis=-1)

    node = LayerOutput(name=name, layer_type="vae_kl",
                       inputs=[mu, logvar], fn=compute, size=1)
    node.is_cost = True
    return node


def build(data_dim: int = 32, hidden: Tuple[int, ...] = (64,),
          latent_dim: int = 8):
    """Returns (x, recon, cost) — cost = BCE(recon, x) + KL."""
    x = layer.data(name="pixel", type=data_type.dense_vector(data_dim))
    h = x
    for i, d in enumerate(hidden):
        h = layer.fc(h, size=d, act="relu", name=f"vae_enc{i}")
    mu = layer.fc(h, size=latent_dim, name="vae_mu")
    logvar = layer.fc(h, size=latent_dim, name="vae_logvar")
    z = _gaussian_sample(mu, logvar)
    g = z
    for i, d in enumerate(reversed(hidden)):
        g = layer.fc(g, size=d, act="relu", name=f"vae_dec{i}")
    recon_logit = layer.fc(g, size=data_dim, name="vae_recon")
    recon = layer.mixed(input=layer.identity_projection(recon_logit),
                        size=data_dim, act="sigmoid")
    bce = layer.multi_binary_label_cross_entropy_cost(input=recon_logit,
                                                      label=x)
    cost = layer.addto([bce, _kl_cost(mu, logvar)])
    cost.is_cost = True
    return x, recon, cost
