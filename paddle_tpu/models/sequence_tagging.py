"""Linear-CRF sequence tagging — the sequence_tagging demo.

Reference: v1_api_demo/sequence_tagging/linear_crf.py (chunking: word +
context-window features -> emission scores -> crf_layer cost, with a
crf_decoding twin sharing the transition parameters for evaluation).

TPU-native: the context window is an embedding + context_projection mixed
layer; the CRF forward (log-partition) and viterbi decode run as lax.scans
inside the jitted step (paddle_tpu/layer.py crf/crf_decoding).
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr


def build(vocab_size: int = 2000, num_tags: int = 9, emb_dim: int = 32,
          context_len: int = 5, hidden: int = 64):
    """Returns (word, label, crf_cost, decoded) LayerOutputs.

    ``decoded`` is the viterbi path from a crf_decoding layer sharing the
    cost layer's transitions via the 'crf_tag' parameter-name prefix
    (reference: linear_crf.py shares via parameter_name)."""
    word = layer.data(
        name="word", type=paddle.data_type.integer_value_sequence(vocab_size))
    label = layer.data(
        name="label", type=paddle.data_type.integer_value_sequence(num_tags))

    emb = layer.embedding(input=word, size=emb_dim)
    ctx = layer.mixed(
        size=emb_dim * context_len,
        input=[layer.context_projection(input=emb,
                                        context_len=context_len,
                                        context_start=-(context_len // 2))])
    feat = layer.fc(input=ctx, size=hidden, act="tanh")
    emission = layer.fc(input=feat, size=num_tags, name="emission")

    shared = ParamAttr(name="crf_tag")
    cost = layer.crf(input=emission, label=label, size=num_tags,
                     param_attr=shared)
    decoded = layer.crf_decoding(input=emission, size=num_tags,
                                 param_attr=shared)
    return word, label, cost, decoded
