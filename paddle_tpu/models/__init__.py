"""Model zoo built on the layer DSL (reference: v1_api_demo/model_zoo,
benchmark/paddle/image + rnn configs)."""

from paddle_tpu.models import lenet
from paddle_tpu.models import alexnet
from paddle_tpu.models import resnet
from paddle_tpu.models import text_lstm
from paddle_tpu.models import seq2seq
from paddle_tpu.models import deepfm
from paddle_tpu.models import gan
from paddle_tpu.models import vae
from paddle_tpu.models import sequence_tagging
from paddle_tpu.models import srl
from paddle_tpu.models import transformer
from paddle_tpu.models import quick_start
from paddle_tpu.models import traffic_prediction
from paddle_tpu.models import googlenet
from paddle_tpu.models import smallnet
