"""Semantic role labeling — stacked alternating-direction LSTMs + CRF.

Reference: the conll05-driven SRL config family (python/paddle/v2/dataset/
conll05.py provides the 9-slot samples; the classic db_lstm topology:
word/context/predicate/mark embeddings -> mixed projection -> ``depth``
LSTM layers alternating direction -> fc emission -> crf_layer, with a
crf_decoding twin sharing transitions).

TPU-native: each LSTM layer is one big input-projection gemm + a fused
pallas recurrent cell (ops/rnn.py); the CRF forward/viterbi are lax.scans
inside the jitted step.
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr


def build(word_dict_len: int = 4000, label_dict_len: int = 67,
          pred_dict_len: int = 300, word_dim: int = 32, mark_dim: int = 5,
          hidden_dim: int = 128, depth: int = 4):
    """Returns (data_layers, crf_cost, decoded).

    ``data_layers`` order matches the conll05 9-slot sample:
    word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate, mark, label.
    """
    seq = paddle.data_type.integer_value_sequence
    word = layer.data(name="word", type=seq(word_dict_len))
    ctx_names = ["ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2"]
    ctxs = [layer.data(name=n, type=seq(word_dict_len)) for n in ctx_names]
    predicate = layer.data(name="verb", type=seq(pred_dict_len))
    mark = layer.data(name="mark", type=seq(2))
    label = layer.data(name="label", type=seq(label_dict_len))

    # word + 5 context slots SHARE one embedding table (the reference ties
    # them via parameter_name emb)
    shared_emb = ParamAttr(name="word_emb.w")
    embs = [layer.embedding(input=x, size=word_dim, param_attr=shared_emb)
            for x in [word] + ctxs]
    embs.append(layer.embedding(input=predicate, size=word_dim))
    embs.append(layer.embedding(input=mark, size=mark_dim))

    hidden = layer.fc(input=embs, size=hidden_dim, act="tanh",
                      name="srl_hidden0")
    lstm = layer.lstmemory(
        input=layer.fc(input=hidden, size=hidden_dim * 4, name="srl_in0"),
        size=hidden_dim, name="srl_lstm0")
    feat = [hidden, lstm]
    for i in range(1, depth):
        mix = layer.fc(input=feat, size=hidden_dim * 4, name=f"srl_in{i}")
        lstm = layer.lstmemory(input=mix, size=hidden_dim,
                               reverse=(i % 2 == 1), name=f"srl_lstm{i}")
        # thread the PER-LAYER mix forward (db_lstm re-binds input_tmp =
        # [mix_hidden, lstm] each layer): layer i+1 and the emission fc
        # consume layer i's mixed projection, not the depth-0 hidden
        feat = [mix, lstm]

    emission = layer.fc(input=feat, size=label_dict_len, name="srl_emission")
    shared_crf = ParamAttr(name="srl_crf")
    cost = layer.crf(input=emission, label=label, size=label_dict_len,
                     param_attr=shared_crf)
    decoded = layer.crf_decoding(input=emission, size=label_dict_len,
                                 param_attr=shared_crf)
    data_layers = [word] + ctxs + [predicate, mark, label]
    return data_layers, cost, decoded
