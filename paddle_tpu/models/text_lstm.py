"""Stacked-LSTM text classifier (reference: benchmark/paddle/rnn/rnn.py —
the RNN benchmark config: 2xLSTM + fc, BASELINE.md RNN tables)."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.networks import simple_lstm


def build(dict_size: int = 30000, embed_size: int = 128, hidden: int = 512,
          num_classes: int = 2, num_layers: int = 2):
    words = layer.data(name="words",
                       type=paddle.data_type.integer_value_sequence(dict_size))
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))
    net = layer.embedding(input=words, size=embed_size)
    for i in range(num_layers):
        net = simple_lstm(input=net, size=hidden, name=f"lstm{i}")
    pooled = layer.pooling(input=net, pooling_type=paddle.pooling.MaxPooling())
    logits = layer.fc(input=pooled, size=num_classes)
    cost = layer.classification_cost(input=logits, label=label)
    return words, label, logits, cost
