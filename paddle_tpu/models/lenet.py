"""LeNet-5-style MNIST CNN (reference: v1_api_demo/mnist — BASELINE config #1)."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.networks import simple_img_conv_pool


def build(img_size: int = 28, num_classes: int = 10):
    """Returns (images, label, logits, cost)."""
    images = layer.data(name="pixel",
                        type=paddle.data_type.dense_vector(img_size * img_size),
                        height=img_size, width=img_size)
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))
    conv1 = simple_img_conv_pool(input=images, filter_size=5, num_filters=20,
                                 pool_size=2, num_channel=1, act="relu")
    conv2 = simple_img_conv_pool(input=conv1, filter_size=5, num_filters=50,
                                 pool_size=2, act="relu")
    fc1 = layer.fc(input=conv2, size=500, act="relu")
    logits = layer.fc(input=fc1, size=num_classes)
    cost = layer.classification_cost(input=logits, label=label)
    return images, label, logits, cost
