"""Traffic-speed forecasting demo — the multi-task shared-weight model of
v1_api_demo/traffic_prediction/trainer_config.py: one encoded history window
(TERM_NUM readings) feeds FORECASTING_NUM per-horizon heads; every head's
first projection shares ONE parameter (`ParamAttr(name='_link_vec.w')`, the
reference's cross-task weight sharing), then predicts a 4-class speed bucket.

Exercises: parameter aliasing across layers, multi-cost training (the
trainer sums the per-horizon classification costs, MultiNetwork-style).
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr

TERM_NUM = 24
FORECASTING_NUM = 24
NUM_BUCKETS = 4


def build(term_num: int = TERM_NUM, forecasting_num: int = FORECASTING_NUM,
          emb_size: int = 16):
    """Returns (link_encode, labels, scores, costs): per-horizon score
    layers (logits over 4 speed buckets) and their classification costs."""
    link_encode = layer.data(
        name="link_encode", type=paddle.data_type.dense_vector(term_num))
    labels, scores, costs = [], [], []
    shared = ParamAttr(name="_link_vec.w")
    for i in range(forecasting_num):
        link_vec = layer.fc(input=link_encode, size=emb_size,
                            param_attr=shared, name=f"link_vec_{i}")
        score = layer.fc(input=link_vec, size=NUM_BUCKETS,
                         name=f"score_{(i + 1) * 5}min")
        label = layer.data(name=f"label_{(i + 1) * 5}min",
                           type=paddle.data_type.integer_value(NUM_BUCKETS))
        cost = layer.classification_cost(input=score, label=label,
                                         name=f"cost_{(i + 1) * 5}min")
        labels.append(label)
        scores.append(score)
        costs.append(cost)
    return link_encode, labels, scores, costs
