"""DeepFM CTR model — the sparse/embedding-distribution gate model.

Reference analog: the wide-and-deep / CTR workloads the reference's sparse
parameter-server path existed for (large_model_dist_train.md; the v1 ctr
demo family). Built on the v2 layer DSL; the embedding tables are the
parameters one row-shards with parallel/sparse.py at scale.

Architecture (Guo et al. 2017): for F categorical fields over a shared
vocab: first-order weights w[field_id], second-order FM term
0.5*((Σv_f)² − Σv_f²) over k-dim factor embeddings, and a deep MLP over
the concatenated embeddings. Output: logistic CTR probability.
"""

from __future__ import annotations

from typing import List, Tuple

from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr


def build(num_fields: int = 8, vocab_size: int = 1024, factor_dim: int = 8,
          deep_layers: Tuple[int, ...] = (64, 32)):
    """Returns (field_inputs, label, prob, cost).

    Each field is an integer_value input (one id per field per example);
    all fields share one vocab/embedding table pair — the standard packed
    layout for row-sharded tables."""
    from paddle_tpu import data_type

    fields = [layer.data(name=f"field_{i}",
                         type=data_type.integer_value(vocab_size))
              for i in range(num_fields)]
    label = layer.data(name="label", type=data_type.integer_value(2))

    # shared tables: first-order [vocab, 1], factors [vocab, k]
    w_attr = ParamAttr(name="deepfm.w1")
    v_attr = ParamAttr(name="deepfm.v")
    firsts = [layer.embedding(f, size=1, param_attr=w_attr) for f in fields]
    embeds = [layer.embedding(f, size=factor_dim, param_attr=v_attr)
              for f in fields]

    first_order = layer.addto(firsts, bias_attr=True)

    # FM second order: 0.5 * ((Σv)^2 - Σ v^2) summed over k
    sum_v = layer.addto(embeds)
    sum_sq = layer.dotmul(sum_v, sum_v)
    sq_sum = layer.addto([layer.dotmul(e, e) for e in embeds])
    from paddle_tpu.initializer import Constant
    second = layer.mixed(
        input=layer.identity_projection(sum_sq + layer.slope_intercept(
            sq_sum, slope=-1.0)), size=factor_dim)
    second_order = layer.fc(second, size=1, bias_attr=False,
                            param_attr=ParamAttr(initializer=Constant(0.5)))

    deep = layer.concat(embeds)
    for width in deep_layers:
        deep = layer.fc(deep, size=width, act="relu")
    deep_out = layer.fc(deep, size=1, bias_attr=False)

    logit = layer.addto([first_order, second_order, deep_out])
    prob = layer.mixed(input=layer.identity_projection(logit), size=1,
                       act="sigmoid")
    # the BCE cost takes LOGITS (sigmoid applied inside, stable form)
    cost = layer.multi_binary_label_cross_entropy_cost(input=logit,
                                                       label=label)
    return fields, label, prob, cost
