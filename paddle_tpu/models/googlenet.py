"""GoogLeNet / Inception-v1 (reference: benchmark/paddle/image/googlenet.py —
a headline row of the reference's benchmark table, BASELINE.md: 613 ms/batch
bs=64 on K40m).

Four-tower inception modules built on img_conv + channel concat (the
ConcatenateLayer path); main classifier head only — the two auxiliary
heads exist for vanishing-gradient-era training and are omitted as they
don't affect the benchmarked forward/backward shape meaningfully.
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer


def _conv(input, filters, size, stride=1, padding=None):
    padding = padding if padding is not None else (size - 1) // 2
    return layer.img_conv(input=input, filter_size=size, num_filters=filters,
                          stride=stride, padding=padding, act="relu")


def inception(input, c1, c3r, c3, c5r, c5, pp):
    """One inception module: 1x1 / 1x1->3x3 / 1x1->5x5 / pool->1x1 towers."""
    t1 = _conv(input, c1, 1)
    t3 = _conv(_conv(input, c3r, 1), c3, 3)
    t5 = _conv(_conv(input, c5r, 1), c5, 5)
    tp = _conv(layer.img_pool(input=input, pool_size=3, stride=1, padding=1),
               pp, 1)
    return layer.concat(input=[t1, t3, t5, tp])


_CFG = [  # (c1, c3r, c3, c5r, c5, pool_proj), with 'M' = maxpool between
    (64, 96, 128, 16, 32, 32),      # 3a
    (128, 128, 192, 32, 96, 64),    # 3b
    "M",
    (192, 96, 208, 16, 48, 64),     # 4a
    (160, 112, 224, 24, 64, 64),    # 4b
    (128, 128, 256, 24, 64, 64),    # 4c
    (112, 144, 288, 32, 64, 64),    # 4d
    (256, 160, 320, 32, 128, 128),  # 4e
    "M",
    (256, 160, 320, 32, 128, 128),  # 5a
    (384, 192, 384, 48, 128, 128),  # 5b
]


def build(img_size: int = 224, num_classes: int = 1000):
    """Returns (images, label, logits, cost)."""
    images = layer.data(
        name="image",
        type=paddle.data_type.dense_vector(3 * img_size * img_size),
        height=img_size, width=img_size)
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))
    net = _conv(images, 64, 7, stride=2, padding=3)
    net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1)
    net = _conv(net, 64, 1)
    net = _conv(net, 192, 3)
    net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1)
    for cfg in _CFG:
        if cfg == "M":
            net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1)
        else:
            net = inception(net, *cfg)
    h, w, c = net.img_shape
    net = layer.img_pool(input=net, pool_size=h, stride=h,
                         pool_type=paddle.pooling.AvgPooling())
    net = layer.dropout(net, 0.4)
    logits = layer.fc(input=net, size=num_classes)
    cost = layer.classification_cost(input=logits, label=label)
    return images, label, logits, cost
