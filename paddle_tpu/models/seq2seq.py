"""Attention seq2seq NMT (reference: the machine-translation demo config —
demo/seqToseq analog built on recurrent_group + simple_attention;
BASELINE config #3).

``build_train`` and ``build_generator`` construct separate topologies whose
parameter keys coincide (explicit layer names), so Parameters trained with
the first run generation with the second — the reference's
config-with-is_generating pattern.
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer, networks
from paddle_tpu.generation import GeneratedInput, beam_search


def _encoder(src_dict_size: int, embed_size: int, hidden: int):
    src = layer.data(name="source_words",
                     type=paddle.data_type.integer_value_sequence(src_dict_size))
    emb = layer.embedding(input=src, size=embed_size, name="src_emb",
                          param_attr=paddle.attr.ParamAttr(name="_src_emb"))
    fwd = networks.simple_gru(input=emb, size=hidden, name="enc_fwd")
    bwd = networks.simple_gru(input=emb, size=hidden, reverse=True, name="enc_bwd")
    encoded = layer.concat(input=[fwd, bwd], name="encoded")
    enc_proj = layer.fc(input=encoded, size=hidden, bias_attr=False,
                        name="enc_proj")
    boot = layer.fc(input=layer.first_seq(input=bwd, name="bwd_first"),
                    size=hidden, act="tanh", name="decoder_boot")
    return src, encoded, enc_proj, boot


def _decoder_step(hidden: int, trg_dict_size: int, boot):
    """Returns step(token_emb, enc_seq, enc_proj) with stable layer names."""

    def step(token_emb, enc_seq, enc_proj):
        dec_mem = layer.memory(name="gru_out", size=hidden, boot_layer=boot)
        context = networks.simple_attention(
            encoded_sequence=enc_seq, encoded_proj=enc_proj,
            decoder_state=dec_mem, name="att")
        x = layer.fc(input=[context, token_emb], size=hidden * 3,
                     bias_attr=True, name="dec_in")
        gru = layer.gru_step(input=x, output_mem=dec_mem, size=hidden,
                             name="gru_out")
        probs = layer.fc(input=gru, size=trg_dict_size, act="softmax",
                         name="dec_out")
        return probs

    return step


def build_train(src_dict_size: int = 1000, trg_dict_size: int = 1000,
                embed_size: int = 64, hidden: int = 64):
    """Returns (cost, probs_seq). Feeds: source_words, target_words (with
    <s> prefix), target_next (shifted labels)."""
    src, encoded, enc_proj, boot = _encoder(src_dict_size, embed_size, hidden)
    trg = layer.data(name="target_words",
                     type=paddle.data_type.integer_value_sequence(trg_dict_size))
    trg_next = layer.data(name="target_next",
                          type=paddle.data_type.integer_value_sequence(trg_dict_size))
    trg_emb = layer.embedding(input=trg, size=embed_size, name="trg_emb",
                              param_attr=paddle.attr.ParamAttr(name="_trg_emb"))
    step = _decoder_step(hidden, trg_dict_size, boot)
    probs_seq = layer.recurrent_group(
        step=step,
        input=[trg_emb, layer.StaticInput(encoded), layer.StaticInput(enc_proj)],
        name="decoder_group")
    cost = layer.cross_entropy_cost(input=probs_seq, label=trg_next,
                                    name="nmt_cost")
    return cost, probs_seq


def build_generator(src_dict_size: int = 1000, trg_dict_size: int = 1000,
                    embed_size: int = 64, hidden: int = 64,
                    bos_id: int = 0, eos_id: int = 1, beam_size: int = 4,
                    max_length: int = 25):
    """Returns the beam-search node; evaluate with paddle.infer."""
    src, encoded, enc_proj, boot = _encoder(src_dict_size, embed_size, hidden)
    step = _decoder_step(hidden, trg_dict_size, boot)
    beam = beam_search(
        step=step,
        input=[GeneratedInput(size=trg_dict_size, embedding_name="_trg_emb",
                              embedding_size=embed_size),
               layer.StaticInput(encoded), layer.StaticInput(enc_proj)],
        bos_id=bos_id, eos_id=eos_id, beam_size=beam_size,
        max_length=max_length, name="nmt_beam")
    return beam
