"""Decoder-only transformer language model (GPT-2 style, pre-LN).

The transformer-era flagship of the model zoo: the reference predates
transformers (its attention surface is simple_attention /
dot_product_attention, python/paddle/trainer_config_helpers/networks.py:1304,
1402), so this is the new-build extension that exercises the same machinery
at modern scale — packed variable-length sequences (SequenceBatch, the
Argument.sequenceStartPositions analog), the pallas flash-attention kernel
(ops/attention.py) via layer.multi_head_attention, layer_norm, and per-token
classification cost.

On TPU this family is the high-MFU headline: all FLOPs live in large bf16
matmuls (QKV/out projections, the 4x FFN, the vocab head) that tile straight
onto the MXU, with flash attention keeping the S^2 term out of HBM.
"""

from __future__ import annotations

import functools

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer


def block(x, *, n_heads: int, ffn_mult: int = 4, name: str,
          dropout: float = 0.0, causal: bool = True, memory=None,
          moe_experts: int = 0, moe_top_k: int = 1):
    """One pre-LN transformer block: x + drop(MHA(LN(x))) [+ x +
    cross-MHA(LN(x), memory) when ``memory`` is given]; x + drop(FFN(LN(x))).

    causal=True/memory=None is the decoder-only LM block; causal=False is
    the encoder block; memory= adds the cross-attention sub-block of the
    encoder-decoder translation model (build_seq2seq)."""
    idx = 1
    a = layer.layer_norm(x, name=f"{name}_ln{idx}")
    a = layer.multi_head_attention(a, num_heads=n_heads, causal=causal,
                                   name=f"{name}_attn")
    if dropout > 0.0:
        a = layer.dropout(a, dropout, name=f"{name}_attn_drop")
    x = layer.addto(input=[x, a], name=f"{name}_res{idx}")
    if memory is not None:
        idx += 1
        c = layer.layer_norm(x, name=f"{name}_ln{idx}")
        c = layer.multi_head_attention(c, key=memory, num_heads=n_heads,
                                       causal=False, name=f"{name}_cross")
        if dropout > 0.0:
            c = layer.dropout(c, dropout, name=f"{name}_cross_drop")
        x = layer.addto(input=[x, c], name=f"{name}_res{idx}")
    idx += 1
    f = layer.layer_norm(x, name=f"{name}_ln{idx}")
    aux = None
    if moe_experts > 0:
        f, aux = layer.moe_ffn(f, num_experts=moe_experts,
                               expert_hidden=x.size * ffn_mult,
                               top_k=moe_top_k, name=f"{name}_moe")
    else:
        f = layer.fc(input=f, size=x.size * ffn_mult, act="gelu",
                     name=f"{name}_ffn_up")
        f = layer.fc(input=f, size=x.size, name=f"{name}_ffn_down")
    if dropout > 0.0:
        f = layer.dropout(f, dropout, name=f"{name}_ffn_drop")
    out = layer.addto(input=[x, f], name=f"{name}_res{idx}")
    return (out, aux) if moe_experts > 0 else out


def build(vocab_size: int = 32768, d_model: int = 512, n_layers: int = 6,
          n_heads: int = 8, max_len: int = 1024, ffn_mult: int = 4,
          dropout: float = 0.0, fused_head: bool = False,
          moe_experts: int = 0, moe_top_k: int = 1, remat: bool = False):
    """Returns (tokens, positions, target, logits, cost).

    Feeds: ``tokens`` / ``target`` are integer sequences (next-token
    targets), ``pos`` is the 0-based position within each sequence
    (fed as data so packed buffers need no in-graph segment arithmetic).

    ``fused_head=True`` swaps the fc(vocab) -> classification_cost pair
    for layer.lm_head_cost (blockwise online-logsumexp; the [tokens,
    vocab] logits never reach HBM — ~0.5-1 GB/step at bench shapes).
    Training-equivalent to f32 rounding (test_network_compare pins it);
    the returned ``logits`` node still exists for decoding and shares
    the head weight by name.

    ``remat=True`` wraps each block in a topology.remat_scope: backward
    recomputes per-block activations from the block's input instead of
    keeping them in HBM — the standard TPU lever that buys batch/sequence
    with ~1 extra forward of FLOPs. Training-equivalent to remat=False up
    to f32 rounding (the recomputed forward may fuse/round differently;
    dropout masks are identical by construction).
    """
    tokens = layer.data(name="tokens",
                        type=paddle.data_type.integer_value_sequence(vocab_size))
    pos = layer.data(name="pos",
                     type=paddle.data_type.integer_value_sequence(max_len))
    target = layer.data(name="target",
                        type=paddle.data_type.integer_value_sequence(vocab_size))

    tok_emb = layer.embedding(input=tokens, size=d_model, name="tok_embed")
    pos_emb = layer.embedding(input=pos, size=d_model, name="pos_embed")
    x = layer.addto(input=[tok_emb, pos_emb], name="embed_sum")
    aux_nodes = []
    import contextlib

    from paddle_tpu import topology as _topo

    for i in range(n_layers):
        scope = (_topo.remat_scope(f"blk{i}") if remat
                 else contextlib.nullcontext())
        with scope:
            if moe_experts > 0:
                x, aux = block(x, n_heads=n_heads, ffn_mult=ffn_mult,
                               name=f"blk{i}", dropout=dropout,
                               moe_experts=moe_experts,
                               moe_top_k=moe_top_k)
                aux_nodes.append(aux)
            else:
                x = block(x, n_heads=n_heads, ffn_mult=ffn_mult,
                          name=f"blk{i}", dropout=dropout)
    x = layer.layer_norm(x, name="final_ln")
    logits = layer.fc(input=x, size=vocab_size, name="lm_head")
    if fused_head:
        # share the fc's default-named weights so decoding (which reads
        # lm_head.w0/b) and checkpoints are identical either way
        from paddle_tpu.attr import ParamAttr
        cost = layer.lm_head_cost(x, target, vocab_size=vocab_size,
                                  param_attr=ParamAttr(name="lm_head.w0"),
                                  bias_attr=ParamAttr(name="lm_head.b"),
                                  name="lm_head_fused")
    else:
        cost = layer.classification_cost(input=logits, label=target)
    if moe_experts > 0:
        # multi-cost training: xent + per-block load-balance aux losses
        # (pass the LIST to SGD(cost=...), the MultiNetwork path)
        cost = [cost] + aux_nodes
    return tokens, pos, target, logits, cost


def build_seq2seq(src_vocab: int = 30000, trg_vocab: int = 30000,
                  d_model: int = 256, n_layers: int = 3, n_heads: int = 4,
                  max_len: int = 256, ffn_mult: int = 4):
    """Encoder-decoder transformer for translation — the modern successor
    of models/seq2seq.py's RNN+attention (reference: demo/seqToseq +
    networks.py simple_attention). Cross-attention rides the same packed
    flash kernel (layer.multi_head_attention with key=encoder memory).

    Returns (src, src_pos, trg, trg_pos, label, logits, cost). Feeds:
    ``trg`` is the shifted-right target (<s> prefix convention is the
    caller's), ``label`` the gold next tokens.
    """
    src = layer.data(name="src",
                     type=paddle.data_type.integer_value_sequence(src_vocab))
    src_pos = layer.data(name="src_pos",
                         type=paddle.data_type.integer_value_sequence(max_len))
    trg = layer.data(name="trg",
                     type=paddle.data_type.integer_value_sequence(trg_vocab))
    trg_pos = layer.data(name="trg_pos",
                         type=paddle.data_type.integer_value_sequence(max_len))
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value_sequence(trg_vocab))

    # encoder: bidirectional (non-causal) self-attention blocks
    enc = layer.addto(input=[
        layer.embedding(input=src, size=d_model, name="src_embed"),
        layer.embedding(input=src_pos, size=d_model, name="src_pos_embed"),
    ], name="enc_embed_sum")
    for i in range(n_layers):
        enc = block(enc, n_heads=n_heads, ffn_mult=ffn_mult,
                    name=f"enc{i}", causal=False)
    memory = layer.layer_norm(enc, name="enc_final_ln")

    # decoder: causal self-attention + cross-attention over the memory
    dec = layer.addto(input=[
        layer.embedding(input=trg, size=d_model, name="trg_embed"),
        layer.embedding(input=trg_pos, size=d_model, name="trg_pos_embed"),
    ], name="dec_embed_sum")
    for i in range(n_layers):
        dec = block(dec, n_heads=n_heads, ffn_mult=ffn_mult,
                    name=f"dec{i}", causal=True, memory=memory)
    dec = layer.layer_norm(dec, name="dec_final_ln")
    logits = layer.fc(input=dec, size=trg_vocab, name="trg_head")
    cost = layer.classification_cost(input=logits, label=label)
    return src, src_pos, trg, trg_pos, label, logits, cost


# ---------------------------------------------------------------------------
# autoregressive decoding with a KV cache — the transformer-era analog of the
# RNN beam-search generation path (generation.py / SequenceGenerator.cpp):
# one jitted lax.scan over decode steps, dense [max_len] K/V caches per
# layer, greedy or temperature sampling. Pure function over the SAME
# parameter dict the trainer produces (names from build() above).
# ---------------------------------------------------------------------------


def _ln(x, g, b):
    # the training graph's normalization (f32 stats, emit in x.dtype)
    from paddle_tpu.ops.norm import layer_norm

    return layer_norm(x, g, b)


def _step_token(p, x_t, caches, t, *, n_layers, n_heads, max_len):
    """One decode step for a [d] embedding; returns (hidden, new caches).

    caches: list of (k, v) with k/v [max_len, H, Dh]; positions >= t are
    zeros and masked out of the attention softmax.
    """
    import jax
    import jax.numpy as jnp

    d = x_t.shape[-1]
    head_dim = d // n_heads
    new_caches = []
    for i in range(n_layers):
        k_cache, v_cache = caches[i]
        a_in = _ln(x_t, p[f"blk{i}_ln1.gamma"], p[f"blk{i}_ln1.beta"])
        q = (a_in @ p[f"blk{i}_attn.wq"]).reshape(n_heads, head_dim)
        k = (a_in @ p[f"blk{i}_attn.wk"]).reshape(n_heads, head_dim)
        v = (a_in @ p[f"blk{i}_attn.wv"]).reshape(n_heads, head_dim)
        k_cache = jax.lax.dynamic_update_index_in_dim(k_cache, k, t, 0)
        v_cache = jax.lax.dynamic_update_index_in_dim(v_cache, v, t, 0)
        # attend over positions [0, t]
        scores = jnp.einsum("hd,shd->hs", q.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) / jnp.sqrt(
                                jnp.float32(head_dim))
        mask = jnp.arange(max_len) <= t
        scores = jnp.where(mask[None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("hs,shd->hd", probs,
                         v_cache.astype(jnp.float32)).reshape(d)
        attn = ctx.astype(x_t.dtype) @ p[f"blk{i}_attn.wo"]
        x_t = x_t + attn
        f_in = _ln(x_t, p[f"blk{i}_ln2.gamma"], p[f"blk{i}_ln2.beta"])
        h = jax.nn.gelu(f_in @ p[f"blk{i}_ffn_up.w0"] + p[f"blk{i}_ffn_up.b"])
        h = h @ p[f"blk{i}_ffn_down.w0"] + p[f"blk{i}_ffn_down.b"]
        x_t = x_t + h
        new_caches.append((k_cache, v_cache))
    return x_t, new_caches


def generate(params, prompt_ids, max_new_tokens: int, *, n_layers: int,
             n_heads: int, max_len: int = 1024, temperature: float = 0.0,
             rng=None, eos_id: int = -1):
    """Greedy/temperature decode continuing ``prompt_ids``.

    params: the trainer's parameter dict (Parameters.as_dict() or a plain
    {name: array}). Returns an int32 array of generated token ids
    (length max_new_tokens; positions after an ``eos_id`` hit repeat eos).
    """
    import jax

    p, prompt, n_prompt, total = _prep_decode(
        params, prompt_ids, max_new_tokens, max_len, "generate")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    run = _decode_fn(n_layers, n_heads, max_len, n_prompt, total,
                     float(temperature), int(eos_id))
    return np.asarray(run(p, prompt, rng))


def _prep_decode(params, prompt_ids, max_new_tokens, max_len, fn_name):
    """Shared argument conversion/validation for the decode entry points."""
    import jax.numpy as jnp

    p = {k: jnp.asarray(v) for k, v in dict(params).items()}
    prompt = jnp.asarray(np.asarray(prompt_ids), jnp.int32)
    n_prompt = int(prompt.shape[0])
    if n_prompt < 1:
        raise ValueError(f"{fn_name}() needs a non-empty prompt")
    total = n_prompt + int(max_new_tokens)
    if total > max_len:
        raise ValueError(f"prompt+new = {total} exceeds max_len {max_len}")
    return p, prompt, n_prompt, total


def _flatten_caches(cs):
    return tuple(x for kv in cs for x in kv)


def _unflatten_caches(flat):
    return [(flat[2 * i], flat[2 * i + 1]) for i in range(len(flat) // 2)]


def block_apply(p_block, x, *, n_heads: int):
    """Functional full-sequence decoder block: x [S, d] -> [S, d].

    ``p_block`` uses the block-local names (ln1.gamma, attn.wq, ffn_up.w0,
    ...) — one stage's slice of the training parameters. Identical math to
    the layer-DSL block() above (causal self-attention, pre-LN, gelu FFN),
    so a stack of these IS the trained model body; being a pure
    (params, x) -> y function of fixed shape, it is directly a
    parallel.pipeline stage_fn — pipeline parallelism over the flagship
    architecture (test_pipeline_transformer pins it to the sequential
    oracle)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import mha_reference

    s, d = x.shape
    n_hd = d // n_heads
    a_in = _ln(x, p_block["ln1.gamma"], p_block["ln1.beta"])
    q = (a_in @ p_block["attn.wq"]).reshape(1, s, n_heads, n_hd)
    k = (a_in @ p_block["attn.wk"]).reshape(1, s, n_heads, n_hd)
    v = (a_in @ p_block["attn.wv"]).reshape(1, s, n_heads, n_hd)
    out = mha_reference(q, k, v, causal=True)[0].reshape(s, d)
    x = x + out.astype(x.dtype) @ p_block["attn.wo"]
    f_in = _ln(x, p_block["ln2.gamma"], p_block["ln2.beta"])
    h = jax.nn.gelu(f_in @ p_block["ffn_up.w0"] + p_block["ffn_up.b"])
    x = x + (h @ p_block["ffn_down.w0"] + p_block["ffn_down.b"])
    return x


def stage_params(params, n_layers: int):
    """Split a trained parameter dict into per-block param dicts with the
    block-local names block_apply expects (for parallel.pipeline
    stack_stage_params)."""
    items = list(dict(params).items())
    out = []
    for i in range(n_layers):
        prefix = f"blk{i}_"
        out.append({k[len(prefix):]: v for k, v in items
                    if k.startswith(prefix)})
    return out


def beam_generate(params, prompt_ids, max_new_tokens: int, *, n_layers: int,
                  n_heads: int, beam_size: int = 4, max_len: int = 1024,
                  eos_id: int = -1, length_penalty: float = 0.0,
                  candidate_adjust=None, path_filter=None,
                  stop_condition=None):
    """Beam-search decode (the transformer analog of generation.py's in-jit
    RNN beam loop / RecurrentGradientMachine::beamSearch).

    Returns (tokens [max_new_tokens] int32, score float) of the best beam.
    Scores are sum of token log-probs, normalized by length**length_penalty
    at the final selection (0 = pure sum, 1 = mean log-prob).

    The user control hooks mirror generation.beam_search (the
    RecurrentGradientMachine.h:73-148 callbacks), traced into the scan:
    ``candidate_adjust(logp [k,V], beam)`` transforms live-beam
    continuation log-probs (beam is a generation.BeamState with leading
    beam axis, batch==1 semantics); ``path_filter(beam) -> keep [k]``
    drops selected beams (score -1e30); ``stop_condition(beam) -> bool``
    marks every beam done — remaining steps extend with EOS at zero cost,
    which is exactly an early stop under the length-normalized selection.
    """
    p, prompt, n_prompt, total = _prep_decode(
        params, prompt_ids, max_new_tokens, max_len, "beam_generate")
    if max_new_tokens == 0:
        return np.zeros((0,), np.int32), 0.0
    run = _beam_fn(n_layers, n_heads, max_len, n_prompt, total,
                   int(beam_size), int(eos_id), float(length_penalty),
                   candidate_adjust, path_filter, stop_condition)
    toks, score = run(p, prompt)
    return np.asarray(toks), float(score)


def beam_generate_batch(params, prompts, max_new_tokens: int, *,
                        n_layers: int, n_heads: int, beam_size: int = 4,
                        max_len: int = 1024, eos_id: int = -1,
                        length_penalty: float = 0.0,
                        candidate_adjust=None, path_filter=None,
                        stop_condition=None):
    """Beam-decode a BATCH of equal-length prompts in one compiled call
    (vmap over the single-prompt beam scan — weights broadcast, caches and
    beams batch). Returns (tokens [N, max_new] int32, scores [N]).

    Prompts must share a length (bucket them host-side; the compiled
    program is shaped by (n_prompt, max_new))."""
    import jax

    prompts = [list(pr) for pr in prompts]
    n_prompt = len(prompts[0])
    if not all(len(pr) == n_prompt for pr in prompts):
        raise ValueError("beam_generate_batch needs equal-length prompts "
                         "(bucket them host-side)")
    p, _, n_prompt, total = _prep_decode(
        params, prompts[0], max_new_tokens, max_len, "beam_generate")
    if max_new_tokens == 0:
        return (np.zeros((len(prompts), 0), np.int32),
                np.zeros((len(prompts),), np.float32))
    run = _beam_fn(n_layers, n_heads, max_len, n_prompt, total,
                   int(beam_size), int(eos_id), float(length_penalty),
                   candidate_adjust, path_filter, stop_condition)
    import jax.numpy as jnp
    batch = jnp.asarray(np.asarray(prompts, np.int32))
    toks, scores = jax.jit(jax.vmap(run, in_axes=(None, 0)))(p, batch)
    return np.asarray(toks), np.asarray(scores)


def _beam_fn(n_layers, n_heads, max_len, n_prompt, total, beam_size, eos_id,
             length_penalty, candidate_adjust=None, path_filter=None,
             stop_condition=None):
    """Jitted beam-search scan for one static config (weights are args).

    Hook-free configs are cached (repeat generate calls skip retracing).
    Configs WITH hooks bypass the cache: callers naturally pass fresh
    lambdas/closures, which would never hit the cache anyway and would pin
    up to 32 closures (plus their captured arrays) alive in it."""
    if candidate_adjust is None and path_filter is None and \
            stop_condition is None:
        return _beam_fn_cached(n_layers, n_heads, max_len, n_prompt, total,
                               beam_size, eos_id, length_penalty)
    return _beam_fn_build(n_layers, n_heads, max_len, n_prompt, total,
                          beam_size, eos_id, length_penalty,
                          candidate_adjust, path_filter, stop_condition)


@functools.lru_cache(maxsize=32)
def _beam_fn_cached(n_layers, n_heads, max_len, n_prompt, total, beam_size,
                    eos_id, length_penalty):
    return _beam_fn_build(n_layers, n_heads, max_len, n_prompt, total,
                          beam_size, eos_id, length_penalty, None, None, None)


def _beam_fn_build(n_layers, n_heads, max_len, n_prompt, total, beam_size,
                   eos_id, length_penalty, candidate_adjust, path_filter,
                   stop_condition):
    import jax
    import jax.numpy as jnp

    NEG = -1e30

    @jax.jit
    def run(p, prompt):
        d = p["tok_embed.w"].shape[1]
        head_dim = d // n_heads
        k = beam_size
        max_new = total - n_prompt

        def step_one(tok, caches, t):
            x_t = p["tok_embed.w"][tok] + p["pos_embed.w"][t]
            h, cs = _step_token(p, x_t, caches, t, n_layers=n_layers,
                                n_heads=n_heads, max_len=max_len)
            h = _ln(h, p["final_ln.gamma"], p["final_ln.beta"])
            logits = (h @ p["lm_head.w0"] + p["lm_head.b"]).astype(jnp.float32)
            return jax.nn.log_softmax(logits), cs

        # ---- prefill: ONE beam consumes the prompt (no k-times waste) ---
        pre_caches = [(jnp.zeros((max_len, n_heads, head_dim), jnp.float32),
                       jnp.zeros((max_len, n_heads, head_dim), jnp.float32))
                      for _ in range(n_layers)]

        def prefill_fn(flat, t):
            _, cs = step_one(prompt[t], _unflatten_caches(flat), t)
            return _flatten_caches(cs), None

        flat, _ = jax.lax.scan(prefill_fn, _flatten_caches(pre_caches),
                               jnp.arange(n_prompt - 1))
        # broadcast the prefilled caches to k beams
        flat = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), flat)

        batched = jax.vmap(step_one,
                           in_axes=(0, [(0, 0)] * n_layers, None),
                           out_axes=(0, [(0, 0)] * n_layers))

        from paddle_tpu.generation import BeamState

        def _beam_state(t_rel, toks_, scores_, done_, hist_):
            lengths = jnp.sum(hist_ != eos_id, axis=1).astype(jnp.int32)
            return BeamState(t_rel, toks_, scores_, done_, lengths)

        def scan_fn(carry, t):
            toks, flat, scores, done, hist = carry
            logp, cs = batched(toks, _unflatten_caches(flat), t)  # [k,V]
            vocab = logp.shape[-1]
            t_rel = t - (n_prompt - 1)
            if candidate_adjust is not None:
                logp = candidate_adjust(
                    logp, _beam_state(t_rel, toks, scores, done, hist))
            # done beams may only extend with eos at no cost; live beams
            # add token log-probs (AFTER the adjust: hooks cannot unfreeze)
            eos_row = jnp.full((vocab,), NEG).at[eos_id].set(0.0)
            logp = jnp.where(done[:, None], eos_row[None, :], logp)
            cand = scores[:, None] + logp                      # [k,V]

            flat_cand = cand.reshape(-1)
            top_scores, top_idx = jax.lax.top_k(flat_cand, k)
            parent = top_idx // vocab
            tok_next = (top_idx % vocab).astype(jnp.int32)

            cs_sel = jax.tree.map(lambda x: x[parent], _flatten_caches(cs))
            new_done = done[parent] | (tok_next == eos_id)
            hist = hist[parent]
            hist = jax.lax.dynamic_update_index_in_dim(
                hist, tok_next, t - (n_prompt - 1), 1)
            if path_filter is not None or stop_condition is not None:
                beam_now = _beam_state(t_rel, tok_next, top_scores, new_done,
                                       hist)
                if path_filter is not None:
                    top_scores = jnp.where(path_filter(beam_now), top_scores,
                                           NEG)
                if stop_condition is not None:
                    new_done = new_done | jnp.broadcast_to(
                        jnp.asarray(stop_condition(beam_now)), (k,))
            return ((tok_next, cs_sel, top_scores, new_done, hist),
                    None)

        hist0 = jnp.zeros((k, max_new), jnp.int32)
        toks0 = jnp.broadcast_to(prompt[n_prompt - 1], (k,)).astype(jnp.int32)
        # only beam 0 is live at entry (all beams share the prompt prefix)
        scores0 = jnp.where(jnp.arange(k) == 0, 0.0, NEG)
        carry = (toks0, flat, scores0, jnp.zeros((k,), jnp.bool_), hist0)
        (toks, _, scores, done, hist), _ = jax.lax.scan(
            scan_fn, carry, jnp.arange(n_prompt - 1, total - 1))
        # length-normalized final selection (done beams ended at eos)
        gen_len = jnp.where(done,
                            jnp.argmax(hist == eos_id, axis=1) + 1, max_new)
        norm = jnp.power(jnp.maximum(gen_len, 1).astype(jnp.float32),
                         length_penalty)
        best = jnp.argmax(scores / norm)
        return hist[best], scores[best]

    return run


@functools.lru_cache(maxsize=32)
def _decode_fn(n_layers, n_heads, max_len, n_prompt, total, temperature,
               eos_id):
    """Build (and cache) the jitted decode scan for one static config.

    Params/prompt/rng are ARGUMENTS of the jitted function, so repeated
    generate() calls with the same shapes hit both this cache and jax's
    compile cache instead of re-tracing with the weights baked in as
    constants."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(p, prompt, rng):
        d = p["tok_embed.w"].shape[1]
        head_dim = d // n_heads
        caches = [(jnp.zeros((max_len, n_heads, head_dim), jnp.float32),
                   jnp.zeros((max_len, n_heads, head_dim), jnp.float32))
                  for _ in range(n_layers)]
        flatten, unflatten = _flatten_caches, _unflatten_caches

        def scan_fn(carry, t):
            tok, flat, rng, done = carry
            x_t = p["tok_embed.w"][tok] + p["pos_embed.w"][t]
            h, cs = _step_token(p, x_t, unflatten(flat), t,
                                n_layers=n_layers, n_heads=n_heads,
                                max_len=max_len)
            h = _ln(h, p["final_ln.gamma"], p["final_ln.beta"])
            logits = (h @ p["lm_head.w0"] + p["lm_head.b"]).astype(jnp.float32)
            rng, sub = jax.random.split(rng)
            if temperature > 0.0:
                nxt = jax.random.categorical(sub, logits / temperature)
            else:
                nxt = jnp.argmax(logits)
            nxt = nxt.astype(jnp.int32)
            # inside the prompt, force-feed the given token (teacher forcing)
            in_prompt = t + 1 < n_prompt
            forced = jnp.where(in_prompt, prompt[jnp.minimum(t + 1,
                                                             n_prompt - 1)],
                               nxt)
            forced = jnp.where(done, eos_id, forced)
            done = done | (~in_prompt & (forced == eos_id))
            return (forced, flatten(cs), rng, done), forced

        init = (prompt[0], flatten(caches), rng, jnp.bool_(False))
        _, toks = jax.lax.scan(scan_fn, init, jnp.arange(total - 1))
        # toks[t] is the token at position t+1; generation starts after
        # the prompt
        return toks[n_prompt - 1:]

    return run
