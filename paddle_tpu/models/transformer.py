"""Decoder-only transformer language model (GPT-2 style, pre-LN).

The transformer-era flagship of the model zoo: the reference predates
transformers (its attention surface is simple_attention /
dot_product_attention, python/paddle/trainer_config_helpers/networks.py:1304,
1402), so this is the new-build extension that exercises the same machinery
at modern scale — packed variable-length sequences (SequenceBatch, the
Argument.sequenceStartPositions analog), the pallas flash-attention kernel
(ops/attention.py) via layer.multi_head_attention, layer_norm, and per-token
classification cost.

On TPU this family is the high-MFU headline: all FLOPs live in large bf16
matmuls (QKV/out projections, the 4x FFN, the vocab head) that tile straight
onto the MXU, with flash attention keeping the S^2 term out of HBM.
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer


def block(x, *, n_heads: int, ffn_mult: int = 4, name: str):
    """One pre-LN decoder block: x + MHA(LN(x)); x + FFN(LN(x))."""
    a = layer.layer_norm(x, name=f"{name}_ln1")
    a = layer.multi_head_attention(a, num_heads=n_heads, causal=True,
                                   name=f"{name}_attn")
    x = layer.addto(input=[x, a], name=f"{name}_res1")
    f = layer.layer_norm(x, name=f"{name}_ln2")
    f = layer.fc(input=f, size=x.size * ffn_mult, act="gelu",
                 name=f"{name}_ffn_up")
    f = layer.fc(input=f, size=x.size, name=f"{name}_ffn_down")
    return layer.addto(input=[x, f], name=f"{name}_res2")


def build(vocab_size: int = 32768, d_model: int = 512, n_layers: int = 6,
          n_heads: int = 8, max_len: int = 1024, ffn_mult: int = 4):
    """Returns (tokens, positions, target, logits, cost).

    Feeds: ``tokens`` / ``target`` are integer sequences (next-token
    targets), ``pos`` is the 0-based position within each sequence
    (fed as data so packed buffers need no in-graph segment arithmetic).
    """
    tokens = layer.data(name="tokens",
                        type=paddle.data_type.integer_value_sequence(vocab_size))
    pos = layer.data(name="pos",
                     type=paddle.data_type.integer_value_sequence(max_len))
    target = layer.data(name="target",
                        type=paddle.data_type.integer_value_sequence(vocab_size))

    tok_emb = layer.embedding(input=tokens, size=d_model, name="tok_embed")
    pos_emb = layer.embedding(input=pos, size=d_model, name="pos_embed")
    x = layer.addto(input=[tok_emb, pos_emb], name="embed_sum")
    for i in range(n_layers):
        x = block(x, n_heads=n_heads, ffn_mult=ffn_mult, name=f"blk{i}")
    x = layer.layer_norm(x, name="final_ln")
    logits = layer.fc(input=x, size=vocab_size, name="lm_head")
    cost = layer.classification_cost(input=logits, label=target)
    return tokens, pos, target, logits, cost
