"""ResNet (reference: v1_api_demo/model_zoo/resnet/resnet.py and
benchmark/paddle/image — the north-star config, BASELINE.json).

Bottleneck-v1 ResNet-50 by default; depth 18/34 use basic blocks."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer


def _conv_bn(input, num_filters, filter_size, stride=1, padding=None, act="relu",
             name=None):
    padding = padding if padding is not None else (filter_size - 1) // 2
    conv = layer.img_conv(input=input, filter_size=filter_size,
                          num_filters=num_filters, stride=stride,
                          padding=padding, act=None, bias_attr=False, name=name)
    return layer.batch_norm(input=conv, act=act)


def _bottleneck(input, planes, stride=1, downsample=False, name=None):
    out = _conv_bn(input, planes, 1, stride=1)
    out = _conv_bn(out, planes, 3, stride=stride)
    out = _conv_bn(out, planes * 4, 1, act=None)
    if downsample:
        short = _conv_bn(input, planes * 4, 1, stride=stride, act=None)
    else:
        short = input
    return layer.addto(input=[out, short], act="relu")


def _basic(input, planes, stride=1, downsample=False, name=None):
    out = _conv_bn(input, planes, 3, stride=stride)
    out = _conv_bn(out, planes, 3, act=None)
    if downsample:
        short = _conv_bn(input, planes, 1, stride=stride, act=None)
    else:
        short = input
    return layer.addto(input=[out, short], act="relu")


_DEPTH_CFG = {
    18: (_basic, [2, 2, 2, 2], 1),
    34: (_basic, [3, 4, 6, 3], 1),
    50: (_bottleneck, [3, 4, 6, 3], 4),
    101: (_bottleneck, [3, 4, 23, 3], 4),
    152: (_bottleneck, [3, 8, 36, 3], 4),
}


def build(depth: int = 50, img_size: int = 224, num_classes: int = 1000):
    """Returns (images, label, logits, cost)."""
    block, layers_cfg, expansion = _DEPTH_CFG[depth]
    images = layer.data(
        name="image", type=paddle.data_type.dense_vector(3 * img_size * img_size),
        height=img_size, width=img_size)
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))

    net = _conv_bn(images, 64, 7, stride=2, padding=3)
    net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1)
    planes = 64
    for stage, blocks in enumerate(layers_cfg):
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            downsample = (b == 0)
            net = block(net, planes, stride=stride, downsample=downsample)
        planes *= 2
    # global average pool over the final 7x7 maps
    h, w, c = net.img_shape
    net = layer.img_pool(input=net, pool_size=h, stride=h, pool_type=paddle.pooling.AvgPooling())
    logits = layer.fc(input=net, size=num_classes)
    cost = layer.classification_cost(input=logits, label=label)
    return images, label, logits, cost
