"""GAN — generator/discriminator pair trained alternately.

Reference analog: v1_api_demo/gan/gan_trainer.py + gan_conf.py (two
networks built from shared parameter names, trained alternately with
separate optimizers). Here both cost graphs share ONE parameter store;
the discriminator tower is applied twice (real batch, generated batch)
through pinned parameter names, and MultiTaskTrainer masks updates to
each side's prefix ("gen_" / "dis_").
"""

from __future__ import annotations

from typing import Tuple

from paddle_tpu import data_type, layer
from paddle_tpu.attr import ParamAttr


def _shared_fc(inp, size, act, pname):
    """fc with pinned parameter names so several applications share
    weights (the reference pins via explicit param names in gan_conf)."""
    return layer.fc(inp, size=size, act=act,
                    param_attr=ParamAttr(name=f"{pname}.w"),
                    bias_attr=ParamAttr(name=f"{pname}.b"),
                    name=layer.unique_name(pname))


def generator(noise, dims: Tuple[int, ...], out_dim: int):
    h = noise
    for i, d in enumerate(dims):
        h = _shared_fc(h, d, "relu", f"gen_h{i}")
    return _shared_fc(h, out_dim, "tanh", "gen_out")


def discriminator_logit(x, dims: Tuple[int, ...]):
    h = x
    for i, d in enumerate(dims):
        h = _shared_fc(h, d, "relu", f"dis_h{i}")
    return _shared_fc(h, 1, None, "dis_out")


def build(noise_dim: int = 16, data_dim: int = 2,
          gen_dims: Tuple[int, ...] = (32, 32),
          dis_dims: Tuple[int, ...] = (32,)):
    """Returns (noise, real, fake, d_cost, g_cost).

    d_cost = BCE(D(real), 1) + BCE(D(fake), 0)   (updates dis_*)
    g_cost = BCE(D(fake), 1)                     (updates gen_*)
    """
    noise = layer.data(name="noise", type=data_type.dense_vector(noise_dim))
    real = layer.data(name="pixel", type=data_type.dense_vector(data_dim))
    ones = layer.data(name="label_one", type=data_type.dense_vector(1))
    zeros = layer.data(name="label_zero", type=data_type.dense_vector(1))

    fake = generator(noise, gen_dims, data_dim)
    d_real = discriminator_logit(real, dis_dims)
    d_fake = discriminator_logit(fake, dis_dims)

    d_cost = layer.addto(
        [layer.multi_binary_label_cross_entropy_cost(input=d_real,
                                                     label=ones),
         layer.multi_binary_label_cross_entropy_cost(input=d_fake,
                                                     label=zeros)])
    d_cost.is_cost = True
    g_cost = layer.multi_binary_label_cross_entropy_cost(input=d_fake,
                                                         label=ones)
    return noise, real, fake, d_cost, g_cost
