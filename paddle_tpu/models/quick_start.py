"""quick_start text-classification family — the 7 architectures of
v1_api_demo/quick_start/trainer_config.{lr,emb,cnn,lstm,bidi-lstm,db-lstm,
resnet-lstm}.py, each a sentiment classifier over word-id sequences
(bag-of-words for ``lr``).

``build(arch)`` returns (word, label, output, cost) where ``output`` is the
class-score layer (logits — classification_cost fuses the softmax, this
framework's convention; argmax/max_id at predict time is softmax-invariant).
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer, pooling
from paddle_tpu.attr import ExtraAttr
from paddle_tpu.networks import (bidirectional_lstm, sequence_conv_pool,
                                 simple_lstm)

ARCHS = ("lr", "emb", "cnn", "lstm", "bidi_lstm", "db_lstm", "resnet_lstm")


def _lr(word, dict_size, emb_size):
    # trainer_config.lr.py: bag-of-words -> softmax fc. The BoW vector is
    # the dense data input itself (dataprovider_bow).
    return word


def _emb_avg(word, dict_size, emb_size):
    emb = layer.embedding(input=word, size=emb_size)
    return layer.pooling(input=emb, pooling_type=pooling.AvgPooling())


def _cnn(word, dict_size, emb_size):
    emb = layer.embedding(input=word, size=emb_size)
    return sequence_conv_pool(emb, context_len=3, hidden_size=512)


def _lstm(word, dict_size, emb_size):
    emb = layer.embedding(input=word, size=emb_size)
    lstm = simple_lstm(emb, size=emb_size)
    lstm = layer.dropout(lstm, 0.25)
    return layer.pooling(input=lstm, pooling_type=pooling.MaxPooling())


def _bidi_lstm(word, dict_size, emb_size):
    emb = layer.embedding(input=word, size=emb_size)
    bi = bidirectional_lstm(emb, size=emb_size)
    return layer.pooling(input=bi, pooling_type=pooling.MaxPooling())


def _db_lstm(word, dict_size, emb_size, depth: int = 4):
    # trainer_config.db-lstm.py: alternating-direction stacked LSTM; each
    # level's fc sees [previous fc, previous lstm]
    emb = layer.embedding(input=word, size=emb_size)
    hidden = layer.fc(input=emb, size=emb_size)
    lstm = layer.lstmemory(
        input=layer.fc(input=hidden, size=emb_size * 4, name="db0_proj"),
        size=emb_size, layer_attr=ExtraAttr(drop_rate=0.1))
    inputs = [hidden, lstm]
    for i in range(1, depth):
        fc = layer.fc(input=inputs, size=emb_size)
        lstm = layer.lstmemory(
            input=layer.fc(input=fc, size=emb_size * 4, name=f"db{i}_proj"),
            size=emb_size, reverse=(i % 2) == 1,
            layer_attr=ExtraAttr(drop_rate=0.1))
        inputs = [fc, lstm]
    return layer.pooling(input=lstm, pooling_type=pooling.MaxPooling())


def _resnet_lstm(word, dict_size, emb_size, depth: int = 3):
    # trainer_config.resnet-lstm.py (GNMT-style residual LSTM stack):
    # level input = previous input + previous hidden state
    emb = layer.embedding(input=word, size=emb_size)
    prev_input, prev_hidden = emb, simple_lstm(emb, size=emb_size)
    for i in range(depth):
        cur = layer.addto(input=[prev_input, prev_hidden])
        hidden = simple_lstm(cur, size=emb_size, name=f"res_lstm{i}")
        prev_input, prev_hidden = cur, hidden
    return layer.pooling(input=prev_hidden,
                         pooling_type=pooling.MaxPooling())


_BUILDERS = {
    "lr": _lr, "emb": _emb_avg, "cnn": _cnn, "lstm": _lstm,
    "bidi_lstm": _bidi_lstm, "db_lstm": _db_lstm, "resnet_lstm": _resnet_lstm,
}


def build(arch: str = "cnn", dict_size: int = 30000, emb_size: int = 128,
          num_classes: int = 2, **arch_kwargs):
    """Returns (word, label, output, cost) for one of ARCHS.

    ``arch_kwargs`` forward to the arch builder (e.g. ``depth=`` for
    db_lstm / resnet_lstm stack depth)."""
    if arch not in _BUILDERS:
        raise KeyError(f"unknown quick_start arch {arch!r}; one of {ARCHS}")
    if arch == "lr":
        word = layer.data(name="word",
                          type=paddle.data_type.dense_vector(dict_size))
    else:
        word = layer.data(
            name="word",
            type=paddle.data_type.integer_value_sequence(dict_size))
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))
    feat = _BUILDERS[arch](word, dict_size, emb_size, **arch_kwargs)
    output = layer.fc(input=feat, size=num_classes)
    cost = layer.classification_cost(input=output, label=label)
    return word, label, output, cost
