"""AlexNet (reference: benchmark/paddle/image/alexnet.py — the headline
single-GPU benchmark config, BASELINE.md: 334 ms/batch @ bs=128 on K40m)."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer


def build(img_size: int = 227, num_classes: int = 1000):
    """Returns (images, label, logits, cost). Input layout: flat C*H*W."""
    images = layer.data(
        name="image", type=paddle.data_type.dense_vector(3 * img_size * img_size),
        height=img_size, width=img_size)
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))

    # conv1: 96 kernels 11x11 stride 4 + LRN + pool
    net = layer.img_conv(input=images, filter_size=11, num_filters=96,
                         num_channels=3, stride=4, padding=1, act="relu")
    net = layer.img_cmrnorm(input=net, size=5)
    net = layer.img_pool(input=net, pool_size=3, stride=2)
    # conv2: 256 kernels 5x5 grouped
    net = layer.img_conv(input=net, filter_size=5, num_filters=256, padding=2,
                         groups=1, act="relu")
    net = layer.img_cmrnorm(input=net, size=5)
    net = layer.img_pool(input=net, pool_size=3, stride=2)
    # conv3-5
    net = layer.img_conv(input=net, filter_size=3, num_filters=384, padding=1,
                         act="relu")
    net = layer.img_conv(input=net, filter_size=3, num_filters=384, padding=1,
                         act="relu")
    net = layer.img_conv(input=net, filter_size=3, num_filters=256, padding=1,
                         act="relu")
    net = layer.img_pool(input=net, pool_size=3, stride=2)

    net = layer.fc(input=net, size=4096, act="relu")
    net = layer.dropout(net, 0.5)
    net = layer.fc(input=net, size=4096, act="relu")
    net = layer.dropout(net, 0.5)
    logits = layer.fc(input=net, size=num_classes)
    cost = layer.classification_cost(input=logits, label=label)
    return images, label, logits, cost
