"""SmallNet — the cifar-quick benchmark config (reference:
benchmark/paddle/image/smallnet_mnist_cifar.py, BASELINE.md SmallNet rows:
10.5 ms/batch bs=64 on K40m; the caffe cifar10_quick lineage: three 5x5
convs with pooling, then fc).
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import layer


def build(img_size: int = 32, num_classes: int = 10):
    """Returns (images, label, logits, cost)."""
    images = layer.data(
        name="image",
        type=paddle.data_type.dense_vector(3 * img_size * img_size),
        height=img_size, width=img_size)
    label = layer.data(name="label",
                       type=paddle.data_type.integer_value(num_classes))
    net = layer.img_conv(input=images, filter_size=5, num_filters=32,
                         padding=2, act="relu")
    net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1)
    net = layer.img_conv(input=net, filter_size=5, num_filters=32, padding=2,
                         act="relu")
    net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1,
                         pool_type=paddle.pooling.AvgPooling())
    net = layer.img_conv(input=net, filter_size=5, num_filters=64, padding=2,
                         act="relu")
    net = layer.img_pool(input=net, pool_size=3, stride=2, padding=1,
                         pool_type=paddle.pooling.AvgPooling())
    net = layer.fc(input=net, size=64)
    logits = layer.fc(input=net, size=num_classes)
    cost = layer.classification_cost(input=logits, label=label)
    return images, label, logits, cost
