"""Trainer events (reference: python/paddle/v2/event.py).

The event handler contract is identical to the reference: the trainer calls a
user handler with BeginPass/EndPass/BeginIteration/EndIteration/TestResult.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class WithMetric:
    """Metric values may arrive as device arrays (the trainer avoids a host
    sync per batch); the ``metrics`` property converts to floats on first
    access and caches — handlers see plain floats either way."""

    def __init__(self, evaluator_result: Optional[Dict[str, float]] = None):
        self._metrics_raw = evaluator_result or {}
        self._metrics: Optional[Dict[str, float]] = None

    @property
    def metrics(self) -> Dict[str, float]:
        if self._metrics is None:
            self._metrics = {k: float(v) for k, v in self._metrics_raw.items()}
        return self._metrics


class BeginPass:
    def __init__(self, pass_id: int):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id: int, evaluator_result=None, parameters=None):
        super().__init__(evaluator_result)
        self.pass_id = pass_id
        self.parameters = parameters


class BeginIteration:
    def __init__(self, pass_id: int, batch_id: int):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id: int, batch_id: int, cost: float,
                 evaluator_result=None):
        super().__init__(evaluator_result)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self._cost_raw = cost
        self._cost: Optional[float] = None

    @property
    def cost(self) -> float:
        """Plain float; forces the device sync lazily on first access."""
        if self._cost is None:
            self._cost = float(self._cost_raw)
        return self._cost


class TestResult(WithMetric):
    def __init__(self, cost: float, evaluator_result=None):
        super().__init__(evaluator_result)
        self.cost = cost
