"""Trainer events (reference: python/paddle/v2/event.py).

The event handler contract is identical to the reference: the trainer calls a
user handler with BeginPass/EndPass/BeginIteration/EndIteration/TestResult.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class WithMetric:
    def __init__(self, evaluator_result: Optional[Dict[str, float]] = None):
        self.metrics = evaluator_result or {}


class BeginPass:
    def __init__(self, pass_id: int):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id: int, evaluator_result=None, parameters=None):
        super().__init__(evaluator_result)
        self.pass_id = pass_id
        self.parameters = parameters


class BeginIteration:
    def __init__(self, pass_id: int, batch_id: int):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id: int, batch_id: int, cost: float,
                 evaluator_result=None):
        super().__init__(evaluator_result)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost


class TestResult(WithMetric):
    def __init__(self, cost: float, evaluator_result=None):
        super().__init__(evaluator_result)
        self.cost = cost
