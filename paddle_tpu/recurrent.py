"""Recurrent layer groups — the RecurrentGradientMachine analog.

Reference: paddle/gserver/gradientmachines/RecurrentGradientMachine.{h,cpp}
(unrolls a per-frame sub-network over sequence frames with cross-frame
`memory` links, AgentLayer/ScatterAgent plumbing) and the config surface
trainer_config_helpers recurrent_group/memory/StaticInput (layers.py).

TPU-native: the user's ``step`` function is traced ONCE into a sub-Topology
whose frame inputs are placeholder nodes; at runtime the group node converts
sequence inputs to padded [B, T, D] and drives the sub-topology under
``lax.scan`` — one compiled region for all timesteps (the reference re-ran a
C++ sub-network per frame). Memories are scan carries; masked steps carry
state through unchanged, preserving exact variable-length semantics.

``memory(name=N)`` links to the step-graph layer literally named N, exactly
like the reference's name-based memory links.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.sequence import (SequenceBatch, nested_from_padded,
                                 nested_to_padded)
from paddle_tpu.topology import (Context, LayerOutput, ParamSpec, Topology,
                                 unique_name)

__all__ = ["memory", "StaticInput", "SubsequenceInput", "recurrent_group"]


# stack of per-group memory collections; populated while a step fn is traced
_MEMORY_STACK: List[List["_Memory"]] = []


class _Memory:
    def __init__(self, node: LayerOutput, link_name: str, size: int,
                 boot_layer: Optional[LayerOutput], is_seq: bool = False):
        self.node = node            # placeholder node used inside the step
        self.link_name = link_name  # step layer whose output feeds t+1
        self.size = size
        self.boot_layer = boot_layer
        self.is_seq = is_seq


def memory(name: str, size: int, boot_layer: Optional[LayerOutput] = None,
           is_seq: bool = False, **_kw) -> LayerOutput:
    """Cross-frame state inside a recurrent_group step (reference:
    trainer_config_helpers memory()). ``name`` names the step layer whose
    output becomes this memory at the next frame."""
    enforce_that(len(_MEMORY_STACK) > 0,
                 "memory() must be called inside a recurrent_group step",
                 context="recurrent")
    enforce_that(not _kw, f"unsupported memory() options: {sorted(_kw)}",
                 context="recurrent")
    enforce_that(is_seq or boot_layer is None
                 or not boot_layer.is_sequence,
                 "memory boot_layer must be a non-sequence layer "
                 "(pool/last_seq it first)", context="recurrent")
    enforce_that(not (is_seq and boot_layer is not None),
                 "sequence memories boot empty (is_seq=True + boot_layer "
                 "is not supported)", context="recurrent")
    node = LayerOutput(name=unique_name(f"mem_{name}"), layer_type="memory",
                       inputs=[], fn=None, size=size, is_sequence=is_seq)
    _MEMORY_STACK[-1].append(_Memory(node, name, size, boot_layer,
                                     is_seq=is_seq))
    return node


class StaticInput:
    """A full (possibly sequence) value visible unchanged at every frame
    (reference: StaticInput in layers.py / the 'static agent' link)."""

    def __init__(self, input: LayerOutput, is_seq: bool = None):
        self.input = input
        self.is_seq = input.is_sequence if is_seq is None else is_seq


class SubsequenceInput:
    """Marks a NESTED sequence in-link of a hierarchical recurrent_group
    (reference: SubsequenceInput, trainer_config_helpers layers.py — the
    sequence_nest_rnn configs): the group's outer loop steps over INNER
    SEQUENCES, so each frame the step receives a SequenceBatch (one inner
    sequence per outer sequence) and can run pooling / an inner
    recurrent_group over it.

    ``max_inner`` (most inner sequences per outer sequence) and
    ``max_inner_len`` (longest inner sequence) are STATIC shape bounds for
    the compiled scan — pass the feeder's bucket bounds; they default to
    the input's max_len (safe but O(max_len^2) padding)."""

    def __init__(self, input: LayerOutput, max_inner: int = None,
                 max_inner_len: int = None):
        self.input = input
        self.max_inner = max_inner
        self.max_inner_len = max_inner_len


# ---------------------------------------------------------------------------
# Shared machinery for step-function hosts (recurrent_group and
# generation.beam_search both trace a step graph, resolve memory links, and
# hoist/pin sub-graph params — keep the logic in one place)
# ---------------------------------------------------------------------------

def make_static_node(group_name: str, item: StaticInput) -> LayerOutput:
    """Placeholder node a StaticInput is bound to inside the step graph."""
    return LayerOutput(name=unique_name(f"{group_name}_static"),
                       layer_type="static_frame", inputs=[], fn=None,
                       size=item.input.size, is_sequence=item.is_seq)


def trace_step(step, frame_args):
    """Trace the user's step function once; returns (outputs, memories)."""
    _MEMORY_STACK.append([])
    try:
        step_outs = step(*frame_args)
    finally:
        memories = _MEMORY_STACK.pop()
    return step_outs, memories


def resolve_memory_links(probe: Topology, memories: Sequence[_Memory],
                         context: str) -> List[LayerOutput]:
    """Find each memory's linked step layer in the probe topology (one entry
    per memory, aligned with ``memories``)."""
    link_nodes: List[LayerOutput] = []
    for m in memories:
        target = probe.by_name.get(m.link_name)
        if target is None:
            raise EnforceError(
                f"memory links to layer {m.link_name!r} which is not in the "
                f"step graph reachable from its outputs", context=context)
        link_nodes.append(target)
    return link_nodes


def pin_param_names(sub_topo: Topology) -> Dict[str, ParamSpec]:
    """Hoist sub-graph params, pinning each spec's canonical name to its sub
    key so the OUTER param table uses the same key regardless of which group
    hosts the step — this is what lets a recurrent_group (training) and a
    beam_search (generation) built from the same step share weights."""
    import dataclasses as _dc

    group_params: Dict[str, ParamSpec] = {}
    for key, spec in sub_topo.param_specs().items():
        if spec.attr.name is None:
            spec = _dc.replace(spec, attr=_dc.replace(spec.attr, name=key))
        group_params[key] = spec
    return group_params


def group_state_slots(sub_topo: Topology) -> Dict[str, Dict[str, object]]:
    """Sub-layer state (e.g. batch_norm moving stats) exposed under the
    SUB-LAYER names themselves (LayerOutput.foreign_state), so a training
    group and a generation host built from the same (stably-named) step
    read and write the same slots — the state analog of pin_param_names."""
    return sub_topo.state_specs()


def read_group_state(ctx: Context, sub_topo: Topology):
    """Rebuild the sub-topology state dict from the shared namespaces."""
    return {
        lname: {k: ctx.get_state(lname, k) for k in slots}
        for lname, slots in sub_topo.state_specs().items()
    }


def write_group_state(ctx: Context, sub_state) -> None:
    for lname, slots in (sub_state or {}).items():
        for k, v in slots.items():
            ctx.set_state(lname, k, v)


def recurrent_group(step, input, reverse: bool = False,
                    name: Optional[str] = None) -> Union[LayerOutput, List[LayerOutput]]:
    """Run ``step`` over the frames of the sequence inputs (reference:
    recurrent_group → RecurrentGradientMachine::forward,
    RecurrentGradientMachine.cpp:530).

    ``input``: sequence LayerOutputs (per-frame slices) and/or StaticInputs.
    ``step(*frame_args)`` builds the per-frame graph; returns one or more
    LayerOutputs. Sequence outputs of the group are SequenceBatches aligned
    with the first sequence input.
    """
    name = name or unique_name("recurrent_group")
    inputs = input if isinstance(input, (list, tuple)) else [input]

    seq_inputs: List[LayerOutput] = []
    static_inputs: List[StaticInput] = []
    nested_specs: List[SubsequenceInput] = []
    frame_args: List[LayerOutput] = []
    frame_nodes: List[LayerOutput] = []    # placeholders for per-frame slices
    static_nodes: List[LayerOutput] = []   # placeholders for statics

    nested = any(isinstance(it, SubsequenceInput) for it in inputs)
    for item in inputs:
        if isinstance(item, StaticInput):
            node = make_static_node(name, item)
            static_inputs.append(item)
            static_nodes.append(node)
            frame_args.append(node)
        elif isinstance(item, SubsequenceInput):
            # hierarchical group: the frame IS an inner sequence
            node = LayerOutput(name=unique_name(f"{name}_subseq_frame"),
                               layer_type="frame", inputs=[], fn=None,
                               size=item.input.size, is_sequence=True)
            seq_inputs.append(item.input)
            nested_specs.append(item)
            frame_nodes.append(node)
            frame_args.append(node)
        else:
            enforce_that(item.is_sequence,
                         f"recurrent_group input {item.name} must be a sequence "
                         "(wrap non-sequences in StaticInput)", context="recurrent")
            enforce_that(not nested,
                         "a hierarchical recurrent_group steps over inner "
                         "sequences: wrap EVERY sequence in-link in "
                         "SubsequenceInput (mixed nest levels don't align, "
                         "the reference's equal-nest-level rule)",
                         context="recurrent")
            node = LayerOutput(name=unique_name(f"{name}_frame"),
                               layer_type="frame", inputs=[], fn=None,
                               size=item.size, is_sequence=False)
            seq_inputs.append(item)
            frame_nodes.append(node)
            frame_args.append(node)

    enforce_that(len(seq_inputs) > 0, "recurrent_group needs >=1 sequence input",
                 context="recurrent")
    enforce_that(not nested or len(nested_specs) == len(seq_inputs),
                 "mixed nested and flat sequence in-links", context="recurrent")

    # ---- trace the step graph once --------------------------------------
    step_outs, memories = trace_step(step, frame_args)
    multi_out = isinstance(step_outs, (list, tuple))
    out_list: List[LayerOutput] = list(step_outs) if multi_out else [step_outs]
    # nested groups may emit per-inner-sequence VECTORS (a flat sequence
    # over the outer structure) or transformed INNER SEQUENCES (a nested
    # sequence out, the reference's NEST_SEQUENCE output mode)

    sub_outputs = list(out_list)
    link_nodes = resolve_memory_links(Topology(sub_outputs), memories,
                                      "recurrent")
    sub_topo = Topology(sub_outputs + link_nodes)

    # ---- build the group node in the outer graph ------------------------
    outer_inputs: List[LayerOutput] = (
        list(seq_inputs) + [s.input for s in static_inputs] +
        [m.boot_layer for m in memories if m.boot_layer is not None])

    group_params = pin_param_names(sub_topo)

    n_seq = len(seq_inputs)
    n_static = len(static_inputs)

    if not nested:
        for m in memories:
            enforce_that(not m.is_seq,
                         "memory(is_seq=True) carries a whole inner "
                         "sequence across OUTER steps — it needs a "
                         "hierarchical group (SubsequenceInput in-links)",
                         context="recurrent")

    def compute(ctx: Context, p, ins):
        seq_vals: List[SequenceBatch] = ins[:n_seq]
        static_vals = ins[n_seq:n_seq + n_static]
        boot_vals = ins[n_seq + n_static:]
        boot_map = {}
        bi = 0
        for m in memories:
            if m.boot_layer is not None:
                boot_map[m.node.name] = boot_vals[bi]
                bi += 1

        first = seq_vals[0]
        padded_list, mask = [], None
        T = None
        for sv in seq_vals:
            pd, mk = sv.to_padded()
            enforce_that(
                T is None or pd.shape[1] == T,
                f"recurrent_group sequence inputs disagree on max length "
                f"({pd.shape[1]} vs {T}); all in-links must share lengths "
                f"and bucketing (reference requires equal-length in-links)",
                context="recurrent")
            padded_list.append(pd)
            # AND the masks: a frame only runs while EVERY in-link is live,
            # so differing per-sample lengths never feed padding into a
            # live step (equal lengths keep this a no-op)
            mask = mk if mask is None else jnp.logical_and(mask, mk)
            T = pd.shape[1]
        B = first.num_seqs

        # stateful sub-layers (batch_norm moving stats) ride the scan carry
        # and propagate outward through namespaces shared by sub-layer name
        group_name = ctx._current or name
        sub_state0 = read_group_state(ctx, sub_topo)
        base_key = ctx.rng_for(group_name)

        def frame(carry, xs):
            mems, sstate = carry
            t_slices, m_t, t_idx = xs
            feeds: Dict[str, Any] = {}
            for node, sl in zip(frame_nodes, t_slices):
                feeds[node.name] = sl
            for node, sv in zip(static_nodes, static_vals):
                feeds[node.name] = sv
            for m in memories:
                feeds[m.node.name] = mems[m.node.name]
            # fresh randomness per frame (dropout masks differ across time)
            key = jax.random.fold_in(base_key, t_idx)
            outs, new_sstate = sub_topo.forward(p, sstate, feeds,
                                                train=ctx.train, rng=key)
            frame_outs = outs[: len(out_list)]
            link_outs = outs[len(out_list):]
            new_mems = {}
            mm = m_t[:, None]
            for m, lo in zip(memories, link_outs):
                prev = mems[m.node.name]
                val = lo.data if isinstance(lo, SequenceBatch) else lo
                new_mems[m.node.name] = jnp.where(mm, val, prev)
            # state only advances on frames where any sequence is live
            any_live = jnp.any(m_t)
            kept_state = jax.tree_util.tree_map(
                lambda new, old: jnp.where(any_live, new, old),
                new_sstate, sstate) if sstate else sstate
            ys = tuple(o.data if isinstance(o, SequenceBatch) else o
                       for o in frame_outs)
            return (new_mems, kept_state), ys

        init_mems = {}
        for m in memories:
            if m.node.name in boot_map:
                bv = boot_map[m.node.name]
                enforce_that(not isinstance(bv, SequenceBatch),
                             f"memory {m.link_name!r} boot_layer must be a "
                             "non-sequence layer (got a sequence)",
                             context="recurrent")
                init_mems[m.node.name] = bv.astype(jnp.float32)
            else:
                init_mems[m.node.name] = jnp.zeros((B, m.size), jnp.float32)

        xs = (tuple(jnp.swapaxes(pd, 0, 1) for pd in padded_list),
              jnp.swapaxes(mask, 0, 1),
              jnp.arange(T, dtype=jnp.int32))
        (_, final_sstate), ys = jax.lax.scan(frame, (init_mems, sub_state0),
                                             xs, reverse=reverse)
        write_group_state(ctx, final_sstate)
        # A frame is a real output only while EVERY in-link was live; with
        # unequal per-sample lengths the extra frames ran on padding, so
        # zero them and report the combined (elementwise-min) lengths.
        out_lengths = jnp.sum(mask.astype(first.lengths.dtype), axis=1)
        # ys: tuple of [T, B, D] -> SequenceBatch each
        results = []
        for y in ys:
            y = jnp.swapaxes(y, 0, 1)  # [B, T, D]
            y = jnp.where(mask[:, :, None], y, 0)
            results.append(SequenceBatch.from_padded(y, out_lengths,
                                                     capacity=first.capacity))
        return tuple(results) if multi_out else results[0]

    def compute_nested(ctx: Context, p, ins):
        """Hierarchical scan: one outer step per INNER sequence. Frames are
        SequenceBatches rebuilt inside the scan from the [B, S, W, ...]
        nested view (reference: RecurrentGradientMachine's nested-level
        forward, test_RecurrentGradientMachine.cpp sequence_nest configs)."""
        seq_vals: List[SequenceBatch] = ins[:len(seq_inputs)]
        static_vals = ins[len(seq_inputs):len(seq_inputs) + len(static_inputs)]
        boot_vals = ins[len(seq_inputs) + len(static_inputs):]
        boot_map = {}
        bi = 0
        for m in memories:
            if m.boot_layer is not None:
                boot_map[m.node.name] = boot_vals[bi]
                bi += 1

        first = seq_vals[0]
        B = first.num_seqs
        views = []
        counts = None
        S = W = None
        for spec, sv in zip(nested_specs, seq_vals):
            enforce_that(sv.sub_segment_ids is not None,
                         "SubsequenceInput needs a nested SequenceBatch "
                         "feed (sub_segment_ids)", context="recurrent")
            s_b = int(spec.max_inner or sv.max_len or sv.capacity)
            w_b = int(spec.max_inner_len or sv.max_len or sv.capacity)
            enforce_that(S is None or (S == s_b and W == w_b),
                         "nested in-links disagree on max_inner/"
                         "max_inner_len bounds", context="recurrent")
            S, W = s_b, w_b
            data, inner_lens, cnt = nested_to_padded(sv, s_b, w_b)
            views.append((data, inner_lens))
            # outer frames advance in lockstep: inner-seq counts must agree
            counts = cnt if counts is None else jnp.minimum(counts, cnt)

        outer_mask = jnp.arange(S)[None, :] < counts[:, None]   # [B, S]

        group_name = ctx._current or name
        sub_state0 = read_group_state(ctx, sub_topo)
        base_key = ctx.rng_for(group_name)

        def frame(carry, xs):
            mems, sstate = carry
            t_views, m_t, t_idx = xs
            feeds: Dict[str, Any] = {}
            for node, (x_t, lens_t) in zip(frame_nodes, t_views):
                # dead outer frames (this row has no s-th inner sequence)
                # get a 1-token zero dummy: empty sequences make max-pool
                # emit -inf whose masked-out gradient is still NaN
                # (0 * inf); the frame's output is discarded by the
                # memory/output masks either way
                safe_lens = jnp.where(m_t, lens_t,
                                      jnp.ones_like(lens_t))
                feeds[node.name] = SequenceBatch.from_padded(
                    x_t, safe_lens, capacity=B * W)
            for node, sv in zip(static_nodes, static_vals):
                feeds[node.name] = sv
            for m in memories:
                if m.is_seq:
                    mp, ml = mems[m.node.name]
                    feeds[m.node.name] = SequenceBatch.from_padded(
                        mp, ml, capacity=B * W)
                else:
                    feeds[m.node.name] = mems[m.node.name]
            key = jax.random.fold_in(base_key, t_idx)
            outs, new_sstate = sub_topo.forward(p, sstate, feeds,
                                                train=ctx.train, rng=key)
            frame_outs = outs[: len(out_list)]
            link_outs = outs[len(out_list):]
            new_mems = {}
            mm = m_t[:, None]
            for m, lo in zip(memories, link_outs):
                prev = mems[m.node.name]
                if m.is_seq:
                    enforce_that(isinstance(lo, SequenceBatch),
                                 f"memory(is_seq=True) links to "
                                 f"{m.link_name!r} which is not a sequence "
                                 "layer", context="recurrent")
                    lp, _lm = lo.to_padded(max_len=W)
                    ll = lo.lengths
                    pp, pl = prev
                    new_mems[m.node.name] = (
                        jnp.where(m_t[:, None, None], lp, pp),
                        jnp.where(m_t, jnp.clip(ll, 1, W), pl))
                else:
                    val = lo.data if isinstance(lo, SequenceBatch) else lo
                    new_mems[m.node.name] = jnp.where(mm, val, prev)
            any_live = jnp.any(m_t)
            kept_state = jax.tree_util.tree_map(
                lambda new, old: jnp.where(any_live, new, old),
                new_sstate, sstate) if sstate else sstate
            ys = tuple(
                # sequence outputs ride the scan as (padded [B, Wo, ...],
                # inner lens [B]); dense outputs as plain arrays
                (o.to_padded()[0], o.lengths)
                if isinstance(o, SequenceBatch) else o
                for o in frame_outs)
            return (new_mems, kept_state), ys

        init_mems = {}
        for m in memories:
            if m.is_seq:
                # boot: a 1-token zero sequence (an EMPTY sequence would
                # make max-pool emit -inf with NaN masked gradients)
                init_mems[m.node.name] = (
                    jnp.zeros((B, W, m.size), jnp.float32),
                    jnp.ones((B,), jnp.int32))
            elif m.node.name in boot_map:
                init_mems[m.node.name] = boot_map[m.node.name].astype(
                    jnp.float32)
            else:
                init_mems[m.node.name] = jnp.zeros((B, m.size), jnp.float32)

        xs = (tuple((jnp.swapaxes(d, 0, 1), jnp.swapaxes(l, 0, 1))
                    for d, l in views),
              jnp.swapaxes(outer_mask, 0, 1),
              jnp.arange(S, dtype=jnp.int32))
        (_, final_sstate), ys = jax.lax.scan(frame, (init_mems, sub_state0),
                                             xs, reverse=reverse)
        write_group_state(ctx, final_sstate)
        results = []
        for o, y in zip(out_list, ys):
            if o.is_sequence:
                # NESTED output: per-frame inner sequences reassemble into
                # a nested SequenceBatch over the outer structure
                yp, ylens = y                        # [S,B,Wo,...], [S,B]
                yp = jnp.moveaxis(yp, 0, 1)          # [B, S, Wo, ...]
                ylens = jnp.where(outer_mask,
                                  jnp.swapaxes(ylens, 0, 1), 0)  # [B, S]
                # capacity must hold the OUTPUT token bound (a step may
                # emit more tokens than the in-link held)
                wo = yp.shape[2]
                results.append(nested_from_padded(
                    yp, jnp.clip(ylens, 0, wo), counts,
                    capacity=max(first.capacity, B * S * wo)))
            else:
                # one row per INNER sequence -> flat sequence whose
                # lengths are the inner-sequence counts
                yd = jnp.swapaxes(y, 0, 1)           # [B, S, D]
                yd = jnp.where(outer_mask[:, :, None], yd, 0)
                results.append(SequenceBatch.from_padded(
                    yd, counts, capacity=B * S))
        return tuple(results) if multi_out else results[0]

    group_node = LayerOutput(name=name, layer_type="recurrent_group",
                             inputs=outer_inputs,
                             fn=compute_nested if nested else compute,
                             params=group_params,
                             foreign_state=group_state_slots(sub_topo),
                             size=out_list[0].size,
                             is_sequence=True)

    if not multi_out:
        return group_node

    # expose each step output as its own node reading the group's tuple
    results = []
    for idx, o in enumerate(out_list):
        def pick(ctx, p, ins, idx=idx):
            return ins[0][idx]

        node = LayerOutput(name=f"{name}_out{idx}", layer_type="rg_output",
                           inputs=[group_node], fn=pick, size=o.size,
                           is_sequence=True)
        results.append(node)
    return results
