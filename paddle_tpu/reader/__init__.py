"""Reader protocol: a reader is a zero-arg callable returning an iterable of
samples (reference: python/paddle/v2/reader — readers as generators)."""

from paddle_tpu.reader.decorator import (buffered, chain, compose, firstn,
                                         map_readers, shuffle, xmap_readers)
from paddle_tpu.reader import creator

__all__ = ["buffered", "chain", "compose", "firstn", "map_readers", "shuffle",
           "xmap_readers", "creator"]
