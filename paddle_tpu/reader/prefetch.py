"""Device-prefetching input pipeline.

Reference: the async double-buffered DataProvider pool
(paddle/gserver/dataproviders/DataProvider.h:292 — getNextBatch runs on a
background thread so host IO overlaps compute) and PyDataProvider2's pool
thread (PyDataProvider2.cpp:334-400).

TPU-native: the hot-path cost is the host->device transfer of each batch
(a 128x224x224x3 f32 ResNet batch is ~77MB). ``device_prefetch`` keeps N
batches in flight on the device — jax.device_put is async, so the
transfer of batch k+1 overlaps the compute of batch k, and a background
thread keeps the host-side feed/convert work off the training loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Optional

import jax

from paddle_tpu.sequence import SequenceBatch


def device_put_feeds(feeds, sharding=None):
    """Async-place one feed dict on the device (or with a NamedSharding)."""
    out = {}
    for k, v in feeds.items():
        if isinstance(v, SequenceBatch):
            out[k] = v  # already device arrays (DataFeeder built them)
        elif sharding is not None:
            out[k] = jax.device_put(v, sharding)
        else:
            out[k] = jax.device_put(v)
    return out


class _ErrorBox:
    """Producer-to-consumer exception hand-off.

    The producer thread stores at most one exception; the consumer takes
    it after seeing the end sentinel.  The queue's own internal lock
    orders ``set`` (before ``put(end)``) against ``take`` (after
    ``get()`` returns ``end``), but the box keeps its own lock so the
    hand-off doesn't depend on that implementation detail."""

    def __init__(self):
        self._lock = threading.Lock()
        self._err: Optional[BaseException] = None   # guarded_by(_lock)

    def set(self, exc: BaseException) -> None:
        with self._lock:
            if self._err is None:  # first error wins
                self._err = exc

    def take(self) -> Optional[BaseException]:
        with self._lock:
            err, self._err = self._err, None
            return err


def device_prefetch(feed_iter: Iterable, size: int = 2,
                    transform: Optional[Callable] = None,
                    place: Optional[Callable] = None):
    """Iterate feed dicts with ``size`` batches resident ahead of use.

    A daemon thread drains ``feed_iter`` (running ``transform`` — e.g. a
    DataFeeder — on the host side) and places each batch on device
    (``place``; defaults to plain device_put, pass e.g. SGD._shard_feeds
    to land mesh shardings) into a bounded queue; the consumer always
    finds the next batch already on device.
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(1, size))
    end = object()
    err_box = _ErrorBox()
    stop = threading.Event()
    place = place or device_put_feeds

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in feed_iter:
                if stop.is_set():
                    return
                if transform is not None:
                    item = transform(item)
                if not put(place(item)):
                    return
        except BaseException as e:  # surfaced on the consumer side
            err_box.set(e)
        finally:
            put(end)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is end:
                err = err_box.take()
                if err is not None:
                    raise err
                return
            yield item
    finally:
        # consumer abandoned the generator (break / exception / close):
        # unblock the producer and drop its pinned device batches
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
