"""Reader decorators (reference: python/paddle/v2/reader/decorator.py:26-233
— map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers)."""

from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Callable, List


def map_readers(func: Callable, *readers):
    """Apply func elementwise across several readers' outputs."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int):
    """Pool-based shuffle with a bounded buffer."""

    def shuffled_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment: bool = True):
    """Zip several readers into tuple samples (flattening tuple items)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                if any(i is None for i in items):
                    raise ComposeNotAligned("readers have different lengths")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())

    return composed


def buffered(reader, size: int):
    """Prefetch into a bounded queue on a background thread — the async
    double-buffering the reference's DataProvider pool thread did
    (PyDataProvider2.cpp:334-400)."""

    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _End:
                break
            yield item

    return buffered_reader


def firstn(reader, n: int):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper: Callable, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a reader with worker threads (reference used
    processes/threads; threads suffice since mappers are usually IO/numpy)."""

    class _End:
        pass

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                got = in_q.get()
                if got is _End:
                    out_q.put(_End)
                    return
                i, item = got
                out_q.put((i, mapper(item)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        done = 0
        if order:
            import heapq

            heap: List = []
            next_idx = 0
            while done < process_num:
                got = out_q.get()
                if got is _End:
                    done += 1
                    continue
                heapq.heappush(heap, got)
                while heap and heap[0][0] == next_idx:
                    _, item = heapq.heappop(heap)
                    yield item
                    next_idx += 1
            while heap:
                _, item = heapq.heappop(heap)
                yield item
        else:
            while done < process_num:
                got = out_q.get()
                if got is _End:
                    done += 1
                    continue
                yield got[1]

    return xreader
