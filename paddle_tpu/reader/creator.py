"""Reader creators (reference: python/paddle/v2/reader/creator.py:22-112 —
np_array, text_file, recordio, cloud_reader)."""

from __future__ import annotations

import os


def np_array(x):
    """Reader over rows of a numpy array."""

    def reader():
        import numpy as np

        arr = np.asarray(x)
        for row in arr:
            yield row

    return reader


def text_file(path: str):
    """Reader yielding stripped lines."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size: int = 100):
    """Reader over simple length-prefixed record files (our recordio analog:
    8-byte little-endian length + payload per record; see
    paddle_tpu.master.recordio_write)."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        import struct

        for path in paths:
            with open(path, "rb") as f:
                while True:
                    header = f.read(8)
                    if len(header) < 8:
                        break
                    (n,) = struct.unpack("<Q", header)
                    yield f.read(n)

    return reader


def cloud_reader(paths, etcd_endpoints=None, timeout_sec: int = 5,
                 buf_size: int = 64):
    """Task-dispatched reader backed by the elastic input master
    (reference: cloud_reader via go master client, creator.py:91-112).

    Here the master is the in-repo task-queue service
    (paddle_tpu.master.MasterClient); etcd is replaced by its address."""

    def reader():
        from paddle_tpu.master import MasterClient

        client = MasterClient(etcd_endpoints)
        try:
            client.set_dataset(paths)
            client.begin_pass()  # recycle tasks if a prior pass finished
            while True:
                rec = client.next_record()
                if rec is None:
                    break
                yield rec
        finally:
            client.close()

    return reader
