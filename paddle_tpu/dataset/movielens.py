"""MovieLens-1M rating prediction (reference: v2/dataset/movielens.py)."""
import numpy as np

MAX_USER = 6040
MAX_MOVIE = 3952


def max_user_id():
    return MAX_USER


def max_movie_id():
    return MAX_MOVIE


def max_job_id():
    return 20


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    user_bias = rng.randn(MAX_USER + 1)
    movie_bias = rng.randn(MAX_MOVIE + 1)
    for _ in range(n):
        u = int(rng.randint(1, MAX_USER + 1))
        m = int(rng.randint(1, MAX_MOVIE + 1))
        gender = int(rng.randint(2))
        age = int(rng.randint(7))
        job = int(rng.randint(21))
        category = [int(rng.randint(19))]
        title = [int(rng.randint(1000)) for _ in range(3)]
        score = float(np.clip(3 + user_bias[u] + movie_bias[m] +
                              0.3 * rng.randn(), 1, 5))
        yield u, gender, age, job, m, category, title, score


def train():
    return lambda: _synthetic(4096, 30)


def test():
    return lambda: _synthetic(512, 31)
