"""MovieLens-1M rating prediction dataset.

Reference: python/paddle/v2/dataset/movielens.py (ml-1m.zip with
movies.dat/users.dat/ratings.dat in ``::``-separated format; 90/10
train/test split by seeded shuffle; samples are
(user_id, gender, age_idx, job, movie_id, category_ids, title_word_ids,
score)). Real pipeline with a synthetic fallback when offline.
"""

from __future__ import annotations

import re
import zipfile
from typing import Dict, List

import numpy as np

from paddle_tpu.dataset import common

URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

MAX_USER = 6040
MAX_MOVIE = 3952

AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]

_TITLE_YEAR_RE = re.compile(r"^(.*)\((\d+)\)$")

_META = None  # lazily-parsed (movie_info, user_info, title_dict, cat_dict)


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, cat_dict, title_dict):
        return [self.index, [cat_dict[c] for c in self.categories],
                [title_dict[w.lower()] for w in self.title.split()]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def parse_movies(lines) -> Dict[int, MovieInfo]:
    """movies.dat: 'id::Title (Year)::Cat|Cat' lines."""
    movies = {}
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("latin1")
        line = line.strip()
        if not line:
            continue
        mid, title, cats = line.split("::")
        m = _TITLE_YEAR_RE.match(title)
        title = m.group(1).strip() if m else title
        movies[int(mid)] = MovieInfo(mid, cats.split("|"), title)
    return movies


def parse_users(lines) -> Dict[int, UserInfo]:
    """users.dat: 'id::gender::age::job::zip' lines."""
    users = {}
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("latin1")
        line = line.strip()
        if not line:
            continue
        uid, gender, age, job, _zip = line.split("::")
        users[int(uid)] = UserInfo(uid, gender, age, job)
    return users


def _load_meta():
    global _META
    if _META is not None:
        return _META
    path = common.download(URL, "movielens", MD5)
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/movies.dat") as f:
            movies = parse_movies(f)
        with z.open("ml-1m/users.dat") as f:
            users = parse_users(f)
    title_words = sorted({w.lower() for m in movies.values()
                          for w in m.title.split()})
    categories = sorted({c for m in movies.values() for c in m.categories})
    _META = (movies, users, {w: i for i, w in enumerate(title_words)},
             {c: i for i, c in enumerate(categories)})
    return _META


def _ratings(is_test: bool, test_ratio: float = 0.1, seed: int = 0):
    movies, users, title_dict, cat_dict = _load_meta()
    path = common.download(URL, "movielens", MD5)
    rng = np.random.RandomState(seed)
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/ratings.dat") as f:
            for line in f:
                line = line.decode("latin1").strip()
                if not line:
                    continue
                if (rng.rand() < test_ratio) != is_test:
                    continue
                uid, mid, rating, _ts = line.split("::")
                usr = users[int(uid)]
                mov = movies[int(mid)]
                yield tuple(usr.value()
                            + mov.value(cat_dict, title_dict)
                            + [float(rating)])


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    user_bias = rng.randn(MAX_USER + 1)
    movie_bias = rng.randn(MAX_MOVIE + 1)
    for _ in range(n):
        u = int(rng.randint(1, MAX_USER + 1))
        m = int(rng.randint(1, MAX_MOVIE + 1))
        gender = int(rng.randint(2))
        age = int(rng.randint(len(AGE_TABLE)))
        job = int(rng.randint(21))
        category = [int(rng.randint(19))]
        title = [int(rng.randint(1000)) for _ in range(3)]
        score = float(np.clip(3 + user_bias[u] + movie_bias[m]
                              + 0.3 * rng.randn(), 1, 5))
        yield u, gender, age, job, m, category, title, score


def train():
    try:
        common.download(URL, "movielens", MD5)
    except Exception:
        return lambda: _synthetic(4096, 30)
    return lambda: _ratings(is_test=False)


def test():
    try:
        common.download(URL, "movielens", MD5)
    except Exception:
        return lambda: _synthetic(512, 31)
    return lambda: _ratings(is_test=True)


# ---- metadata accessors (reference API surface) ---------------------------


def movie_info() -> Dict[int, MovieInfo]:
    return _load_meta()[0]


def user_info() -> Dict[int, UserInfo]:
    return _load_meta()[1]


def get_movie_title_dict() -> Dict[str, int]:
    try:
        return _load_meta()[2]
    except Exception:
        return {f"t{i}": i for i in range(1000)}


def movie_categories() -> Dict[str, int]:
    try:
        return _load_meta()[3]
    except Exception:
        return {f"c{i}": i for i in range(19)}


def max_user_id() -> int:
    try:
        return max(u.index for u in _load_meta()[1].values())
    except Exception:
        return MAX_USER


def max_movie_id() -> int:
    try:
        return max(m.index for m in _load_meta()[0].values())
    except Exception:
        return MAX_MOVIE


def max_job_id() -> int:
    try:
        return max(u.job_id for u in _load_meta()[1].values())
    except Exception:
        return 20


def age_table() -> List[int]:
    return list(AGE_TABLE)


def fetch() -> None:
    common.download(URL, "movielens", MD5)
