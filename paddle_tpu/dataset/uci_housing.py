"""UCI housing regression (reference: v2/dataset/uci_housing.py)."""
import numpy as np

from paddle_tpu.dataset import common
from paddle_tpu.dataset import _synth

URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/housing.data"
MD5 = "d4accdce7a25600298819f8e28e8d593"
feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _load():
    path = common.download(URL, "uci_housing", MD5)
    data = np.loadtxt(path).astype(np.float32)
    feats = data[:, :-1]
    feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-6)
    return feats, data[:, -1]


def train():
    try:
        feats, target = _load()
        split = int(len(feats) * 0.8)

        def reader():
            for i in range(split):
                yield feats[i], float(target[i])

        return reader
    except Exception:
        return lambda: _synth.regression(400, 13, 0)


def test():
    try:
        feats, target = _load()
        split = int(len(feats) * 0.8)

        def reader():
            for i in range(split, len(feats)):
                yield feats[i], float(target[i])

        return reader
    except Exception:
        return lambda: _synth.regression(100, 13, 1)
