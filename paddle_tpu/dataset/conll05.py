"""CoNLL-05 semantic role labeling dataset.

Reference: python/paddle/v2/dataset/conll05.py (public test tarball with
words.gz/props.gz, star-bracket props -> BIO tags, context-window sample
construction). Samples are 9-tuples:
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, label_ids)
where every ctx/pred slot is broadcast to sentence length (the SRL demo's
input layout). Real pipeline with a synthetic fallback when offline.
"""

from __future__ import annotations

import gzip
import itertools
import tarfile
from typing import Dict, Iterator, List, Tuple

import numpy as np

from paddle_tpu.dataset import common

DATA_URL = "http://www.cs.upc.edu/~srlconll/conll05st-tests.tar.gz"
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
WORDDICT_URL = ("http://paddlepaddle.bj.bcebos.com/demo/"
                "srl_dict_and_embedding/wordDict.txt")
WORDDICT_MD5 = "ea7fb7d4c75cc6254716f0177a506baa"
VERBDICT_URL = ("http://paddlepaddle.bj.bcebos.com/demo/"
                "srl_dict_and_embedding/verbDict.txt")
VERBDICT_MD5 = "0d2977293bbb6cbefab5b0f97db1e77c"
TRGDICT_URL = ("http://paddlepaddle.bj.bcebos.com/demo/"
               "srl_dict_and_embedding/targetDict.txt")
TRGDICT_MD5 = "d8c7f03ceb5fc2e5a0fa7503a4353751"
EMB_URL = "http://paddlepaddle.bj.bcebos.com/demo/srl_dict_and_embedding/emb"
EMB_MD5 = "bf436eb0faa1f6f9103017f8be57cdb7"

WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"

UNK_IDX = 0

# offline-fallback dims
WORD_DIM = 4000
LABEL_DIM = 67
PRED_DIM = 300


def load_dict(filename: str) -> Dict[str, int]:
    """One token per line -> zero-based index map."""
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def props_to_bio(prop_columns: List[List[str]]) -> Iterator[Tuple[int, List[str]]]:
    """Convert star-bracket proposition columns to BIO tag sequences.

    Column 0 holds the verbs ('-' for non-predicates); columns 1.. hold one
    argument layer per predicate in star notation: '(A0*', '*', '*)' ...
    Yields (predicate_index_in_verb_column, bio_tags).
    """
    verbs = [v for v in prop_columns[0] if v != "-"]
    for i, col in enumerate(prop_columns[1:]):
        cur, inside = "O", False
        tags: List[str] = []
        for tok in col:
            if tok == "*":
                tags.append("I-" + cur if inside else "O")
            elif tok == "*)":
                tags.append("I-" + cur)
                inside = False
            elif "(" in tok and ")" in tok:
                cur = tok[1:tok.find("*")]
                tags.append("B-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                tags.append("B-" + cur)
                inside = True
            else:
                raise ValueError(f"unexpected prop label {tok!r}")
        yield verbs[i], tags


def corpus_reader(words_lines, props_lines):
    """Pair a words stream with a props stream; blank line = sentence end.
    Yields (sentence_words, predicate, bio_tags) per predicate."""
    sentence: List[str] = []
    columns: List[List[str]] = []
    for word, prop in itertools.zip_longest(words_lines, props_lines,
                                            fillvalue=""):
        if isinstance(word, bytes):
            word = word.decode("utf-8", errors="ignore")
        if isinstance(prop, bytes):
            prop = prop.decode("utf-8", errors="ignore")
        word = word.strip()
        fields = prop.strip().split()
        if not fields:  # end of sentence
            if columns:
                ncol = len(columns[0])
                col_major = [[row[i] for row in columns] for i in range(ncol)]
                for verb, tags in props_to_bio(col_major):
                    yield sentence, verb, tags
            sentence, columns = [], []
        else:
            sentence.append(word)
            columns.append(fields)


def make_sample(sentence: List[str], predicate: str, tags: List[str],
                word_dict: Dict[str, int], verb_dict: Dict[str, int],
                label_dict: Dict[str, int]):
    """Context-window sample construction: 5 context words around the
    predicate (bos/eos beyond the edges), a +-2 window mark vector, all
    broadcast to sentence length."""
    sen_len = len(sentence)
    v = tags.index("B-V")
    mark = [0] * sen_len

    def ctx(offset, fallback):
        i = v + offset
        if 0 <= i < sen_len:
            mark[i] = 1
            return sentence[i]
        return fallback

    ctx_0 = ctx(0, None)
    ctx_n1 = ctx(-1, "bos")
    ctx_n2 = ctx(-2, "bos")
    ctx_p1 = ctx(1, "eos")
    ctx_p2 = ctx(2, "eos")

    word_ids = [word_dict.get(w, UNK_IDX) for w in sentence]
    bcast = lambda w: [word_dict.get(w, UNK_IDX)] * sen_len
    pred_ids = [verb_dict.get(predicate, 0)] * sen_len
    label_ids = [label_dict[t] for t in tags]
    return (word_ids, bcast(ctx_n2), bcast(ctx_n1), bcast(ctx_0),
            bcast(ctx_p1), bcast(ctx_p2), pred_ids, mark, label_ids)


def _real_reader(tar_path: str, word_dict, verb_dict, label_dict):
    def reader():
        with tarfile.open(tar_path) as tf:
            wf = tf.extractfile(WORDS_NAME)
            pf = tf.extractfile(PROPS_NAME)
            with gzip.GzipFile(fileobj=wf) as words, \
                    gzip.GzipFile(fileobj=pf) as props:
                for sentence, verb, tags in corpus_reader(words, props):
                    yield make_sample(sentence, verb, tags, word_dict,
                                      verb_dict, label_dict)

    return reader


def get_dict():
    """(word_dict, verb_dict, label_dict) — downloaded, or synthetic dims."""
    try:
        return _real_dicts()
    except Exception:
        return ({f"w{i}": i for i in range(WORD_DIM)},
                {f"v{i}": i for i in range(PRED_DIM)},
                {f"l{i}": i for i in range(LABEL_DIM)})


def get_embedding() -> str:
    return common.download(EMB_URL, "conll05st", EMB_MD5)


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = int(rng.randint(5, 30))
        words = [int(w) for w in rng.randint(0, WORD_DIM, length)]
        v = int(rng.randint(length))
        mark = [0] * length
        for off in (-2, -1, 0, 1, 2):
            if 0 <= v + off < length:
                mark[v + off] = 1
        bcast = lambda: [int(rng.randint(WORD_DIM))] * length
        pred = [int(rng.randint(PRED_DIM))] * length
        labels = [int(l) for l in rng.randint(0, LABEL_DIM, length)]
        yield (words, bcast(), bcast(), bcast(), bcast(), bcast(), pred,
               mark, labels)


def _real_dicts():
    """Real dicts or raise — never pair the real corpus with synthetic
    dicts (make_sample would KeyError on real BIO tags mid-iteration)."""
    return (load_dict(common.download(WORDDICT_URL, "conll05st",
                                      WORDDICT_MD5)),
            load_dict(common.download(VERBDICT_URL, "conll05st",
                                      VERBDICT_MD5)),
            load_dict(common.download(TRGDICT_URL, "conll05st",
                                      TRGDICT_MD5)))


def test():
    """CoNLL-05 ships only its test split publicly (the reference notes the
    train set is licensed); `train()` mirrors it for demo parity."""
    try:
        path = common.download(DATA_URL, "conll05st", DATA_MD5)
        word_dict, verb_dict, label_dict = _real_dicts()
    except Exception:
        return lambda: _synthetic(128, 41)
    return _real_reader(path, word_dict, verb_dict, label_dict)


def train():
    try:
        path = common.download(DATA_URL, "conll05st", DATA_MD5)
        word_dict, verb_dict, label_dict = _real_dicts()
    except Exception:
        return lambda: _synthetic(1024, 40)
    return _real_reader(path, word_dict, verb_dict, label_dict)


def fetch() -> None:
    for url, name, md5 in ((WORDDICT_URL, "conll05st", WORDDICT_MD5),
                           (VERBDICT_URL, "conll05st", VERBDICT_MD5),
                           (TRGDICT_URL, "conll05st", TRGDICT_MD5),
                           (EMB_URL, "conll05st", EMB_MD5),
                           (DATA_URL, "conll05st", DATA_MD5)):
        common.download(url, name, md5)
