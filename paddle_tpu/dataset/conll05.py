"""CoNLL-05 semantic role labeling (reference: v2/dataset/conll05.py).
Samples: (word_seq, predicate, ctx_n2..ctx_p2 seqs, mark_seq, label_seq)."""
import numpy as np

WORD_DIM = 4000
LABEL_DIM = 67  # BIO tags
PRED_DIM = 300


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_DIM)}
    verb_dict = {f"v{i}": i for i in range(PRED_DIM)}
    label_dict = {f"l{i}": i for i in range(LABEL_DIM)}
    return word_dict, verb_dict, label_dict


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = int(rng.randint(5, 30))
        words = [int(w) for w in rng.randint(0, WORD_DIM, length)]
        pred = int(rng.randint(PRED_DIM))
        mark = [int(m) for m in (rng.rand(length) < 0.2)]
        labels = [int(l) for l in rng.randint(0, LABEL_DIM, length)]
        yield (words, [pred] * length, mark, labels)


def train():
    return lambda: _synthetic(1024, 40)


def test():
    return lambda: _synthetic(128, 41)
