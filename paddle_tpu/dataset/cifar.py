"""CIFAR-10/100 (reference: v2/dataset/cifar.py). Synthetic fallback offline."""
import numpy as np

from paddle_tpu.dataset import common

URL10 = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
MD5_10 = "c58f30108f718f92721af3b95e74349a"
URL100 = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"
MD5_100 = "eb9058c3a382ffc7106e4002c42a8d85"


def _synthetic(n, num_classes, seed):
    rng = np.random.RandomState(seed)
    templates = rng.randn(num_classes, 3072).astype(np.float32)
    labels = rng.randint(0, num_classes, n)
    imgs = np.tanh(templates[labels] * 0.4 +
                   rng.randn(n, 3072).astype(np.float32) * 0.4)
    for i in range(n):
        yield imgs[i], int(labels[i])


def _real_reader(url, md5, sub_name, batch_names):
    import pickle
    import tarfile

    path = common.download(url, "cifar", md5)

    def reader():
        with tarfile.open(path) as tar:
            for m in tar.getmembers():
                if any(b in m.name for b in batch_names):
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    labels = d.get(b"labels", d.get(b"fine_labels"))
                    for img, lab in zip(d[b"data"], labels):
                        yield (img.astype(np.float32) / 255.0, int(lab))

    return reader


def train10():
    try:
        return _real_reader(URL10, MD5_10, "cifar-10", ["data_batch"])
    except Exception:
        return lambda: _synthetic(4096, 10, 0)


def test10():
    try:
        return _real_reader(URL10, MD5_10, "cifar-10", ["test_batch"])
    except Exception:
        return lambda: _synthetic(512, 10, 1)


def train100():
    try:
        return _real_reader(URL100, MD5_100, "cifar-100", ["train"])
    except Exception:
        return lambda: _synthetic(4096, 100, 2)


def test100():
    try:
        return _real_reader(URL100, MD5_100, "cifar-100", ["test"])
    except Exception:
        return lambda: _synthetic(512, 100, 3)
