"""WMT-14 fr-en translation pairs (reference: v2/dataset/wmt14.py).
Samples: (src_ids, trg_ids_with_<s>, trg_ids_next)."""
import numpy as np

DICT_SIZE = 30000
START = 0
END = 1
UNK = 2


def _synthetic(n, seed, dict_size):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        slen = int(rng.randint(3, 25))
        src = [int(t) for t in rng.randint(3, dict_size, slen)]
        # toy "translation": reversed + offset
        trg = [(t + 7) % (dict_size - 3) + 3 for t in reversed(src)]
        yield (src, [START] + trg, trg + [END])


def train(dict_size=DICT_SIZE):
    return lambda: _synthetic(2048, 50, dict_size)


def test(dict_size=DICT_SIZE):
    return lambda: _synthetic(256, 51, dict_size)
