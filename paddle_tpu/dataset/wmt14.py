"""WMT-14 fr-en translation dataset.

Reference: python/paddle/v2/dataset/wmt14.py (shrunk wmt14.tgz with
src.dict/trg.dict + tab-separated parallel files; samples are
(src_ids with <s>/<e>, <s>+trg_ids, trg_ids+<e>), len>80 dropped).
Real pipeline with a synthetic fallback when offline.
"""

from __future__ import annotations

import tarfile
from typing import Dict, Tuple

import numpy as np

from paddle_tpu.dataset import common

URL_TRAIN = "http://paddlepaddle.cdn.bcebos.com/demo/wmt_shrinked_data/wmt14.tgz"
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"
URL_DEV_TEST = ("http://www-lium.univ-lemans.fr/~schwenk/"
                "cslm_joint_paper/data/dev+test.tgz")
MD5_DEV_TEST = "7d7897317ddd8ba0ae5c5fa7248d3ff5"

DICT_SIZE = 30000
START = "<s>"
END = "<e>"
UNK = "<unk>"
START_IDX = 0
END_IDX = 1
UNK_IDX = 2


def read_dicts_from_tar(tar_path: str, dict_size: int
                        ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """First ``dict_size`` lines of the bundled src.dict / trg.dict."""
    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.decode("utf-8", errors="ignore").strip()] = i
        return out

    with tarfile.open(tar_path) as f:
        src_name = [m.name for m in f if m.name.endswith("src.dict")]
        trg_name = [m.name for m in f if m.name.endswith("trg.dict")]
        assert len(src_name) == 1 and len(trg_name) == 1
        return (to_dict(f.extractfile(src_name[0]), dict_size),
                to_dict(f.extractfile(trg_name[0]), dict_size))


def parse_lines(lines, src_dict: Dict[str, int], trg_dict: Dict[str, int],
                max_len: int = 80):
    """'src\\ttrg' lines -> (src_ids, trg_ids, trg_ids_next) samples."""
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="ignore")
        parts = line.strip().split("\t")
        if len(parts) != 2:
            continue
        src_words = parts[0].split()
        src_ids = [src_dict.get(w, UNK_IDX)
                   for w in [START] + src_words + [END]]
        trg_ids = [trg_dict.get(w, UNK_IDX) for w in parts[1].split()]
        if len(src_ids) > max_len or len(trg_ids) > max_len:
            continue
        yield (src_ids, [trg_dict[START]] + trg_ids,
               trg_ids + [trg_dict[END]])


def _real_reader(tar_path: str, file_suffix: str, dict_size: int):
    # dicts parsed once at creator time, not per epoch inside reader()
    src_dict, trg_dict = read_dicts_from_tar(tar_path, dict_size)

    def reader():
        with tarfile.open(tar_path) as f:
            names = [m.name for m in f if m.name.endswith(file_suffix)]
            for name in names:
                yield from parse_lines(f.extractfile(name), src_dict,
                                       trg_dict)

    return reader


def _synthetic(n, seed, dict_size):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        slen = int(rng.randint(3, 25))
        src = [int(t) for t in rng.randint(3, dict_size, slen)]
        # toy "translation": reversed + offset
        trg = [(t + 7) % (dict_size - 3) + 3 for t in reversed(src)]
        yield (src, [START_IDX] + trg, trg + [END_IDX])


def get_dict(dict_size: int = DICT_SIZE):
    path = common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
    return read_dicts_from_tar(path, dict_size)


def train(dict_size: int = DICT_SIZE):
    try:
        path = common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
    except Exception:
        return lambda: _synthetic(2048, 50, dict_size)
    return _real_reader(path, "train/train", dict_size)


def test(dict_size: int = DICT_SIZE):
    try:
        path = common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
    except Exception:
        return lambda: _synthetic(256, 51, dict_size)
    return _real_reader(path, "test/test", dict_size)


def gen(dict_size: int = DICT_SIZE):
    try:
        path = common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
    except Exception:
        return lambda: _synthetic(64, 52, dict_size)
    return _real_reader(path, "gen/gen", dict_size)


def fetch() -> None:
    common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
