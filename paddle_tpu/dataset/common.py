"""Dataset cache/download helpers (reference: v2/dataset/common.py — DATA_HOME
cache, md5-verified download, cluster split helpers)."""

from __future__ import annotations

import hashlib
import os
from typing import Callable, List

DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                              "~/.cache/paddle_tpu/dataset"))


def data_home() -> str:
    os.makedirs(DATA_HOME, exist_ok=True)
    return DATA_HOME


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str) -> str:
    """Download with cache + md5 check; raises with a clear message when the
    environment has no egress (callers fall back to synthetic data)."""
    dirname = os.path.join(data_home(), module_name)
    os.makedirs(dirname, exist_ok=True)
    filename = os.path.join(dirname, url.split("/")[-1])
    if os.path.exists(filename) and (not md5sum or md5file(filename) == md5sum):
        return filename
    import urllib.request

    # fetch to a temp name + atomic rename: an interrupted transfer must
    # never leave a truncated file that a later call (especially one with
    # no md5, e.g. sentiment) would trust as a valid cache hit
    part = filename + ".part"
    try:
        urllib.request.urlretrieve(url, part)
        if md5sum and md5file(part) != md5sum:
            raise IOError(f"md5 mismatch for {url}")
        os.replace(part, filename)
    finally:
        if os.path.exists(part):
            os.remove(part)
    return filename


def split(reader: Callable, line_count: int, suffix: str = "%05d.pickle",
          dumper=None) -> List[str]:
    """Split reader output into chunk files (cluster data prep helper)."""
    import pickle

    dumper = dumper or pickle.dump
    files = []
    buf = []
    idx = 0
    for item in reader():
        buf.append(item)
        if len(buf) == line_count:
            path = os.path.join(data_home(), suffix % idx)
            with open(path, "wb") as f:
                dumper(buf, f)
            files.append(path)
            buf, idx = [], idx + 1
    if buf:
        path = os.path.join(data_home(), suffix % idx)
        with open(path, "wb") as f:
            dumper(buf, f)
        files.append(path)
    return files


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=None):
    """Read this trainer's shard of chunk files (reference:
    common.py cluster_files_reader)."""
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        paths = sorted(glob.glob(files_pattern))
        for i, path in enumerate(paths):
            if i % trainer_count == trainer_id:
                with open(path, "rb") as f:
                    for item in loader(f):
                        yield item

    return reader
