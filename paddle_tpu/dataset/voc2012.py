"""VOC2012 segmentation (reference: v2/dataset/voc2012.py). Synthetic fallback."""
import numpy as np


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        img = rng.rand(3, 32, 32).astype(np.float32)
        seg = rng.randint(0, 21, (32, 32)).astype(np.int32)
        yield img, seg


def train():
    return lambda: _synthetic(256, 70)


def test():
    return lambda: _synthetic(64, 71)


def val():
    return lambda: _synthetic(64, 72)
