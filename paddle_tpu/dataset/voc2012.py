"""PASCAL VOC2012 segmentation dataset.

Reference: python/paddle/v2/dataset/voc2012.py (VOCtrainval tarball;
Segmentation imageset lists select JPEGImages/{}.jpg + palette-indexed
SegmentationClass/{}.png pairs; yields (image HWC uint8, label HW uint8)).
Real pipeline with a synthetic fallback when offline.
"""

from __future__ import annotations

import tarfile

import numpy as np

from paddle_tpu import image as pimage
from paddle_tpu.dataset import common

VOC_URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
           "VOCtrainval_11-May-2012.tar")
VOC_MD5 = "6cd6e144f989b92b3379bac3b3de84fd"

SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _decode_png_indexed(data: bytes) -> np.ndarray:
    """Palette PNG -> HW index array (class ids, 255 = void)."""
    import io

    from PIL import Image

    return np.asarray(Image.open(io.BytesIO(data)))


def reader_creator(tar_path: str, sub_name: str):
    def reader():
        with tarfile.open(tar_path) as tf:
            members = {m.name: m for m in tf.getmembers()}
            sets = tf.extractfile(members[SET_FILE.format(sub_name)])
            for line in sets:
                name = line.decode("utf-8").strip()
                if not name:
                    continue
                img_bytes = tf.extractfile(
                    members[DATA_FILE.format(name)]).read()
                lab_bytes = tf.extractfile(
                    members[LABEL_FILE.format(name)]).read()
                img = pimage.load_image_bytes(img_bytes)  # HWC uint8
                label = _decode_png_indexed(lab_bytes)    # HW class ids
                yield img, label

    return reader


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        img = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        seg = rng.randint(0, 21, (32, 32)).astype(np.uint8)
        yield img, seg


def _make(sub_name, synth_n, synth_seed):
    try:
        path = common.download(VOC_URL, "voc2012", VOC_MD5)
    except Exception:
        return lambda: _synthetic(synth_n, synth_seed)
    return reader_creator(path, sub_name)


def train():
    return _make("trainval", 256, 70)


def test():
    return _make("train", 64, 71)


def val():
    return _make("val", 64, 72)


def fetch() -> None:
    common.download(VOC_URL, "voc2012", VOC_MD5)
