"""Datasets (reference: python/paddle/v2/dataset — 13 auto-downloading
datasets). This build has no network egress in CI; every dataset module
supports (a) download-if-possible with md5 cache like the reference
(common.py), and (b) a deterministic ``synthetic`` fallback so tests and
demos run hermetically.
"""

from paddle_tpu.dataset import common
from paddle_tpu.dataset import mnist
from paddle_tpu.dataset import cifar
from paddle_tpu.dataset import uci_housing
from paddle_tpu.dataset import imdb
from paddle_tpu.dataset import imikolov
from paddle_tpu.dataset import movielens
from paddle_tpu.dataset import conll05
from paddle_tpu.dataset import wmt14
from paddle_tpu.dataset import flowers
from paddle_tpu.dataset import voc2012
from paddle_tpu.dataset import sentiment
from paddle_tpu.dataset import mq2007

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "conll05", "wmt14", "flowers", "voc2012",
           "sentiment", "mq2007"]
