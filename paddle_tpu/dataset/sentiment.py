"""Movie-review sentiment (reference: v2/dataset/sentiment.py)."""
from paddle_tpu.dataset import _synth

WORD_DIM = 1500


def get_word_dict():
    return {f"w{i}": i for i in range(WORD_DIM)}


def train(word_dict=None):
    dim = len(word_dict) if word_dict else WORD_DIM
    return lambda: _synth.seq_classification(1024, dim, 2, seed=80)


def test(word_dict=None):
    dim = len(word_dict) if word_dict else WORD_DIM
    return lambda: _synth.seq_classification(128, dim, 2, seed=81)
