"""NLTK movie_reviews sentiment dataset.

Reference: python/paddle/v2/dataset/sentiment.py (nltk movie_reviews corpus,
freq-sorted word dict, neg/pos interleaved; first 1600 train / last 400
test; label 0=neg 1=pos). The corpus is a plain zip of
movie_reviews/{neg,pos}/*.txt — parsed directly (no nltk dependency) with
a synthetic fallback when offline.
"""

from __future__ import annotations

import collections
import zipfile
from typing import Dict, Iterator, List, Tuple

from paddle_tpu.dataset import _synth, common

URL = ("https://raw.githubusercontent.com/nltk/nltk_data/gh-pages/"
       "packages/corpora/movie_reviews.zip")
MD5 = ""  # nltk publishes no stable md5; cache by name only

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

WORD_DIM = 1500  # offline-fallback dict size


def _tokenize(text: str) -> List[str]:
    # the corpus ships pre-tokenized (tokens separated by whitespace /
    # newlines); lowercase to match the reference's word dict
    return text.lower().split()


def iter_documents(zip_path: str) -> Iterator[Tuple[List[str], int]]:
    """Yield (tokens, label) interleaved neg/pos (label 0=neg, 1=pos),
    ordered by filename within each class (cross-reading keeps the
    train/test split class-balanced)."""
    with zipfile.ZipFile(zip_path) as z:
        names = sorted(z.namelist())
        neg = [n for n in names if "/neg/" in n and n.endswith(".txt")]
        pos = [n for n in names if "/pos/" in n and n.endswith(".txt")]
        for n_name, p_name in zip(neg, pos):
            yield _tokenize(z.read(n_name).decode("utf-8", "ignore")), 0
            yield _tokenize(z.read(p_name).decode("utf-8", "ignore")), 1


def build_word_dict(zip_path: str) -> Dict[str, int]:
    freq: Dict[str, int] = collections.defaultdict(int)
    for tokens, _ in iter_documents(zip_path):
        for w in tokens:
            freq[w] += 1
    kept = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    return {w: i for i, (w, _) in enumerate(kept)}


def get_word_dict() -> Dict[str, int]:
    try:
        return build_word_dict(common.download(URL, "sentiment", MD5))
    except Exception:
        return {f"w{i}": i for i in range(WORD_DIM)}


def _real_reader(lo: int, hi: int, word_dict: Dict[str, int]):
    def reader():
        zip_path = common.download(URL, "sentiment", MD5)
        for i, (tokens, label) in enumerate(iter_documents(zip_path)):
            if lo <= i < hi:
                yield [word_dict[w] for w in tokens if w in word_dict], label

    return reader


def train(word_dict: Dict[str, int] = None):
    try:
        common.download(URL, "sentiment", MD5)
    except Exception:
        dim = len(word_dict) if word_dict else WORD_DIM
        return lambda: _synth.seq_classification(1024, dim, 2, seed=80)
    return _real_reader(0, NUM_TRAINING_INSTANCES, word_dict or get_word_dict())


def test(word_dict: Dict[str, int] = None):
    try:
        common.download(URL, "sentiment", MD5)
    except Exception:
        dim = len(word_dict) if word_dict else WORD_DIM
        return lambda: _synth.seq_classification(128, dim, 2, seed=81)
    return _real_reader(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES,
                        word_dict or get_word_dict())


def fetch() -> None:
    common.download(URL, "sentiment", MD5)
