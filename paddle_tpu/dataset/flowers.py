"""Oxford-102 flowers (reference: v2/dataset/flowers.py). Synthetic fallback."""
import numpy as np


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    templates = rng.randn(102, 3 * 32 * 32).astype(np.float32)
    for _ in range(n):
        lab = int(rng.randint(102))
        img = np.tanh(templates[lab] * 0.4 + rng.randn(3 * 32 * 32) * 0.4)
        yield img.astype(np.float32), lab


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return lambda: _synthetic(1024, 60)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return lambda: _synthetic(128, 61)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return lambda: _synthetic(128, 62)
