"""Oxford-102 flowers classification dataset.

Reference: python/paddle/v2/dataset/flowers.py (102flowers.tgz images +
imagelabels.mat/setid.mat split files; train/test splits deliberately
swapped — 'tstid' is the larger set and used for training; samples are
(transformed image, 0-based label)). Images are preprocessed with
paddle_tpu.image.simple_transform; the TPU-native default yields HWC
float32 (flatten for the v2 dense_vector layer is the mapper's job).
Real pipeline with a synthetic fallback when offline.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from paddle_tpu import image as pimage
from paddle_tpu.dataset import common
from paddle_tpu.reader.decorator import map_readers, xmap_readers

DATA_URL = "http://www.robots.ox.ac.uk/~vgg/data/flowers/102/102flowers.tgz"
LABEL_URL = "http://www.robots.ox.ac.uk/~vgg/data/flowers/102/imagelabels.mat"
SETID_URL = "http://www.robots.ox.ac.uk/~vgg/data/flowers/102/setid.mat"
DATA_MD5 = "52808999861908f626f3c1f4e79d11fa"
LABEL_MD5 = "e0620be6f572b9609742df49c70aed4d"
SETID_MD5 = "a5357ecc9cb78c4bef273ce3793fc85c"

# official readme flags; tstid (the bigger split) is used for TRAINING
TRAIN_FLAG = "tstid"
TEST_FLAG = "trnid"
VALID_FLAG = "valid"


# ImageNet channel means in BGR order (cv2's decode order)
_MEAN_BGR = [103.94, 116.78, 123.68]


def default_mapper(is_train: bool, sample):
    img_bytes, label = sample
    img = pimage.load_image_bytes(img_bytes)
    mean = (_MEAN_BGR if pimage.channel_order() == "BGR"
            else _MEAN_BGR[::-1])
    img = pimage.simple_transform(img, 256, 224, is_train, mean=mean)
    return img.flatten().astype(np.float32), label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def split_img2label(label_mat_path: str, setid_mat_path: str,
                    flag: str) -> Dict[str, int]:
    """jpg member name -> 1-based label for the given split flag."""
    import scipy.io as scio

    labels = scio.loadmat(label_mat_path)["labels"][0]
    indexes = scio.loadmat(setid_mat_path)[flag][0]
    return {f"jpg/image_{i:05d}.jpg": int(labels[i - 1]) for i in indexes}


def _reader_creator(data_file, label_file, setid_file, flag, mapper,
                    buffered_size=1024, use_xmap=True):
    import pickle

    img2label = split_img2label(label_file, setid_file, flag)
    file_list = pimage.batch_images_from_tar(data_file, flag, img2label)

    def reader():
        with open(file_list) as flist:
            for batch_path in flist:
                with open(batch_path.strip(), "rb") as f:
                    batch = pickle.load(f)
                for sample, label in zip(batch["data"], batch["label"]):
                    yield sample, int(label) - 1

    if use_xmap:
        import multiprocessing

        return xmap_readers(mapper, reader, multiprocessing.cpu_count(),
                            buffered_size)
    return map_readers(mapper, reader)


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    templates = rng.randn(102, 3 * 32 * 32).astype(np.float32)
    for _ in range(n):
        lab = int(rng.randint(102))
        img = np.tanh(templates[lab] * 0.4 + rng.randn(3 * 32 * 32) * 0.4)
        yield img.astype(np.float32), lab


def _make(flag, mapper, buffered_size, use_xmap, synth_n, synth_seed):
    try:
        data = common.download(DATA_URL, "flowers", DATA_MD5)
        label = common.download(LABEL_URL, "flowers", LABEL_MD5)
        setid = common.download(SETID_URL, "flowers", SETID_MD5)
    except Exception:
        return lambda: _synthetic(synth_n, synth_seed)
    return _reader_creator(data, label, setid, flag, mapper, buffered_size,
                           use_xmap)


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True):
    return _make(TRAIN_FLAG, mapper, buffered_size, use_xmap, 1024, 60)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return _make(TEST_FLAG, mapper, buffered_size, use_xmap, 128, 61)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return _make(VALID_FLAG, mapper, buffered_size, use_xmap, 128, 62)


def fetch() -> None:
    common.download(DATA_URL, "flowers", DATA_MD5)
    common.download(LABEL_URL, "flowers", LABEL_MD5)
    common.download(SETID_URL, "flowers", SETID_MD5)
