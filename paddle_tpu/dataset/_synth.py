"""Shared synthetic-data helpers for offline dataset fallbacks."""
import numpy as np


def seq_classification(n, vocab, num_classes, seed, max_len=40):
    """Token sequences whose class is recoverable from token statistics."""
    rng = np.random.RandomState(seed)
    class_dists = rng.dirichlet(np.ones(vocab) * 0.05, size=num_classes)
    for _ in range(n):
        label = int(rng.randint(num_classes))
        length = int(rng.randint(5, max_len))
        toks = rng.choice(vocab, size=length, p=class_dists[label])
        yield list(map(int, toks)), label


def regression(n, dim, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n)).astype(np.float32)
    for i in range(n):
        yield x[i], float(y[i])
