"""IMDB sentiment dataset — aclImdb tarball -> tokenized ID sequences.

Reference: python/paddle/v2/dataset/imdb.py:1-120 (streaming tar tokenizer,
frequency-sorted dict with <unk> last, pos=0/neg=1 labels). Real pipeline
with a deterministic synthetic fallback when the environment has no egress.
"""

from __future__ import annotations

import collections
import re
import string
import tarfile
from typing import Dict, Iterator, List, Tuple

from paddle_tpu.dataset import _synth, common

URL = "http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

WORD_DIM = 5147  # offline-fallback dict size ballpark

_PUNCT_TABLE = str.maketrans("", "", string.punctuation)


def tokenize(pattern, tar_path: str = None) -> Iterator[List[str]]:
    """Stream docs whose member name matches ``pattern`` from the tarball;
    lowercase, strip punctuation, whitespace-tokenize. Sequential tar access
    (``next()``) — random access on an 80k-member tgz thrashes the disk."""
    tar_path = tar_path or common.download(URL, "imdb", MD5)
    with tarfile.open(tar_path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(_PUNCT_TABLE).lower().split()
            tf = tarf.next()


def build_dict(pattern, cutoff: int, tar_path: str = None) -> Dict[str, int]:
    """Frequency-sorted word dict (ties broken alphabetically), words with
    freq <= cutoff dropped, '<unk>' appended last."""
    word_freq: Dict[str, int] = collections.defaultdict(int)
    for doc in tokenize(pattern, tar_path):
        for word in doc:
            word_freq[word] += 1
    kept = [(w, f) for w, f in word_freq.items() if f > cutoff]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _real_reader(pos_re: str, neg_re: str, word_idx: Dict[str, int],
                 tar_path: str = None):
    """Alternate pos (label 0) / neg (label 1) docs — the reference
    interleaves the two streams so minibatches stay class-balanced."""
    unk = word_idx["<unk>"]

    def reader() -> Iterator[Tuple[List[int], int]]:
        streams = [tokenize(re.compile(pos_re), tar_path),
                   tokenize(re.compile(neg_re), tar_path)]
        done = [False, False]
        i = 0
        while not all(done):
            if not done[i % 2]:
                doc = next(streams[i % 2], None)
                if doc is None:
                    done[i % 2] = True
                else:
                    yield [word_idx.get(w, unk) for w in doc], i % 2
            i += 1

    return reader


def word_dict(cutoff: int = 150) -> Dict[str, int]:
    try:
        return build_dict(
            re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
            cutoff)
    except Exception:
        d = {f"w{i}": i for i in range(WORD_DIM - 1)}
        d["<unk>"] = WORD_DIM - 1
        return d


def train(word_idx: Dict[str, int] = None):
    dim = len(word_idx) if word_idx else WORD_DIM
    try:
        common.download(URL, "imdb", MD5)
    except Exception:
        return lambda: _synth.seq_classification(2048, dim, 2, seed=10,
                                                 max_len=100)
    return _real_reader(r"aclImdb/train/pos/.*\.txt$",
                        r"aclImdb/train/neg/.*\.txt$",
                        word_idx or word_dict())


def test(word_idx: Dict[str, int] = None):
    dim = len(word_idx) if word_idx else WORD_DIM
    try:
        common.download(URL, "imdb", MD5)
    except Exception:
        return lambda: _synth.seq_classification(256, dim, 2, seed=11,
                                                 max_len=100)
    return _real_reader(r"aclImdb/test/pos/.*\.txt$",
                        r"aclImdb/test/neg/.*\.txt$",
                        word_idx or word_dict())


def fetch() -> None:
    common.download(URL, "imdb", MD5)
