"""IMDB sentiment (reference: v2/dataset/imdb.py). Synthetic fallback."""
from paddle_tpu.dataset import _synth

WORD_DIM = 5147  # reference dict size ballpark


def word_dict():
    return {f"w{i}": i for i in range(WORD_DIM)}


def train(word_idx=None):
    dim = len(word_idx) if word_idx else WORD_DIM
    return lambda: _synth.seq_classification(2048, dim, 2, seed=10, max_len=100)


def test(word_idx=None):
    dim = len(word_idx) if word_idx else WORD_DIM
    return lambda: _synth.seq_classification(256, dim, 2, seed=11, max_len=100)
