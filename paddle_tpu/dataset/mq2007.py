"""MQ2007 learning-to-rank (reference: v2/dataset/mq2007.py).
Yields (query_group) lists for listwise, or pairs for pairwise format."""
import numpy as np

FEATURE_DIM = 46


def _synthetic_queries(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(FEATURE_DIM)
    for _ in range(n_queries):
        n_docs = int(rng.randint(5, 20))
        feats = rng.randn(n_docs, FEATURE_DIM).astype(np.float32)
        scores = feats @ w + 0.5 * rng.randn(n_docs)
        rels = np.digitize(scores, np.percentile(scores, [33, 66]))
        yield [(float(rels[i]), feats[i]) for i in range(n_docs)]


def train(format="listwise"):
    def reader():
        for group in _synthetic_queries(512, 90):
            if format == "listwise":
                yield group
            else:
                for i in range(len(group)):
                    for j in range(len(group)):
                        if group[i][0] > group[j][0]:
                            yield group[i][1], group[j][1], 1.0

    return reader


def test(format="listwise"):
    def reader():
        for group in _synthetic_queries(64, 91):
            if format == "listwise":
                yield group
            else:
                for i in range(len(group)):
                    for j in range(len(group)):
                        if group[i][0] > group[j][0]:
                            yield group[i][1], group[j][1], 1.0

    return reader
